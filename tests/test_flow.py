"""Workflow DAG engine + content-addressed result cache (ISSUE 19).

Covers: DAG validation/expansion, critical-path-first scheduling (pinned
bit-compatible with plain FIFO for linear graphs), the ResultCache unit
surface, end-to-end fan-out/fan-in drains with a single-rooted trace tree,
cache-hit replays (byte-identical, ≥90% second-submission hit rate, dedupe
ratio in /v1/usage), journal replay mid-DAG, the replay-ordering
DependencyFailed regression, the LoopbackSession /v1/workflows route, the
/v1/infer front-door cache, and loadgen's zipfian payload mix.
"""

from __future__ import annotations

import json
import os
import random

import pytest

from agent_tpu.chaos import LoopbackSession
from agent_tpu.config import FlowConfig, SchedConfig
from agent_tpu.controller.core import Controller
from agent_tpu.flow.dag import (
    DagError,
    critical_path_lengths,
    expand_workflow,
    parse_workflow,
    toposort_stages,
)
from agent_tpu.flow.result_cache import ResultCache, result_key
from agent_tpu.loadgen import TrafficClass, zipf_rank

KNOWN = ["echo", "map_tokenize", "risk_accumulate", "map_summarize"]

FANOUT_DOC = {
    "stages": [
        {"name": "tok", "op": "echo", "payload": {"v": 1}},
        {"name": "cls", "op": "echo", "payload": {"v": 2},
         "after": ["tok"], "fan_out": 3},
        {"name": "acc", "op": "risk_accumulate", "payload": {},
         "after": ["cls"]},
        {"name": "rep", "op": "echo", "payload": {"final": True},
         "after": ["acc"]},
    ]
}


def drain(c, ops=("echo", "risk_accumulate"), rounds=50, status="succeeded"):
    """Minimal inline agent: echo returns its payload (minus the collect
    marker), risk_accumulate counts its partials."""
    leases = 0
    for _ in range(rounds):
        lease = c.lease("a1", {"ops": list(ops)}, max_tasks=8)
        if lease is None:
            break
        leases += 1
        for t in lease["tasks"]:
            if status != "succeeded":
                c.report(lease["lease_id"], t["id"], t["job_epoch"], status,
                         error={"type": "ValueError", "message": "boom",
                                "trace": ""})
                continue
            if t["op"] == "risk_accumulate":
                res = {"n": len(t["payload"].get("partials", []))}
            else:
                res = {k: v for k, v in t["payload"].items()
                       if k != "__collect_partials__"}
            c.report(lease["lease_id"], t["id"], t["job_epoch"],
                     "succeeded", result=res)
    return leases


# ---------------------------------------------------------------------------
# DAG validation + expansion (pure half)
# ---------------------------------------------------------------------------


class TestDagValidation:
    def test_valid_fanout_fanin_parses(self):
        spec = parse_workflow(FANOUT_DOC, KNOWN)
        assert [s.name for s in spec.stages] == ["tok", "cls", "acc", "rep"]
        assert toposort_stages(spec) == ["tok", "cls", "acc", "rep"]

    def test_cycle_rejected(self):
        doc = {"stages": [
            {"name": "a", "op": "echo", "after": ["b"]},
            {"name": "b", "op": "echo", "after": ["a"]},
        ]}
        with pytest.raises(DagError, match="cycle"):
            parse_workflow(doc, KNOWN)

    def test_unknown_op_rejected(self):
        doc = {"stages": [{"name": "a", "op": "nope"}]}
        with pytest.raises(DagError, match="unknown op"):
            parse_workflow(doc, KNOWN)

    def test_duplicate_stage_name_rejected(self):
        doc = {"stages": [
            {"name": "a", "op": "echo"}, {"name": "a", "op": "echo"},
        ]}
        with pytest.raises(DagError, match="duplicate"):
            parse_workflow(doc, KNOWN)

    def test_unknown_after_and_self_dep_rejected(self):
        with pytest.raises(DagError, match="unknown"):
            parse_workflow(
                {"stages": [{"name": "a", "op": "echo", "after": ["z"]}]},
                KNOWN,
            )
        with pytest.raises(DagError, match="itself"):
            parse_workflow(
                {"stages": [{"name": "a", "op": "echo", "after": ["a"]}]},
                KNOWN,
            )

    def test_stage_and_width_limits(self):
        many = {"stages": [
            {"name": f"s{i}", "op": "echo"} for i in range(5)
        ]}
        with pytest.raises(DagError, match="FLOW_MAX_STAGES"):
            parse_workflow(many, KNOWN, max_stages=4)
        wide = {"stages": [{"name": "a", "op": "echo", "fan_out": 9}]}
        with pytest.raises(DagError, match="FLOW_MAX_WIDTH"):
            parse_workflow(wide, KNOWN, max_width=8)
        with pytest.raises(DagError):
            parse_workflow(
                {"stages": [{"name": "a", "op": "echo", "fan_out": True}]},
                KNOWN,
            )

    def test_critical_path_linear_is_strictly_decreasing(self):
        doc = {"stages": [
            {"name": "s0", "op": "echo"},
            {"name": "s1", "op": "echo", "after": ["s0"]},
            {"name": "s2", "op": "echo", "after": ["s1"]},
        ]}
        cp = critical_path_lengths(parse_workflow(doc, KNOWN))
        assert cp == {"s0": 3, "s1": 2, "s2": 1}

    def test_expand_fan_in_lists_every_upstream_instance(self):
        spec = parse_workflow(FANOUT_DOC, KNOWN)
        planned = {p.job_id: p for p in expand_workflow(spec, "wf-x")}
        acc = planned["wf-x-acc"]
        assert acc.after == ("wf-x-cls-0", "wf-x-cls-1", "wf-x-cls-2")
        assert acc.payload["__collect_partials__"] is True
        cls0 = planned["wf-x-cls-0"]
        assert cls0.payload["fan_index"] == 0
        assert cls0.payload["fan_out"] == 3
        assert cls0.after == ("wf-x-tok",)
        assert cls0.critical_path == 3 and acc.critical_path == 2
        assert planned["wf-x-rep"].critical_path == 1
        assert planned["wf-x-tok"].critical_path == 4


# ---------------------------------------------------------------------------
# critical-path-first scheduling
# ---------------------------------------------------------------------------


class TestCriticalPathFirst:
    def test_linear_graphs_drain_exactly_like_plain_fifo(self):
        """Property test (seeded, the ISSUE 19 pin): for a LINEAR graph the
        critical-path sort is a no-op. Along a chain cp strictly decreases
        in submit order, and a chain stage is only ever eligible after every
        earlier stage finished, so at each decision point the eligible job
        with the highest cp is also the earliest arrival — drain order is
        bit-identical to plain FIFO (== submit order). Plain-only workloads
        (all cp == 0) pin the stable-sort identity half of the claim."""
        rng = random.Random(7)
        for trial in range(20):
            c = Controller(flow=FlowConfig(cache_enabled=False))
            expected = []  # job ids in submit order == plain-FIFO order
            if rng.random() < 0.7:
                depth = rng.randint(1, 6)
                doc = {"stages": [
                    {"name": f"s{i}", "op": "echo",
                     "payload": {"i": i}, "collect": False,
                     **({"after": [f"s{i-1}"]} if i else {})}
                    for i in range(depth)
                ]}
                expected.extend(c.submit_workflow(doc)["job_ids"])
            for j in range(rng.randint(0, 6)):
                expected.append(c.submit("echo", {"j": j}))
            drained = []
            for _ in range(len(expected)):
                lease = c.lease("a1", {"ops": ["echo"]}, max_tasks=1)
                if lease is None:
                    break
                for t in lease["tasks"]:
                    drained.append(t["id"])
                    c.report(lease["lease_id"], t["id"], t["job_epoch"],
                             "succeeded", result={"ok": True})
            assert drained == expected, f"trial {trial}"

    def test_deep_dag_preempts_shallow_plain_jobs(self):
        """With mixed work queued, the stage with the most downstream work
        leases first even though it arrived last — under fifo AND fair."""
        for policy in ("fifo", "fair"):
            c = Controller(
                sched=SchedConfig(policy=policy),
                flow=FlowConfig(cache_enabled=False),
            )
            plain = [c.submit("echo", {"p": i}) for i in range(3)]
            out = c.submit_workflow({"stages": [
                {"name": "deep0", "op": "echo", "collect": False},
                {"name": "deep1", "op": "echo", "after": ["deep0"],
                 "collect": False},
                {"name": "deep2", "op": "echo", "after": ["deep1"],
                 "collect": False},
            ]})
            lease = c.lease("a1", {"ops": ["echo"]}, max_tasks=1)
            first = lease["tasks"][0]["id"]
            assert first == out["job_ids"][0], policy
            assert first not in plain


# ---------------------------------------------------------------------------
# ResultCache unit surface
# ---------------------------------------------------------------------------


class TestResultCache:
    def test_key_is_order_insensitive_and_version_sensitive(self):
        k1 = result_key("echo", {"a": 1, "b": 2}, "v1")
        k2 = result_key("echo", {"b": 2, "a": 1}, "v1")
        assert k1 == k2
        assert result_key("echo", {"a": 1, "b": 2}, "v2") != k1
        assert result_key("other", {"a": 1, "b": 2}, "v1") != k1

    def test_lru_eviction(self):
        rc = ResultCache(capacity=2)
        rc.put("echo", {"k": 1}, {"r": 1})
        rc.put("echo", {"k": 2}, {"r": 2})
        assert rc.get("echo", {"k": 1}) == {"r": 1}   # 1 now MRU
        rc.put("echo", {"k": 3}, {"r": 3})            # evicts 2
        assert rc.get("echo", {"k": 2}) is None
        assert rc.get("echo", {"k": 1}) == {"r": 1}
        assert rc.stats()["evictions"] == 1

    def test_model_version_bump_invalidates(self):
        rc = ResultCache(capacity=8, model_version="v1")
        rc.put("echo", {"k": 1}, {"r": 1})
        rc.set_model_version("v2")
        assert rc.get("echo", {"k": 1}) is None
        assert rc.stats()["invalidations"] == 1
        rc.put("echo", {"k": 1}, {"r": "new"})
        assert rc.get("echo", {"k": 1}) == {"r": "new"}

    def test_stored_results_are_isolated_copies(self):
        rc = ResultCache(capacity=8)
        src = {"rows": [1, 2]}
        rc.put("echo", {"k": 1}, src)
        src["rows"].append(3)
        out = rc.get("echo", {"k": 1})
        assert out == {"rows": [1, 2]}
        out["rows"].append(9)
        assert rc.get("echo", {"k": 1}) == {"rows": [1, 2]}

    def test_capacity_zero_disables(self):
        rc = ResultCache(capacity=0)
        assert not rc.enabled
        rc.put("echo", {"k": 1}, {"r": 1})
        assert rc.get("echo", {"k": 1}) is None


# ---------------------------------------------------------------------------
# end-to-end: submit -> drain -> status -> cache-hit resubmission
# ---------------------------------------------------------------------------


class TestWorkflowEndToEnd:
    def test_fanout_fanin_drains_with_single_trace_tree(self):
        c = Controller()
        out = c.submit_workflow(FANOUT_DOC, tenant="acme", priority=6)
        wid = out["workflow_id"]
        assert out["stages"] == ["tok", "cls", "acc", "rep"]
        assert len(out["job_ids"]) == 6
        drain(c)
        wj = c.workflow_json(wid)
        assert wj["state"] == "succeeded"
        assert wj["terminal_jobs"] == wj["total_jobs"] == 6
        assert wj["failed_jobs"] == 0
        # the fan-in landed the 3 shard results as ordered partials
        (rep_result,) = wj["results"].values()
        assert rep_result["partials"] == [{"n": 3}]
        # ONE trace tree: a single root span named "workflow", every other
        # span parented (transitively) under it, all under trace_id == wid
        spans = c.traces.spans(wid)
        roots = [s for s in spans if not s.get("parent_span_id")]
        assert len(roots) == 1 and roots[0]["name"] == "workflow"
        ids = {s["span_id"] for s in spans}
        assert all(
            s["parent_span_id"] in ids
            for s in spans if s.get("parent_span_id")
        )
        assert {"submit", "lease", "apply"} <= {s["name"] for s in spans}

    def test_unknown_workflow_returns_none(self):
        assert Controller().workflow_json("wf-nope") is None

    def test_second_identical_submission_hits_cache(self):
        c = Controller()
        first = c.submit_workflow(FANOUT_DOC)
        drain(c)
        wj1 = c.workflow_json(first["workflow_id"])
        second = c.submit_workflow(FANOUT_DOC)
        leases = drain(c)
        wj2 = c.workflow_json(second["workflow_id"])
        assert wj2["state"] == "succeeded"
        # ≥90% of the second submission served from cache (here: all of it,
        # so it drained with no agent leases at all)
        assert wj2["cache_hits"] >= 0.9 * wj2["total_jobs"]
        assert leases == 0
        # byte-identical results
        assert json.dumps(list(wj1["results"].values()), sort_keys=True) \
            == json.dumps(list(wj2["results"].values()), sort_keys=True)
        # dedupe ratio visible in the usage report
        usage = c.usage_json()
        assert usage["totals"]["result_cache_hits"] == wj2["cache_hits"]
        assert usage["totals"]["result_dedupe_ratio"] is not None
        by_tenant = usage["by_tenant"]["default"]
        assert by_tenant["result_dedupe_ratio"] is not None
        # cache-hit jobs billed at cache price
        stats = c.workflows_json()["result_cache"]
        assert stats["hits"] == wj2["cache_hits"]

    def test_cache_disabled_by_config(self):
        c = Controller(flow=FlowConfig(cache_enabled=False))
        c.submit_workflow(FANOUT_DOC)
        drain(c)
        c.submit_workflow(FANOUT_DOC)
        leases = drain(c)
        assert leases > 0
        assert c.workflows_json()["result_cache"] is None

    def test_flow_disabled_raises(self):
        c = Controller(flow=FlowConfig(enabled=False))
        with pytest.raises(RuntimeError, match="FLOW_ENABLED"):
            c.submit_workflow(FANOUT_DOC)

    def test_dependency_failed_cascade_kills_downstream(self):
        c = Controller(max_attempts=1)
        out = c.submit_workflow(FANOUT_DOC)
        drain(c, status="failed", rounds=1)
        wj = c.workflow_json(out["workflow_id"])
        assert wj["state"] == "dead"
        assert wj["terminal_jobs"] == wj["total_jobs"]
        counts = {s["name"]: s["counts"] for s in wj["stages"]}
        assert counts["tok"] == {"failed": 1}
        assert counts["cls"] == {"dead": 3}
        assert counts["acc"] == {"dead": 1}
        assert counts["rep"] == {"dead": 1}
        dead = c.job_snapshot(out["job_ids"][-1])
        assert dead["error"]["type"] == "DependencyFailed"


# ---------------------------------------------------------------------------
# journal replay
# ---------------------------------------------------------------------------


class TestWorkflowReplay:
    def test_replay_mid_dag_resumes_to_identical_output(self, tmp_path):
        jp = os.fspath(tmp_path / "journal.jsonl")
        c = Controller(journal_path=jp)
        out = c.submit_workflow(FANOUT_DOC)
        wid = out["workflow_id"]
        # drain only tok + the 3 cls shards, then "crash"
        for _ in range(2):
            lease = c.lease("a1", {"ops": ["echo"]}, max_tasks=4)
            for t in lease["tasks"]:
                c.report(lease["lease_id"], t["id"], t["job_epoch"],
                         "succeeded",
                         result={k: v for k, v in t["payload"].items()
                                 if k != "__collect_partials__"})
        c.close()
        c2 = Controller(journal_path=jp)
        wj = c2.workflow_json(wid)
        assert wj["state"] == "running"
        assert wj["terminal_jobs"] == 4
        assert wj["critical_stage"] == "acc"
        drain(c2)
        wj = c2.workflow_json(wid)
        assert wj["state"] == "succeeded"
        (rep_result,) = wj["results"].values()
        assert rep_result == {"final": True, "partials": [{"n": 3}]}
        # the replayed incarnation reopened ONE workflow trace root
        spans = c2.traces.spans(wid)
        roots = [s for s in spans if not s.get("parent_span_id")]
        assert len(roots) == 1 and roots[0]["name"] == "workflow"

    def test_replayed_cache_hits_stay_bit_identical(self, tmp_path):
        """A journal holding cache-hit terminal events replays to the same
        terminal results, byte for byte."""
        jp = os.fspath(tmp_path / "journal.jsonl")
        c = Controller(journal_path=jp)
        a = c.submit_workflow(FANOUT_DOC)
        drain(c)
        b = c.submit_workflow(FANOUT_DOC)   # all from cache
        drain(c)
        want_a = c.workflow_json(a["workflow_id"])
        want_b = c.workflow_json(b["workflow_id"])
        assert want_b["cache_hits"] == want_b["total_jobs"]
        c.close()
        c2 = Controller(journal_path=jp)
        got_a = c2.workflow_json(a["workflow_id"])
        got_b = c2.workflow_json(b["workflow_id"])
        for want, got in ((want_a, got_a), (want_b, got_b)):
            assert got["state"] == "succeeded"
            assert got["cache_hits"] == want["cache_hits"]
            assert json.dumps(got["results"], sort_keys=True) \
                == json.dumps(want["results"], sort_keys=True)

    def test_replay_ordering_regression_upstream_failed_last_record(
        self, tmp_path
    ):
        """THE replay-ordering bug (ISSUE 19 satellite): a crash lands
        between the upstream's terminal-failure record and the cascade's
        records. Replay must not strand the dep-gated dependent in PENDING —
        ``_finalize_replay_locked`` re-runs the cascade."""
        jp = os.fspath(tmp_path / "journal.jsonl")
        c = Controller(journal_path=jp, max_attempts=1)
        out = c.submit_workflow({"stages": [
            {"name": "up", "op": "echo", "payload": {}},
            {"name": "down", "op": "risk_accumulate", "payload": {},
             "after": ["up"]},
        ]})
        lease = c.lease("a1", {"ops": ["echo"]}, max_tasks=1)
        t = lease["tasks"][0]
        c.report(lease["lease_id"], t["id"], t["job_epoch"], "failed",
                 error={"type": "ValueError", "message": "boom",
                        "trace": ""})
        c.close()
        # drop every journal record after the upstream's failure
        lines = open(jp).read().splitlines()
        keep = []
        for ln in lines:
            keep.append(ln)
            ev = json.loads(ln)
            if ev.get("ev") == "result" and ev["job_id"].endswith("-up"):
                break
        assert len(keep) < len(lines)  # the cascade record WAS journaled
        with open(jp, "w") as f:
            f.write("\n".join(keep) + "\n")
        c2 = Controller(journal_path=jp)
        wj = c2.workflow_json(out["workflow_id"])
        assert wj["state"] == "dead"
        assert wj["terminal_jobs"] == 2
        snap = c2.job_snapshot(out["job_ids"][1])
        assert snap["state"] == "dead"
        assert snap["error"]["type"] == "DependencyFailed"
        # and nothing is left leasable
        assert c2.lease("a1", {"ops": ["echo", "risk_accumulate"]}) is None

    def test_dep_gated_job_rearms_after_replayed_upstream_success(
        self, tmp_path
    ):
        """The companion direction: upstream SUCCEEDED in the journal —
        after replay the dependent must lease (with partials materialized),
        not sit stranded."""
        jp = os.fspath(tmp_path / "journal.jsonl")
        c = Controller(journal_path=jp)
        c.submit_workflow({"stages": [
            {"name": "up", "op": "echo", "payload": {"v": 7}},
            {"name": "down", "op": "risk_accumulate", "payload": {},
             "after": ["up"]},
        ]})
        lease = c.lease("a1", {"ops": ["echo"]}, max_tasks=1)
        t = lease["tasks"][0]
        c.report(lease["lease_id"], t["id"], t["job_epoch"], "succeeded",
                 result={"v": 7})
        c.close()
        c2 = Controller(journal_path=jp, flow=FlowConfig(cache_enabled=False))
        lease = c2.lease("a1", {"ops": ["risk_accumulate"]}, max_tasks=4)
        assert lease is not None and len(lease["tasks"]) == 1
        assert lease["tasks"][0]["payload"]["partials"] == [{"v": 7}]


# ---------------------------------------------------------------------------
# LoopbackSession route (the HTTP dispatch minus sockets)
# ---------------------------------------------------------------------------


class TestLoopbackWorkflows:
    def test_submit_and_error_mapping(self):
        c = Controller()
        s = LoopbackSession(c)
        resp = s.post("http://c/v1/workflows", json=dict(
            FANOUT_DOC, tenant="acme", priority=4,
        ))
        assert resp.status_code == 200
        body = resp.json()
        assert body["workflow_id"].startswith("wf-")
        assert len(body["job_ids"]) == 6
        drain(c)
        assert c.workflow_json(body["workflow_id"])["state"] == "succeeded"

        bad = s.post("http://c/v1/workflows", json={"stages": [
            {"name": "a", "op": "echo", "after": ["a"]},
        ]})
        assert bad.status_code == 400

        off = LoopbackSession(Controller(flow=FlowConfig(enabled=False)))
        resp = off.post("http://c/v1/workflows", json=FANOUT_DOC)
        assert resp.status_code == 501


# ---------------------------------------------------------------------------
# /v1/infer front-door cache
# ---------------------------------------------------------------------------


class TestInferFrontDoorCache:
    def test_identical_request_served_from_cache(self):
        from agent_tpu.config import ServeConfig
        from tests.test_serving import TINY_CLS, _drain_serving

        c = Controller(serve=ServeConfig(max_wait_ms=0.0, max_batch=4))
        params = {"model_config": TINY_CLS, "topk": 2}
        rid1 = c.submit_infer("classify", "cache this text", params=params)
        c._serve_pump()
        _drain_serving(c)
        c._serve_reap()
        snap1 = c.infer_snapshot(rid1)
        assert snap1["state"] == "done"

        # identical resubmission: done at submit time, no job, no drain
        rid2 = c.submit_infer("classify", "cache this text", params=params)
        snap2 = c.infer_snapshot(rid2)
        assert snap2["state"] == "done"
        assert snap2["job_id"] is None
        assert snap2["result"] == snap1["result"]
        assert snap2["ttft_ms"] == 0.0
        usage = c.usage_json()
        assert usage["totals"]["result_cache_hits"] == 1

        # different text misses
        rid3 = c.submit_infer("classify", "different text", params=params)
        assert c.infer_snapshot(rid3)["state"] != "done"


# ---------------------------------------------------------------------------
# loadgen zipfian payload mix
# ---------------------------------------------------------------------------


class TestZipfPayloads:
    def test_zipf_rank_seeded_and_head_heavy(self):
        rng = random.Random(3)
        draws = [zipf_rank(rng, 50, 1.1) for _ in range(2000)]
        again = [zipf_rank(random.Random(3), 50, 1.1)]
        assert draws[0] == again[0]
        counts = {}
        for d in draws:
            counts[d] = counts.get(d, 0) + 1
        assert counts.get(0, 0) > counts.get(10, 0) > counts.get(40, 0)
        assert max(draws) < 50 and min(draws) >= 0
        # s=0 is uniform-ish: rank 0 no longer dominates
        flat = [zipf_rank(random.Random(3), 50, 0.0) for _ in range(2000)]
        fc = {}
        for d in flat:
            fc[d] = fc.get(d, 0) + 1
        assert fc.get(0, 0) < 3 * (2000 / 50)

    def test_traffic_class_zipf_payloads_recur_byte_identical(self):
        cls = TrafficClass(
            name="z", op="echo", payload={"base": 1}, payload_zipf_s=1.2,
            payload_pool=8,
        )
        rng = random.Random(11)
        payloads = [cls.build_payload(rng, i) for i in range(200)]
        variants = {p["variant"] for p in payloads}
        assert variants <= set(range(8)) and len(variants) > 1
        by_variant = {}
        for p in payloads:
            by_variant.setdefault(p["variant"], set()).add(
                json.dumps(p, sort_keys=True)
            )
        assert all(len(v) == 1 for v in by_variant.values())

    def test_payload_fn_becomes_pure_function_of_rank(self):
        def fn(rng, rank):
            return {"rank": rank, "noise": rng.random()}

        cls = TrafficClass(
            name="z", op="echo", payload_fn=fn, payload_zipf_s=1.0,
            payload_pool=4,
        )
        rng = random.Random(5)
        seen = {}
        for i in range(100):
            p = cls.build_payload(rng, i)
            key = p["rank"]
            if key in seen:
                assert seen[key] == p
            seen[key] = p
