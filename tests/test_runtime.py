"""Device runtime tests — run on the 8-device virtual CPU mesh (conftest)."""

import numpy as np
import pytest

import jax

from agent_tpu.config import DeviceConfig
from agent_tpu.runtime import MeshSpec, TpuRuntime, build_mesh
from agent_tpu.runtime.executor import ExecutableCache
from agent_tpu.runtime.runtime import detect_platform, get_runtime, reset_runtime


def test_virtual_mesh_has_8_devices():
    assert len(jax.devices()) == 8  # conftest flag took effect


def test_meshspec_defaults_all_to_dp():
    spec = MeshSpec.resolve(8)
    assert dict(spec.axes) == {"dp": 8, "tp": 1, "sp": 1}


def test_meshspec_partial_shape():
    spec = MeshSpec.resolve(8, {"tp": 2})
    assert dict(spec.axes) == {"dp": 4, "tp": 2, "sp": 1}
    spec = MeshSpec.resolve(8, {"tp": 2, "sp": 2})
    assert dict(spec.axes) == {"dp": 2, "tp": 2, "sp": 2}


def test_meshspec_rejects_indivisible():
    with pytest.raises(ValueError):
        MeshSpec.resolve(8, {"tp": 3})
    with pytest.raises(ValueError):
        MeshSpec.resolve(8, {"dp": 16})
    with pytest.raises(ValueError):
        MeshSpec.resolve(8, {"tp": 0})


def test_build_mesh_axes():
    mesh = build_mesh(shape={"dp": 2, "tp": 2, "sp": 2})
    assert dict(mesh.shape) == {"dp": 2, "tp": 2, "sp": 2}


def test_runtime_shards_batch_over_dp():
    rt = TpuRuntime(DeviceConfig())
    assert rt.n_devices == 8
    batch = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = rt.put_batch(batch)
    assert arr.sharding.spec == jax.sharding.PartitionSpec("dp")
    # Each of the 8 devices holds 2 of the 16 rows.
    assert arr.addressable_shards[0].data.shape == (2, 3)
    np.testing.assert_array_equal(np.asarray(arr), batch)


def test_params_store_builds_once():
    rt = TpuRuntime(DeviceConfig())
    calls = []

    def build():
        calls.append(1)
        return {"w": np.ones((4, 4), dtype=np.float32)}

    p1 = rt.get_params("m", build)
    p2 = rt.get_params("m", build)
    assert len(calls) == 1
    assert p1 is p2


def test_executable_cache_counts():
    cache = ExecutableCache()
    fn1 = cache.get_or_build(("k", 1), lambda: (lambda x: x + 1))
    fn2 = cache.get_or_build(("k", 1), lambda: (lambda x: x + 2))
    assert fn1 is fn2
    assert cache.stats() == {"entries": 1, "hits": 1, "misses": 1}


def test_detect_platform_cpu_here():
    assert detect_platform() == "cpu"  # conftest forces JAX_PLATFORMS=cpu


def test_singleton_reset():
    reset_runtime()
    rt1 = get_runtime()
    assert get_runtime() is rt1
    reset_runtime()
    assert get_runtime() is not rt1


def test_describe_telemetry_shape():
    rt = TpuRuntime(DeviceConfig())
    d = rt.describe()
    assert d["platform"] == "cpu"
    assert d["n_devices"] == 8
    assert d["mesh"] == {"dp": 8, "tp": 1, "sp": 1}


def test_clear_params_empties_store_and_rebuilds():
    """clear_params drops every resident model (HBM give-back for
    many-model workloads — see the r4 bench RESOURCE_EXHAUSTED note) and
    the next get_params rebuilds from scratch."""
    import numpy as np

    from agent_tpu.config import DeviceConfig
    from agent_tpu.runtime.runtime import TpuRuntime

    rt = TpuRuntime(config=DeviceConfig(tpu_disabled=True),
                    devices=jax.devices("cpu")[:2])
    builds = []

    def build(tag):
        def f():
            builds.append(tag)
            return {"w": np.ones((4, 4), np.float32)}
        return f

    rt.get_params("m-a", build("a"))
    rt.get_params("m-b", build("b"))
    rt.get_params("m-a", build("a2"))     # cached — no rebuild
    assert builds == ["a", "b"]
    assert len(rt._params) == 2
    rt.clear_params()
    assert len(rt._params) == 0
    rt.get_params("m-a", build("a3"))
    assert builds == ["a", "b", "a3"]


def test_clear_params_fences_in_flight_build():
    """A clear() racing an in-flight build must win: the late insert is
    dropped so a post-clear store is actually empty (the HBM give-back
    contract of clear_params)."""
    import threading

    import numpy as np

    from agent_tpu.config import DeviceConfig
    from agent_tpu.runtime.runtime import TpuRuntime

    rt = TpuRuntime(config=DeviceConfig(tpu_disabled=True),
                    devices=jax.devices("cpu")[:2])
    build_started = threading.Event()
    release_build = threading.Event()

    def slow_build():
        build_started.set()
        release_build.wait(5)
        return {"w": np.ones((2, 2), np.float32)}

    out = {}
    t = threading.Thread(
        target=lambda: out.setdefault(
            "tree", rt.get_params("raced-model", slow_build)
        )
    )
    t.start()
    assert build_started.wait(5)
    rt.clear_params()            # races the in-flight build
    release_build.set()
    t.join(5)
    assert "tree" in out         # the caller still gets its params
    assert len(rt._params) == 0  # ...but the cleared store stays empty
    assert rt.describe()["models_resident"] == []
