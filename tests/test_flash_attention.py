"""The Pallas flash-attention kernel must agree with dense attention.

Runs in interpreter mode on the CPU test mesh (the identical kernel compiles
via Mosaic on real TPU — same-program-different-backend). Covers multi-tile
streaming (Lk > block_k), padded keys, broadcast masks, fully-masked rows, and
the dense fallback for off-contract shapes.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from agent_tpu.kernels import flash_attention
from agent_tpu.models import layers


def _qkvm(B=2, H=2, Lq=16, Lk=16, D=8, pad_tail=0, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, H, Lq, D)), dtype=jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, Lk, D)), dtype=jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, Lk, D)), dtype=jnp.float32)
    mask_1d = np.ones((B, Lk), dtype=np.int32)
    if pad_tail:
        mask_1d[:, -pad_tail:] = 0
    mask = jnp.asarray(mask_1d)[:, None, None, :]
    return q, k, v, mask


def _check(got, q, k, v, mask, rtol=2e-5, atol=2e-5):
    want = np.asarray(layers.dot_product_attention(q, k, v, mask))
    np.testing.assert_allclose(np.asarray(got), want, rtol=rtol, atol=atol)


def test_flash_matches_dense_single_tile():
    q, k, v, mask = _qkvm(pad_tail=3)
    _check(flash_attention(q, k, v, mask, min_key_len=0, interpret=True), q, k, v, mask)


def test_flash_matches_dense_multi_tile_streaming():
    """Lq and Lk both larger than the tile → real streaming-softmax carry."""
    q, k, v, mask = _qkvm(Lq=32, Lk=48, D=8, pad_tail=5, seed=1)
    got = flash_attention(q, k, v, mask, block_q=16, block_k=16, min_key_len=0, interpret=True)
    _check(got, q, k, v, mask)


def test_flash_broadcast_mask_and_cross_lengths():
    q, k, v, _ = _qkvm(Lq=16, Lk=32, seed=2)
    shared = np.ones((1, 1, 1, 32), dtype=np.int32)
    shared[..., -7:] = 0
    shared = jnp.asarray(shared)
    got = flash_attention(q, k, v, shared, block_q=16, block_k=16,
                          min_key_len=0, interpret=True)
    _check(got, q, k, v, shared)


def test_flash_fully_masked_row_is_zero_not_nan():
    q, k, v, mask = _qkvm(seed=3)
    mask = mask.at[1].set(0)
    got = np.asarray(flash_attention(q, k, v, mask, min_key_len=0, interpret=True))
    assert np.isfinite(got).all()
    np.testing.assert_array_equal(got[1], np.zeros_like(got[1]))
    _check(flash_attention(q, k, v, mask, min_key_len=0, interpret=True)[0][None],
           q[0][None], k[0][None], v[0][None], mask[0][None])


def test_flash_falls_back_on_causal_mask():
    q, k, v, _ = _qkvm()
    causal = jnp.asarray(layers.causal_mask(16))
    got = np.asarray(flash_attention(q, k, v, causal, min_key_len=0, interpret=True))
    want = np.asarray(layers.dot_product_attention(q, k, v, causal))
    np.testing.assert_array_equal(got, want)


def test_flash_falls_back_on_indivisible_lengths():
    q, k, v, mask = _qkvm(Lq=10, Lk=10)  # 10 % 16 != 0 after min() → bq=10 ok
    # Make it actually indivisible: force tile 16 on Lk=10 via explicit blocks.
    got = np.asarray(
        flash_attention(q[:, :, :7], k, v, mask, block_q=4, min_key_len=0, interpret=True)
    )
    want = np.asarray(layers.dot_product_attention(q[:, :, :7], k, v, mask))
    np.testing.assert_array_equal(got, want)


def test_flash_bfloat16_inputs():
    q, k, v, mask = _qkvm(pad_tail=2, seed=4)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    got = np.asarray(
        flash_attention(qb, kb, vb, mask, min_key_len=0, interpret=True)
    ).astype(np.float32)
    want = np.asarray(
        layers.dot_product_attention(qb, kb, vb, mask)
    ).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_mesh_flash_preserves_dp_sharding():
    """shard_map-wrapped kernel must keep the batch dp-sharded (the bare
    pallas_call has no GSPMD rule and would replicate the full batch)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from agent_tpu.kernels import make_flash_attention
    from agent_tpu.runtime.mesh import build_mesh

    mesh = build_mesh(jax.devices()[:8], {"dp": 4, "tp": 2})
    fn = make_flash_attention(mesh)
    q, k, v, mask = _qkvm(B=8, H=4, Lq=16, Lk=16, D=8, pad_tail=3)
    shard = NamedSharding(mesh, P("dp", "tp", None, None))
    qs = jax.device_put(q, shard)
    ks = jax.device_put(k, shard)
    vs = jax.device_put(v, shard)
    ms = jax.device_put(mask, NamedSharding(mesh, P("dp", None, None, None)))
    out = jax.jit(fn)(qs, ks, vs, ms)
    assert out.sharding.spec == P("dp", "tp", None, None), out.sharding
    _check(out, q, k, v, mask)
    # Indivisible heads (H=3 over tp=2) → dense fallback, still correct.
    got = fn(q[:, :3], k[:, :3], v[:, :3], mask)
    _check(got, q[:, :3], k[:, :3], v[:, :3], mask)


def test_encoder_forward_with_flash_matches_dense():
    from agent_tpu.models import encoder

    cfg = encoder.EncoderConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_len=16, n_classes=10, dtype="float32",
    )
    params = encoder.init_params(cfg, model_id="flash-test")
    rng = np.random.default_rng(5)
    ids = jnp.asarray(rng.integers(0, 64, size=(4, 16)), dtype=jnp.int32)
    mask = np.ones((4, 16), dtype=np.int32)
    mask[:, 12:] = 0
    mask = jnp.asarray(mask)

    def attn(q, k, v, m):
        return flash_attention(q, k, v, m, min_key_len=0, interpret=True)

    dense_logits = encoder.forward(params, ids, mask, cfg)
    flash_logits = encoder.forward(params, ids, mask, cfg, attn_fn=attn)
    np.testing.assert_allclose(
        np.asarray(flash_logits), np.asarray(dense_logits),
        rtol=5e-5, atol=5e-5,
    )


# ---------------------------------------------------------------------------
# Trainable kernel (custom_vjp: Pallas forward AND backward)
# ---------------------------------------------------------------------------

import functools

import jax

from agent_tpu.kernels import flash_attention_trainable


def _train_attn(**kw):
    return functools.partial(
        flash_attention_trainable, min_key_len=0, interpret=True, **kw
    )


def _grads(attn_fn, q, k, v, mask, g):
    def loss(q, k, v):
        return jnp.sum(attn_fn(q, k, v, mask) * g)

    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


def test_trainable_forward_equals_inference_kernel():
    """Same streaming-softmax math → bit-identical forward outputs."""
    q, k, v, mask = _qkvm(Lq=32, Lk=48, pad_tail=5, seed=6)
    got = flash_attention_trainable(
        q, k, v, mask, block_q=16, block_k=16, min_key_len=0, interpret=True
    )
    want = flash_attention(
        q, k, v, mask, block_q=16, block_k=16, min_key_len=0, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_trainable_grads_match_dense_multi_tile():
    """dq/dk/dv from the streaming backward kernels == autodiff through the
    dense path, with real tile streaming (Lq, Lk > blocks) and padded keys."""
    q, k, v, mask = _qkvm(Lq=32, Lk=48, D=8, pad_tail=5, seed=7)
    g = jnp.asarray(
        np.random.default_rng(8).normal(size=q.shape), dtype=jnp.float32
    )
    flash = _grads(_train_attn(block_q=16, block_k=16), q, k, v, mask, g)
    dense = _grads(layers.dot_product_attention, q, k, v, mask, g)
    for got, want in zip(flash, dense):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )


def test_trainable_grads_bfloat16():
    q, k, v, mask = _qkvm(Lq=32, Lk=32, pad_tail=3, seed=9)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    g = jnp.asarray(
        np.random.default_rng(10).normal(size=q.shape), dtype=jnp.bfloat16
    )
    flash = _grads(_train_attn(block_q=16, block_k=16), qb, kb, vb, mask, g)
    dense = _grads(layers.dot_product_attention, qb, kb, vb, mask, g)
    for got, want in zip(flash, dense):
        np.testing.assert_allclose(
            np.asarray(got).astype(np.float32),
            np.asarray(want).astype(np.float32),
            rtol=5e-2, atol=5e-2,
        )


def test_trainable_fully_masked_row_grads_finite():
    """Documented divergence: a no-keys row contributes ZERO gradient on the
    flash path (dense backpropagates through its uniform-softmax guard);
    gradients must stay finite, never NaN."""
    q, k, v, mask = _qkvm(seed=11)
    mask = mask.at[1].set(0)
    g = jnp.ones_like(q)
    dq, dk, dv = _grads(_train_attn(), q, k, v, mask, g)
    for a in (dq, dk, dv):
        assert np.isfinite(np.asarray(a)).all()
    np.testing.assert_array_equal(np.asarray(dq[1]), 0.0)


def test_trainable_off_contract_falls_back_differentiable():
    """Causal mask → dense fallback; autodiff must flow through it."""
    q, k, v, _ = _qkvm()
    causal = jnp.asarray(layers.causal_mask(16))
    g = jnp.ones_like(q)
    flash = _grads(_train_attn(), q, k, v, causal, g)
    dense = _grads(layers.dot_product_attention, q, k, v, causal, g)
    for got, want in zip(flash, dense):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)


def test_trainable_selection_counter_ticks():
    import importlib

    fa_mod = importlib.import_module("agent_tpu.kernels.flash_attention")
    q, k, v, mask = _qkvm()
    before = fa_mod.SELECTION_COUNTS.get("flash_train", 0)
    flash_attention_trainable(q, k, v, mask, min_key_len=0, interpret=True)
    assert fa_mod.SELECTION_COUNTS["flash_train"] == before + 1


def test_trainable_under_remat_and_train_step():
    """The custom_vjp must compose with jax.checkpoint and the full train
    step: one flash-attn SGD step == one dense SGD step (loss and params)."""
    from agent_tpu.models import encoder
    from agent_tpu.models.train import make_train_step

    cfg = encoder.EncoderConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_len=16, n_classes=10, dtype="float32",
    )
    rng = np.random.default_rng(12)
    ids = jnp.asarray(rng.integers(0, 64, size=(4, 16)), dtype=jnp.int32)
    mask = np.ones((4, 16), dtype=np.int32)
    mask[:, 12:] = 0
    mask = jnp.asarray(mask)
    labels = jnp.asarray(rng.integers(0, 10, size=(4,)), dtype=jnp.int32)

    losses, states = [], []
    for attn_fn in (layers.dot_product_attention, _train_attn()):
        params = encoder.init_params(cfg, model_id="trainable-flash")
        init_state, step = make_train_step(cfg, remat=True, attn_fn=attn_fn)
        opt_state = init_state(params)
        params, opt_state, loss = step(params, opt_state, ids, mask, labels)
        losses.append(float(loss))
        states.append(params)
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)
    flat_d = jax.tree_util.tree_leaves(states[0])
    flat_f = jax.tree_util.tree_leaves(states[1])
    for a, b in zip(flat_d, flat_f):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )


def test_mesh_trainable_grads_on_dp_tp_mesh():
    """shard_map + custom_vjp: sharded backward == dense autodiff."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from agent_tpu.kernels import make_flash_attention_trainable
    from agent_tpu.runtime.mesh import build_mesh

    mesh = build_mesh(jax.devices()[:8], {"dp": 4, "tp": 2})
    fn = make_flash_attention_trainable(mesh)
    q, k, v, mask = _qkvm(B=8, H=4, Lq=16, Lk=16, D=8, pad_tail=3, seed=13)
    g = jnp.asarray(
        np.random.default_rng(14).normal(size=q.shape), dtype=jnp.float32
    )
    shard = NamedSharding(mesh, P("dp", "tp", None, None))
    qs, ks, vs = (jax.device_put(x, shard) for x in (q, k, v))
    ms = jax.device_put(mask, NamedSharding(mesh, P("dp", None, None, None)))

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v, ms) * g)

    flash = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(qs, ks, vs)
    dense = _grads(layers.dot_product_attention, q, k, v, mask, g)
    for got, want in zip(flash, dense):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )


def test_selects_flash_train_gate_and_mesh_divisibility():
    """The training-path predicate: 512 gate (below serving's 2048) AND the
    mesh wrapper's dp/tp divisibility fallback — the remat-off decision in
    bench's train leg rides on exactly this logic."""
    import importlib

    from agent_tpu.runtime.mesh import build_mesh

    fa_mod = importlib.import_module("agent_tpu.kernels.flash_attention")
    sel = fa_mod.selects_flash_train
    assert sel(512, batch=128, n_heads=12)
    assert not sel(256, batch=128, n_heads=12)        # below training gate
    assert not sel(520, batch=128, n_heads=12)        # tile-indivisible
    assert fa_mod.selects_flash(512, min_key_len=None) is False  # serving: 2048

    mesh = build_mesh(jax.devices("cpu")[:8], {"dp": 4, "tp": 2})
    assert sel(512, batch=128, n_heads=12, mesh=mesh)
    assert not sel(512, batch=126, n_heads=12, mesh=mesh)  # B % dp != 0
    assert not sel(512, batch=128, n_heads=11, mesh=mesh)  # H % tp != 0
    one = build_mesh(jax.devices("cpu")[:1], {"dp": 1})
    assert sel(512, batch=1, n_heads=3, mesh=one)     # size-1 mesh: no wrapper
