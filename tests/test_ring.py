"""Ring attention (sp sequence parallelism) must agree with dense attention.

The ring path (``agent_tpu.parallel.ring``) is a different *schedule* of the
same math — streaming softmax over ppermute-rotated K/V blocks — so on an
8-device virtual mesh its output must match ``dot_product_attention`` to
float32 tolerance, including padded keys, fully-padded rows, and the silent
dense fallback for incompatible shapes.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agent_tpu.config import DeviceConfig
from agent_tpu.models import encoder, layers
from agent_tpu.parallel.ring import make_ring_attention
from agent_tpu.runtime import TpuRuntime

MESH_SHAPE = {"dp": 2, "tp": 2, "sp": 2}


@pytest.fixture(scope="module")
def rt():
    return TpuRuntime(DeviceConfig(mesh_shape=MESH_SHAPE))


def _qkvm(B=4, H=4, L=16, D=8, pad_tail=3, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, H, L, D)), dtype=jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, L, D)), dtype=jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, L, D)), dtype=jnp.float32)
    mask_1d = np.ones((B, L), dtype=np.int32)
    if pad_tail:
        mask_1d[:, -pad_tail:] = 0
    mask = jnp.asarray(mask_1d)[:, None, None, :]
    return q, k, v, mask


def test_ring_matches_dense(rt):
    ring = make_ring_attention(rt.mesh)
    q, k, v, mask = _qkvm()
    got = np.asarray(ring(q, k, v, mask))
    want = np.asarray(layers.dot_product_attention(q, k, v, mask))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ring_fully_padded_row_is_zero_not_nan(rt):
    ring = make_ring_attention(rt.mesh)
    q, k, v, mask = _qkvm()
    mask = mask.at[1].set(0)  # row 1: every key masked (all-pad bucket row)
    got = np.asarray(ring(q, k, v, mask))
    assert np.isfinite(got).all()
    np.testing.assert_array_equal(got[1], np.zeros_like(got[1]))
    # Other rows unaffected.
    want = np.asarray(layers.dot_product_attention(q, k, v, mask))
    np.testing.assert_allclose(got[0], want[0], rtol=2e-5, atol=2e-5)


def test_ring_under_jit_and_cross_attention_lengths(rt):
    """Lq != Lk (cross-attention) and jit-wrapped: both must hold."""
    ring = make_ring_attention(rt.mesh)
    rng = np.random.default_rng(1)
    B, H, Lq, Lk, D = 4, 4, 8, 16, 8
    q = jnp.asarray(rng.normal(size=(B, H, Lq, D)), dtype=jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, Lk, D)), dtype=jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, Lk, D)), dtype=jnp.float32)
    mask = jnp.ones((B, 1, 1, Lk), dtype=jnp.int32)
    got = np.asarray(jax.jit(ring)(q, k, v, mask))
    want = np.asarray(layers.dot_product_attention(q, k, v, mask))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ring_broadcast_shared_mask(rt):
    """A [1,1,1,Lk] shared mask (dot_product_attention's broadcast contract)
    must work on the ring path, not crash shard_map."""
    ring = make_ring_attention(rt.mesh)
    q, k, v, _ = _qkvm()
    shared = np.ones((1, 1, 1, 16), dtype=np.int32)
    shared[..., -5:] = 0
    shared = jnp.asarray(shared)
    got = np.asarray(ring(q, k, v, shared))
    want = np.asarray(layers.dot_product_attention(q, k, v, shared))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ring_falls_back_on_incompatible_shapes(rt):
    ring = make_ring_attention(rt.mesh)
    # Lq=7 does not divide sp=2 → silent dense path, still correct.
    q, k, v, _ = _qkvm(B=4, H=4, L=16, D=8)
    q7 = q[:, :, :7]
    mask = jnp.ones((4, 1, 1, 16), dtype=jnp.int32)
    got = np.asarray(ring(q7, k, v, mask))
    want = np.asarray(layers.dot_product_attention(q7, k, v, mask))
    np.testing.assert_array_equal(got, want)
    # Causal (Lq-dim) mask → dense path too.
    causal = jnp.asarray(layers.causal_mask(16))
    got = np.asarray(ring(q, k, v, causal))
    want = np.asarray(layers.dot_product_attention(q, k, v, causal))
    np.testing.assert_array_equal(got, want)


def test_sp1_mesh_returns_dense_kernel():
    rt1 = TpuRuntime(DeviceConfig(mesh_shape={"dp": 8}))
    assert rt1.attention_fn() is layers.dot_product_attention


def test_encoder_forward_with_ring_matches_dense(rt):
    cfg = encoder.EncoderConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_len=16, n_classes=10, dtype="float32",
    )
    params = encoder.init_params(cfg, model_id="ring-test")
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, 64, size=(4, 16)), dtype=jnp.int32)
    mask = np.ones((4, 16), dtype=np.int32)
    mask[:, 12:] = 0
    mask = jnp.asarray(mask)
    ring = rt.attention_fn()
    assert ring is not layers.dot_product_attention
    dense_logits = encoder.forward(params, ids, mask, cfg)
    ring_logits = encoder.forward(params, ids, mask, cfg, attn_fn=ring)
    np.testing.assert_allclose(
        np.asarray(ring_logits), np.asarray(dense_logits), rtol=5e-5, atol=5e-5
    )


def test_ring_with_flash_fold_matches_dense(rt):
    """Ring hops folding through the Pallas kernel (interpret mode on the
    CPU mesh) must equal dense attention — the ring schedules communication,
    the kernel does the math."""
    ring = make_ring_attention(rt.mesh, use_flash_fold=True)
    q, k, v, mask = _qkvm()
    got = np.asarray(ring(q, k, v, mask))
    want = np.asarray(layers.dot_product_attention(q, k, v, mask))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    # Fully-masked row stays zero through the kernel path too.
    mask0 = mask.at[1].set(0)
    got0 = np.asarray(ring(q, k, v, mask0))
    assert np.isfinite(got0).all()
    np.testing.assert_array_equal(got0[1], np.zeros_like(got0[1]))
