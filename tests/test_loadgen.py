"""Open-loop traffic generator (ISSUE 10): seeded determinism, rate
shaping (burst/diurnal), class mixing, and the open-loop drop semantics."""

import pytest

from agent_tpu.config import LoadgenConfig
from agent_tpu.loadgen import (
    Arrival,
    ArrivalPattern,
    LoadGen,
    Rejected,
    TrafficClass,
    session_submitter,
)


def _classes():
    return [
        TrafficClass(name="interactive", op="probe", weight=3.0,
                     tenant="rt1", priority=8, deadline_sec=30.0,
                     payload={"sleep_ms": 5}),
        TrafficClass(name="bulk", op="shard", weight=1.0, tenant="bulk"),
    ]


class TestArrivalPattern:
    def test_burst_multiplies_rate_inside_window_only(self):
        p = ArrivalPattern(2.0, bursts=[(4.0, 8.0, 10.0)])
        assert p.rate(2.0) == pytest.approx(2.0)
        assert p.rate(5.0) == pytest.approx(20.0)
        assert p.rate(8.0) == pytest.approx(2.0)  # window is half-open
        assert p.peak_rate() >= 20.0

    def test_diurnal_swings_but_never_negative(self):
        p = ArrivalPattern(1.0, diurnal_amplitude=1.0,
                           diurnal_period_sec=10.0)
        rates = [p.rate(t / 10.0) for t in range(0, 101)]
        assert min(rates) >= 0.0
        assert max(rates) == pytest.approx(2.0, abs=0.05)

    def test_from_config_wires_the_env_surface(self):
        cfg = LoadgenConfig(base_rate=3.0, burst_factor=5.0,
                            burst_at_sec=1.0, burst_len_sec=2.0,
                            diurnal_amplitude=0.5)
        p = ArrivalPattern.from_config(cfg)
        assert p.rate(2.0) > p.rate(0.0)
        assert p.bursts == [(1.0, 3.0, 5.0)]


class TestSchedule:
    def test_same_seed_same_schedule(self):
        gen = LoadGen(_classes(), ArrivalPattern(5.0), seed=42)
        a = gen.schedule(10.0)
        b = gen.schedule(10.0)
        assert [(x.t, x.cls.name, x.payload, x.seq) for x in a] == \
               [(x.t, x.cls.name, x.payload, x.seq) for x in b]
        assert len(a) > 10

    def test_different_seed_different_schedule(self):
        base = ArrivalPattern(5.0)
        a = LoadGen(_classes(), base, seed=1).schedule(10.0)
        b = LoadGen(_classes(), base, seed=2).schedule(10.0)
        assert [x.t for x in a] != [x.t for x in b]

    def test_burst_density_tracks_the_factor(self):
        p = ArrivalPattern(4.0, bursts=[(10.0, 20.0, 10.0)])
        arrivals = LoadGen(_classes(), p, seed=7).schedule(30.0)
        calm = sum(1 for x in arrivals if x.t < 10.0)
        burst = sum(1 for x in arrivals if 10.0 <= x.t < 20.0)
        # 10× the rate over equal windows; allow generous Poisson noise.
        assert burst > 5 * max(1, calm)

    def test_class_mix_follows_weights(self):
        arrivals = LoadGen(_classes(), ArrivalPattern(50.0), seed=3
                           ).schedule(10.0)
        n = len(arrivals)
        interactive = sum(
            1 for x in arrivals if x.cls.name == "interactive"
        )
        assert n > 100
        assert 0.6 < interactive / n < 0.9  # weight 3:1

    def test_zero_rate_or_duration_yields_nothing(self):
        assert LoadGen(_classes(), ArrivalPattern(0.0)).schedule(10.0) == []
        assert LoadGen(_classes(), ArrivalPattern(5.0)).schedule(0.0) == []

    def test_rejects_bad_class_mixes(self):
        with pytest.raises(ValueError):
            LoadGen([], ArrivalPattern(1.0))
        with pytest.raises(ValueError):
            LoadGen([TrafficClass(name="x", op="o", weight=-1.0)],
                    ArrivalPattern(1.0))
        with pytest.raises(ValueError):
            LoadGen([TrafficClass(name="x", op="o", weight=0.0)],
                    ArrivalPattern(1.0))

    def test_payload_fn_is_seed_deterministic(self):
        cls = TrafficClass(
            name="x", op="o",
            payload_fn=lambda rng, seq: {"v": rng.randrange(1000),
                                         "seq": seq},
        )
        gen = LoadGen([cls], ArrivalPattern(10.0), seed=9)
        assert [a.payload for a in gen.schedule(5.0)] == \
               [a.payload for a in gen.schedule(5.0)]


class TestRun:
    def _gen(self, rate=50.0, seed=4):
        return LoadGen(_classes(), ArrivalPattern(rate), seed=seed)

    def test_open_loop_submits_everything_and_records_ledger(self):
        gen = self._gen()
        n_sched = len(gen.schedule(2.0))
        ids = iter(range(10_000))

        # Virtual clock: no real sleeping in tests.
        clock = {"t": 0.0}
        stats = gen.run(
            lambda a: f"job-{next(ids)}", 2.0,
            now=lambda: clock["t"],
            sleep=lambda s: clock.__setitem__("t", clock["t"] + s),
        )
        assert stats.total_submitted() == n_sched
        assert len(stats.jobs) == n_sched
        assert stats.job_ids("interactive")
        assert stats.total_rejected() == 0

    def test_rejections_drop_not_retry(self):
        gen = self._gen()
        calls = {"n": 0}

        def submit(arrival):
            calls["n"] += 1
            if calls["n"] % 3 == 0:
                raise Rejected("429")
            return f"job-{calls['n']}"

        clock = {"t": 0.0}
        stats = gen.run(
            submit, 1.0, now=lambda: clock["t"],
            sleep=lambda s: clock.__setitem__("t", clock["t"] + s),
        )
        assert stats.total_rejected() > 0
        # Open loop: every arrival got exactly one submit attempt.
        assert calls["n"] == stats.total_submitted() + stats.total_rejected()

    def test_submit_errors_counted_not_fatal(self):
        gen = self._gen(rate=20.0)
        clock = {"t": 0.0}

        def submit(arrival):
            raise RuntimeError("controller blip")

        stats = gen.run(
            submit, 1.0, now=lambda: clock["t"],
            sleep=lambda s: clock.__setitem__("t", clock["t"] + s),
        )
        assert stats.total_submitted() == 0
        assert sum(stats.errors.values()) > 0


class TestSessionSubmitter:
    class _Resp:
        def __init__(self, status, body=None):
            self.status_code = status
            self._body = body or {}

        def json(self):
            return self._body

    def test_submits_class_fields_and_parses_job_id(self):
        seen = []

        class Session:
            def post(self, url, json=None, timeout=None):
                seen.append((url, json))
                return TestSessionSubmitter._Resp(200, {"job_id": "j-1"})

        submit = session_submitter(Session(), "http://ctl")
        cls = _classes()[0]
        jid = submit(Arrival(0.0, cls, {"sleep_ms": 5}, 0))
        assert jid == "j-1"
        url, body = seen[0]
        assert url == "http://ctl/v1/jobs"
        assert body["tenant"] == "rt1" and body["priority"] == 8
        assert body["deadline_sec"] == 30.0
        assert body["payload"] == {"sleep_ms": 5}

    def test_429_raises_rejected_others_raise_runtime(self):
        class Session:
            def __init__(self, status):
                self.status = status

            def post(self, url, json=None, timeout=None):
                return TestSessionSubmitter._Resp(self.status, {})

        cls = _classes()[1]
        with pytest.raises(Rejected):
            session_submitter(Session(429))(Arrival(0.0, cls, {}, 0))
        with pytest.raises(RuntimeError):
            session_submitter(Session(500))(Arrival(0.0, cls, {}, 0))

    def test_429_carries_router_partition_stamp(self):
        """Behind the partition router (ISSUE 18) the 429 body names the
        rejecting partition; the drop counts under that partition."""

        class Session:
            def post(self, url, json=None, timeout=None):
                return TestSessionSubmitter._Resp(
                    429, {"error": "queue full", "retry_after_ms": 500,
                          "partition": "p2"},
                )

        cls = _classes()[1]
        submit = session_submitter(Session(), "http://router")
        with pytest.raises(Rejected) as exc:
            submit(Arrival(0.0, cls, {}, 0))
        assert exc.value.partition == "p2"

        gen = LoadGen(_classes(), ArrivalPattern(20.0), seed=3)
        clock = {"t": 0.0}
        stats = gen.run(
            submit, 1.0, now=lambda: clock["t"],
            sleep=lambda s: clock.__setitem__("t", clock["t"] + s),
        )
        assert stats.total_rejected() > 0
        assert stats.rejected_by_partition == {
            "p2": stats.total_rejected()
        }

    def test_loopback_round_trip(self):
        from agent_tpu.chaos import LoopbackSession
        from agent_tpu.controller.core import Controller

        c = Controller()
        submit = session_submitter(LoopbackSession(c))
        cls = _classes()[0]
        jid = submit(Arrival(0.0, cls, {"sleep_ms": 1}, 0))
        snap = c.job_snapshot(jid)
        assert snap["tenant"] == "rt1" and snap["priority"] == 8
        assert snap["deadline_sec"] == 30.0
