"""End-to-end: real HTTP controller + real agent loop draining a CSV job
through read_csv_shard → map_tokenize → risk_accumulate (SURVEY.md §4.2).

This is the full wire path: ControllerServer (ThreadingHTTPServer) ⇄ Agent
(requests) over localhost, dispatching through the registry — no stubs.
"""

import threading

import pytest

requests = pytest.importorskip("requests")

from agent_tpu.agent.app import Agent
from agent_tpu.config import AgentConfig, Config
from agent_tpu.controller import Controller, ControllerServer


def make_agent(url, tasks, max_tasks=4):
    cfg = Config(
        agent=AgentConfig(
            controller_url=url,
            agent_name="e2e-agent",
            tasks=tuple(tasks),
            max_tasks=max_tasks,
            idle_sleep_sec=0.01,
            error_backoff_sec=0.01,
        )
    )
    agent = Agent(config=cfg)
    agent._profile = {"tier": "test"}  # skip hardware probing in tests
    return agent


def drain(agent, controller, max_steps=200):
    for _ in range(max_steps):
        agent.step()
        if controller.drained():
            return True
    return False


@pytest.fixture()
def big_csv(tmp_path):
    path = tmp_path / "rows.csv"
    lines = ["id,text,risk"]
    for i in range(1000):
        lines.append(f'{i},"record {i} text",{(i % 17) * 0.25}')
    path.write_text("\n".join(lines) + "\n", encoding="utf-8")
    return str(path)


def test_drain_csv_map_reduce_over_http(big_csv):
    controller = Controller()
    with ControllerServer(controller) as server:
        shard_ids, _ = controller.submit_csv_job(
            big_csv, total_rows=1000, shard_size=100
        )
        # Map stage: tokenize each row-text; reduce stage: accumulate risks.
        agent = make_agent(
            server.url, ["read_csv_shard", "map_tokenize", "risk_accumulate"]
        )
        assert drain(agent, controller)

        results = controller.results()
        assert len(results) == len(shard_ids) == 10
        total_rows = sum(r["count"] for r in results.values())
        assert total_rows == 1000

        # Feed shard outputs onward: tokenize + accumulate, still over HTTP.
        all_rows = [row for r in results.values() for row in r["rows"]]
        controller.submit(
            "map_tokenize", {"items": [row["text"] for row in all_rows[:50]]}
        )
        controller.submit(
            "risk_accumulate",
            {
                "items": [{"risk": float(row["risk"])} for row in all_rows],
                "field": "risk",
            },
        )
        assert drain(agent, controller)
        res = controller.results()
        risk = next(
            r for r in res.values() if isinstance(r, dict) and "sum" in r
        )
        expected = sum((i % 17) * 0.25 for i in range(1000))
        assert risk["count"] == 1000
        assert abs(risk["sum"] - expected) < 1e-6


def test_epoch_fencing_discards_stale_result_over_http(big_csv):
    import time

    controller = Controller(lease_ttl_sec=0.05)
    with ControllerServer(controller) as server:
        controller.submit("echo", {"x": 1})
        controller.inject("stale_epoch")
        agent = make_agent(server.url, ["echo"])
        agent.step()  # executes and reports; controller discards (stale epoch)
        assert controller.stale_results == 1
        assert not controller.drained()
        # After the lease TTL passes the job re-queues at the bumped epoch and
        # a fresh attempt lands.
        time.sleep(0.06)
        assert drain(agent, controller, max_steps=10)


def test_two_agents_share_the_queue(big_csv):
    controller = Controller()
    with ControllerServer(controller) as server:
        for i in range(20):
            controller.submit("echo", {"i": i})
        a1 = make_agent(server.url, ["echo"], max_tasks=1)
        a2 = make_agent(server.url, ["echo"], max_tasks=1)

        def loop(agent):
            while not controller.drained():
                agent.step()

        t1 = threading.Thread(target=loop, args=(a1,))
        t2 = threading.Thread(target=loop, args=(a2,))
        t1.start(); t2.start()
        t1.join(timeout=30); t2.join(timeout=30)
        assert controller.drained()
        assert a1.tasks_done + a2.tasks_done == 20


def test_agent_ships_dynamic_worker_profile(big_csv):
    """The profile from sizing (not a hardcoded dict) reaches the controller —
    the wiring the reference never did (SURVEY.md §1 gap 1)."""
    controller = Controller()
    with ControllerServer(controller) as server:
        agent = make_agent(server.url, ["echo"])
        agent._profile = None  # force the real sizing path
        agent.step()  # idle lease is enough to ship profile+metrics
        prof = controller.last_profile
        assert prof["schema"] == "worker_profile/v2"
        assert prof["cpu"]["logical_cores"] >= 1
        assert "tpu" in prof and "limits" in prof
        assert prof["limits"]["max_payload_bytes"] == 262144


def test_full_map_reduce_drain_with_partials(big_csv):
    """The complete map-reduce story: risk_accumulate as the per-shard map
    stage over the CSV's risk column, the controller materializing shard
    partials into the reduce job, and the merged stats equal to a
    whole-column pass — all over real HTTP."""
    controller = Controller()
    with ControllerServer(controller) as server:
        shard_ids, reduce_id = controller.submit_csv_job(
            big_csv, total_rows=1000, shard_size=100,
            map_op="risk_accumulate",
            extra_payload={"field": "risk"},
            reduce_op="risk_accumulate",
            collect_partials=True,
        )
        agent = make_agent(server.url, ["risk_accumulate"])
        assert drain(agent, controller)

        final = controller.job(reduce_id).result
        values = [(i % 17) * 0.25 for i in range(1000)]
        assert final["count"] == 1000
        assert abs(final["sum"] - sum(values)) < 1e-6
        assert final["min"] == min(values) and final["max"] == max(values)
        assert final["n_partials"] == 10
        # Each shard computed real partials, not raw row echoes.
        shard0 = controller.job(shard_ids[0]).result
        assert shard0["count"] == 100 and "sum" in shard0
