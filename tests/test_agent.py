"""Agent loop unit tests: protocol shapes, error paths, dispatch through the
registry — driven via a stub session, no sockets (reference behaviors at
``app.py:143-316``)."""

import json

import pytest

from agent_tpu.agent.app import Agent, collect_host_metrics
from agent_tpu.config import AgentConfig, Config


class StubResponse:
    def __init__(self, status_code, body=None):
        self.status_code = status_code
        self._body = body
        self.text = json.dumps(body) if body is not None else ""

    def json(self):
        if self._body is None:
            raise ValueError("no body")
        return self._body


class StubSession:
    """Scripted controller: pops one response per POST, records requests."""

    def __init__(self, script):
        self.script = list(script)
        self.requests = []

    def post(self, url, json=None, timeout=None):
        self.requests.append((url, json))
        item = self.script.pop(0)
        if isinstance(item, Exception):
            raise item
        return item


def fast_config(**agent_kw):
    agent_kw.setdefault("controller_url", "http://test")
    agent_kw.setdefault("idle_sleep_sec", 0.0)
    agent_kw.setdefault("error_backoff_sec", 0.0)
    agent_kw.setdefault("tasks", ("echo",))
    return Config(agent=AgentConfig(**agent_kw))


def test_lease_request_carries_protocol_fields():
    session = StubSession([StubResponse(204)])
    agent = Agent(config=fast_config(agent_name="a1"), session=session)
    agent._profile = {"tier": "test"}  # skip hardware probing
    assert agent.lease_once() is None
    url, body = session.requests[0]
    assert url.endswith("/v1/leases")
    assert body["agent"] == "a1"
    assert body["capabilities"]["ops"] == ["echo"]
    assert body["max_tasks"] == 1
    assert body["timeout_ms"] == 3000
    assert body["worker_profile"] == {"tier": "test"}
    assert "metrics" in body


def test_lease_capabilities_carry_device_and_load_fields():
    """ISSUE 4 satellite: the lease body's capabilities ship device_kind /
    mesh_devices (from TpuRuntime.describe()) and the staged queue_depth —
    regardless of the controller's scheduler policy. Wire shape pinned."""

    class StubRuntime:
        def describe(self):
            return {"platform": "tpu", "n_devices": 8, "mesh": {"dp": 8}}

    session = StubSession([StubResponse(204)])
    agent = Agent(config=fast_config(agent_name="a1"), session=session,
                  runtime=StubRuntime())
    agent._profile = {"tier": "test"}
    agent.staged_depth_fn = lambda: 3
    assert agent.lease_once() is None
    _, body = session.requests[0]
    assert body["capabilities"] == {
        "ops": ["echo"],
        "queue_depth": 3,
        "device_kind": "tpu",
        "mesh_devices": 8,
        "wire_formats": ["b1"],
    }


def test_lease_capabilities_without_runtime_omit_device_fields():
    """A pure-host agent (no runtime built) must not fabricate device
    telemetry — and must not force the runtime into existence either."""
    session = StubSession([StubResponse(204)])
    agent = Agent(config=fast_config(), session=session)
    agent._profile = {}
    assert agent.lease_once() is None
    _, body = session.requests[0]
    assert body["capabilities"] == {
        "ops": ["echo"], "queue_depth": 0, "wire_formats": ["b1"],
    }
    assert agent.runtime is None


def test_wire_binary_off_drops_the_capability_advert():
    """WIRE_BINARY=0 agents must look exactly like pre-wire agents on the
    lease body (the negotiation is strictly opt-in from both sides)."""
    session = StubSession([StubResponse(204)])
    agent = Agent(config=fast_config(wire_binary=False), session=session)
    agent._profile = {}
    assert agent.lease_once() is None
    _, body = session.requests[0]
    assert body["capabilities"] == {"ops": ["echo"], "queue_depth": 0}


def test_metrics_flush_ships_fresh_queue_depth():
    """ISSUE 6 satellite: every channel that ships capabilities samples
    ``staged_q.qsize()`` at request-BUILD time — including the poster's
    metrics-only flush, which used to advertise no depth at all and could
    lag reality by a whole poll cycle."""
    session = StubSession([StubResponse(204), StubResponse(204)])
    agent = Agent(config=fast_config(), session=session)
    agent._profile = {}
    depth = {"n": 5}
    agent.staged_depth_fn = lambda: depth["n"]
    assert agent.push_metrics() is True
    depth["n"] = 2  # queue drained between the two flushes
    assert agent.push_metrics() is True
    first, second = (body for _, body in session.requests)
    assert first["max_tasks"] == 0 and second["max_tasks"] == 0
    assert first["capabilities"] == {"ops": [], "queue_depth": 5}
    assert second["capabilities"] == {"ops": [], "queue_depth": 2}


def test_lease_batch_hint_raises_the_grant_ask():
    """The staging pool's hint lifts max_tasks on the wire (never below the
    configured MAX_TASKS); without a hint the legacy ask is unchanged."""
    session = StubSession([StubResponse(204), StubResponse(204)])
    agent = Agent(config=fast_config(max_tasks=2), session=session)
    agent._profile = {}
    assert agent.lease_once() is None
    agent.lease_batch_hint = 4
    assert agent.lease_once() is None
    (_, first), (_, second) = session.requests
    assert first["max_tasks"] == 2
    assert second["max_tasks"] == 4


def test_binary_task_payload_decodes_before_dispatch():
    """A controller-encoded ``__bin__`` payload reaches the op as the plain
    decoded dict; a corrupt envelope fails the task like any malformed
    task (structured error, no crash)."""
    from agent_tpu.data import wire

    good = wire.encode_task_payload({"texts": ["a", "b"], "topk": 1})
    lease = StubResponse(200, {
        "lease_id": "L1",
        "wire": "b1",
        "tasks": [
            {"id": "j1", "op": "echo", "payload": good, "job_epoch": 0},
            {"id": "j2", "op": "echo",
             "payload": {"__bin__": "!!not base64!!"}, "job_epoch": 0},
        ],
    })
    session = StubSession([lease, StubResponse(200, {}),
                           StubResponse(200, {})])
    agent = Agent(config=fast_config(max_tasks=2), session=session)
    agent._profile = {}
    agent.step()
    assert agent.wire_format == "b1"
    _, ok_body = session.requests[1]
    assert ok_body["result"]["echo"] == {"texts": ["a", "b"], "topk": 1}
    _, bad_body = session.requests[2]
    assert bad_body["status"] == "failed"
    assert bad_body["error"]["type"] == "ValueError"


def test_transport_error_raises_for_backoff():
    session = StubSession([OSError("connection refused")])
    agent = Agent(config=fast_config(), session=session)
    agent._profile = {}
    with pytest.raises(RuntimeError, match="transport"):
        agent.lease_once()


def test_step_executes_task_and_reports_success():
    lease = StubResponse(
        200,
        {
            "lease_id": "L1",
            "tasks": [
                {"id": "j1", "op": "echo", "payload": {"hello": 1}, "job_epoch": 3}
            ],
        },
    )
    session = StubSession([lease, StubResponse(200, {"accepted": True})])
    agent = Agent(config=fast_config(), session=session)
    agent._profile = {}
    assert agent.step() is True
    url, body = session.requests[1]
    assert url.endswith("/v1/results")
    assert body["lease_id"] == "L1"
    assert body["job_id"] == "j1"
    assert body["job_epoch"] == 3  # epoch echoed for fencing
    assert body["status"] == "succeeded"
    assert body["result"]["echo"] == {"hello": 1}
    assert "duration_ms" in body["result"]


def test_op_exception_becomes_structured_failed_result():
    lease = StubResponse(
        200,
        {
            "lease_id": "L1",
            "tasks": [{"id": "j1", "op": "boom", "payload": {}, "job_epoch": 0}],
        },
    )
    session = StubSession([lease, StubResponse(200, {})])
    agent = Agent(config=fast_config(), session=session)
    agent._profile = {}

    def boom(payload, ctx=None):
        raise RuntimeError("kaput")

    agent.handlers["boom"] = boom
    agent.step()
    _, body = session.requests[1]
    assert body["status"] == "failed"
    assert body["error"]["type"] == "RuntimeError"
    assert body["error"]["message"] == "kaput"
    assert "trace" in body["error"]


def test_unknown_op_reports_failed_not_crash():
    lease = StubResponse(
        200,
        {
            "lease_id": "L1",
            "tasks": [{"id": "j1", "op": "no_such", "payload": {}, "job_epoch": 0}],
        },
    )
    session = StubSession([lease, StubResponse(200, {})])
    agent = Agent(config=fast_config(), session=session)
    agent._profile = {}
    agent.step()
    _, body = session.requests[1]
    assert body["status"] == "failed"
    assert body["error"]["type"] == "UnknownOp"


def test_extract_task_accepts_id_or_job_id_and_validates():
    ok = {"id": "a", "op": "echo", "payload": {}, "job_epoch": 1}
    assert Agent.extract_task(ok)[0] == "a"
    alt = {"job_id": "b", "op": "echo"}
    job_id, op, payload, epoch = Agent.extract_task(alt)
    assert (job_id, op, payload, epoch) == ("b", "echo", {}, None)
    for bad in [
        "not a dict",
        {"op": "echo"},
        {"id": "a"},
        {"id": "a", "op": "echo", "payload": []},
        {"id": 7, "op": "echo"},
    ]:
        with pytest.raises(ValueError):
            Agent.extract_task(bad)


def test_shutdown_drains_mid_lease():
    """SIGTERM drain regression (ISSUE 10 satellite): the in-flight task's
    result is DELIVERED, the unstarted remainder of the lease is RELEASED
    (not abandoned to the TTL), the spool ends empty, and the final
    metrics flush carries the `draining` mark."""
    lease = StubResponse(
        200,
        {
            "lease_id": "L1",
            "tasks": [
                {"id": "j1", "op": "echo", "payload": {}, "job_epoch": 0},
                {"id": "j2", "op": "echo", "payload": {}, "job_epoch": 0},
            ],
        },
    )
    session = StubSession([
        lease,
        StubResponse(200, {"accepted": True}),   # j1 result
        StubResponse(200, {"accepted": True, "released": True}),  # j2
        StubResponse(204),                       # final metrics flush
    ])
    agent = Agent(config=fast_config(max_tasks=2), session=session)
    agent._profile = {}

    real_run = agent.run_task

    def run_then_stop(lease_id, task):
        real_run(lease_id, task)
        agent.shutdown()  # the actual SIGTERM handler

    agent.run_task = run_then_stop
    agent.run(max_steps=5)
    # The in-flight task ran and its result was delivered.
    assert agent.tasks_done == 1
    results = [
        body for url, body in session.requests if url.endswith("/v1/results")
    ]
    assert [r["job_id"] for r in results] == ["j1", "j2"]
    assert results[0]["status"] == "succeeded"
    assert results[1]["status"] == "released"  # handed back, no TTL wait
    # Nothing left undelivered, and the drain announced itself.
    assert len(agent.spool) == 0
    flush = session.requests[-1][1]
    assert flush["max_tasks"] == 0 and flush["draining"] is True


def test_hard_stop_without_drain_abandons_remainder():
    """running=False WITHOUT request_drain (the hard-kill model) keeps the
    historical behavior: the unstarted task is abandoned to the lease TTL,
    no release is posted."""
    lease = StubResponse(
        200,
        {
            "lease_id": "L1",
            "tasks": [
                {"id": "j1", "op": "echo", "payload": {}, "job_epoch": 0},
                {"id": "j2", "op": "echo", "payload": {}, "job_epoch": 0},
            ],
        },
    )
    session = StubSession([
        lease,
        StubResponse(200, {"accepted": True}),
        StubResponse(204),  # final flush (no draining mark)
    ])
    agent = Agent(config=fast_config(max_tasks=2), session=session)
    agent._profile = {}

    real_run = agent.run_task

    def run_then_kill(lease_id, task):
        real_run(lease_id, task)
        agent.running = False  # hard stop, not a drain

    agent.run_task = run_then_kill
    agent.run(max_steps=5)
    results = [
        body for url, body in session.requests if url.endswith("/v1/results")
    ]
    assert [r["job_id"] for r in results] == ["j1"]
    assert "draining" not in session.requests[-1][1]


def test_release_task_posts_released_status():
    session = StubSession([StubResponse(200, {"accepted": True})])
    agent = Agent(config=fast_config(), session=session)
    agent._profile = {}
    ok = agent.release_task(
        "L9", {"id": "j7", "op": "echo", "job_epoch": 3}
    )
    assert ok
    url, body = session.requests[0]
    assert url.endswith("/v1/results")
    assert body["status"] == "released" and body["job_id"] == "j7"
    assert body["job_epoch"] == 3 and body["lease_id"] == "L9"
    # Malformed tasks release nothing (nothing to address the release to).
    assert agent.release_task("L9", {"op": "echo"}) is False
    assert agent.release_task("L9", "not-a-dict") is False


def test_host_metrics_shape():
    m = collect_host_metrics()
    if m:  # psutil present
        assert 0.0 <= m["cpu_util"] <= 1.0
        assert m["ram_mb"] >= 0


def test_profile_dir_captures_trace(tmp_path, monkeypatch):
    """PROFILE_DIR → the first task leaves an XProf trace on disk (§5.1)."""
    import os

    from agent_tpu.agent.app import Agent
    from agent_tpu.config import Config

    monkeypatch.setenv("TASKS", "echo")
    monkeypatch.setenv("PROFILE_DIR", str(tmp_path / "traces"))

    class OneLeaseSession:
        def __init__(self):
            self.posts = []

        def post(self, url, json=None, timeout=None):
            class R:
                status_code = 200

                def __init__(self, body):
                    self._body = body

                def json(self):
                    return self._body

            self.posts.append((url, json))
            if url.endswith("/v1/leases"):
                return R({"lease_id": "l1", "tasks": [
                    {"id": "j1", "op": "echo", "payload": {"x": 1},
                     "job_epoch": 0}]})
            return R({"accepted": True})

    agent = Agent(config=Config.from_env(), session=OneLeaseSession())
    agent.step()
    assert agent.tasks_done == 1
    trace_root = tmp_path / "traces"
    assert trace_root.exists()
    files = [p for p in trace_root.rglob("*") if p.is_file()]
    assert files, "no trace files written"
