"""Capability detection & worker sizing — successor of reference ``worker_sizing.py``.

Same stance as the reference, different substrate:

- **Proof-based TPU detection** (reference ``worker_sizing.py:203-213``): we claim
  a TPU only if ``jax.devices()`` actually lists TPU devices. Env vars
  (JAX_PLATFORM_NAME / TPU_NAME / TPU_TYPE) are recorded as hints, never trusted.
- CPU sizing reserves cores for the OS and derives an in-flight target from a
  pipeline factor (reference ``worker_sizing.py:44-124``).
- GPU detection parses ``nvidia-smi`` and honors ``NVIDIA_VISIBLE_DEVICES=none``
  (reference ``worker_sizing.py:127-185``).
- TPU_ONLY mode caps CPU at one worker and zeroes GPU so the controller cannot
  accidentally schedule host work on a TPU agent (reference ``:233-240``), while
  keeping cpu/gpu keys in the profile to avoid schema drift (reference ``:224-225``).

The TPU-native upgrade: batch/shard sizing is derived from the **mesh topology**
(device count, HBM bytes) rather than CPU core count — the profile carries
``tpu.suggested_batch`` and ``tpu.suggested_shard_rows`` hints the controller can
use when splitting jobs.
"""

from agent_tpu.sizing.profile import (
    build_worker_profile,
    detect_cpu,
    detect_gpu,
    detect_tpu,
)

__all__ = ["build_worker_profile", "detect_cpu", "detect_gpu", "detect_tpu"]
