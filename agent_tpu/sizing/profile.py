"""Worker profile assembly (reference ``worker_sizing.py:44-256``, rethought).

Everything here is host-side and side-effect free except the optional probes
(psutil import, one ``nvidia-smi`` subprocess, one ``jax.devices()`` call). All
probes degrade to conservative answers when their dependency is missing — the
agent must boot anywhere, like the reference booting without pycoral
(reference ``ops/_tpu_runtime.py:45-46``).
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Any, Dict, List, Optional

from agent_tpu.config import Config, DeviceConfig, SizingConfig, env_bool

# Hard limits advertised to the controller with every lease. The reference
# hardcoded these in its static profile (reference app.py:108); they are a wire
# contract so we keep the numbers, but max_tokens now reflects the real model
# context (long-context ring attention lifts it per-model; this is the default).
MAX_PAYLOAD_BYTES = 262_144
MAX_TOKENS = 2_048


def _logical_cores() -> int:
    try:
        import psutil  # type: ignore

        n = psutil.cpu_count(logical=True)
        if n:
            return int(n)
    except Exception:  # noqa: BLE001 — psutil optional
        pass
    return os.cpu_count() or 1


def _total_ram_bytes() -> Optional[int]:
    try:
        import psutil  # type: ignore

        return int(psutil.virtual_memory().total)
    except Exception:  # noqa: BLE001
        pass
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page_size = os.sysconf("SC_PAGE_SIZE")
        return int(pages) * int(page_size)
    except (ValueError, OSError, AttributeError):
        return None


def detect_cpu(cfg: Optional[SizingConfig] = None) -> Dict[str, Any]:
    """CPU sizing: reserve cores for the OS, derive worker counts + in-flight
    target (reference ``worker_sizing.py:44-124``)."""
    cfg = cfg or SizingConfig()
    cores = _logical_cores()
    # Reserve ~25% of cores for the OS, clamped to [floor, cap], never all cores.
    reserved = min(
        cfg.cpu_reserved_cores_cap,
        max(cfg.cpu_reserved_cores_floor, cores // 4),
    )
    reserved = min(reserved, max(cores - 1, 0))
    usable = max(1, cores - reserved)

    target_inflight = max(
        cfg.cpu_min_workers, int(usable * max(cfg.cpu_pipeline_factor, 0.0))
    )

    soft_cap = cores * max(cfg.cpu_soft_cap_multiplier, 1)
    ram = _total_ram_bytes()
    if ram and cfg.cpu_per_worker_bytes > 0:
        soft_cap = min(soft_cap, max(1, ram // cfg.cpu_per_worker_bytes))

    out: Dict[str, Any] = {
        "logical_cores": cores,
        "reserved_cores": reserved,
        "usable_cores": usable,
        "target_inflight": min(target_inflight, soft_cap),
        "max_cpu_workers": int(soft_cap),
    }
    if ram is not None:
        out["ram_bytes"] = ram
    return out


def _nvidia_devices_allowed() -> bool:
    """``NVIDIA_VISIBLE_DEVICES=none`` (or ``void``) disables GPU scheduling
    (reference ``worker_sizing.py:127-136``)."""
    v = os.environ.get("NVIDIA_VISIBLE_DEVICES")
    if v is None:
        return True
    return v.strip().lower() not in ("none", "void", "")


def detect_gpu() -> Dict[str, Any]:
    """GPU inventory via ``nvidia-smi`` (reference ``worker_sizing.py:139-185``).

    Absent binary, disallowed visibility, or parse failure all mean "no GPU".
    """
    none = {"gpu_present": False, "gpus": [], "max_gpu_workers": 0}
    if not _nvidia_devices_allowed():
        return none
    if shutil.which("nvidia-smi") is None:
        return none
    try:
        proc = subprocess.run(
            [
                "nvidia-smi",
                "--query-gpu=name,memory.total",
                "--format=csv,noheader,nounits",
            ],
            capture_output=True,
            text=True,
            timeout=5,
        )
    except (OSError, subprocess.TimeoutExpired):
        return none
    if proc.returncode != 0:
        return none
    gpus: List[Dict[str, Any]] = []
    for line in proc.stdout.splitlines():
        parts = [p.strip() for p in line.split(",")]
        if len(parts) < 2 or not parts[0]:
            continue
        gpu: Dict[str, Any] = {"name": parts[0]}
        try:
            gpu["memory_mb"] = int(float(parts[1]))
        except (TypeError, ValueError):
            pass
        gpus.append(gpu)
    if not gpus:
        return none
    return {"gpu_present": True, "gpus": gpus, "max_gpu_workers": len(gpus)}


def detect_tpu(device_cfg: Optional[DeviceConfig] = None) -> Dict[str, Any]:
    """Proof-based TPU detection (reference ``worker_sizing.py:188-218``).

    A TPU is claimed only when ``jax.devices()`` lists devices whose platform is
    ``tpu``. Hints from the environment are recorded for observability but never
    flip ``tpu_present`` by themselves. The TPU_DISABLED kill-switch returns
    early *without importing jax* — initializing the TPU plugin is exactly what
    the switch exists to prevent.
    """
    device_cfg = device_cfg or DeviceConfig()
    hints = {
        k: v
        for k, v in {
            "platform_hint": device_cfg.platform_hint,
            "tpu_name": device_cfg.tpu_name,
            "tpu_type": device_cfg.tpu_type,
        }.items()
        if v
    }
    if device_cfg.tpu_disabled:
        return {
            "tpu_present": False,
            "max_tpu_workers": 0,
            "disabled": True,
            "hints": hints,
        }
    out: Dict[str, Any] = {"tpu_present": False, "max_tpu_workers": 0, "hints": hints}
    try:
        import jax

        devices = jax.devices()
        tpus = [d for d in devices if d.platform == "tpu"]
        if tpus:
            out["tpu_present"] = True
            # One runtime owns the whole mesh (single-owner invariant, SURVEY
            # §5.2) — so one "worker", however many chips it spans.
            out["max_tpu_workers"] = 1
            out["n_chips"] = len(tpus)
            out["device_kind"] = tpus[0].device_kind
            # Probe ALL chips, not just tpus[0] (ISSUE 9 satellite): sizing
            # derives batch hints from per-chip HBM, and a heterogeneous or
            # partially-reporting slice must size to the SMALLEST chip —
            # the conservative bound that never overflows a member.
            limits: List[int] = []
            for dev in tpus:
                try:
                    mem = dev.memory_stats() or {}
                except Exception:  # noqa: BLE001 — memory_stats optional
                    continue
                if isinstance(mem, dict) and mem.get("bytes_limit"):
                    limits.append(int(mem["bytes_limit"]))
            if limits:
                out["hbm_bytes_per_chip"] = min(limits)
                out["hbm_bytes_total"] = sum(limits)
                if len(limits) != len(tpus):
                    out["hbm_probed_chips"] = len(limits)
        else:
            out["backend_platform"] = devices[0].platform if devices else None
    except Exception as exc:  # noqa: BLE001 — no jax / no backend ⇒ no TPU
        out["probe_error"] = repr(exc)
    return out


def _tpu_batch_hints(tpu: Dict[str, Any]) -> Dict[str, int]:
    """Topology-derived batching hints — the TPU-native replacement for sizing
    by CPU core count. The controller reads ``suggested_shard_rows`` from the
    last-seen profile when ``submit_csv_job`` is called without an explicit
    ``shard_size`` (``controller/core.py::suggested_shard_size``).

    suggested_batch: rows per device step — sized so activation memory stays a
    small slice of HBM at our default encoder footprint; multiple of chip count
    so the dp axis always divides the batch.
    suggested_shard_rows: rows per leased task — enough batches per task that
    lease-protocol overhead amortizes to noise (SURVEY §3.1).
    """
    chips = max(1, int(tpu.get("n_chips", 1)))
    hbm = int(tpu.get("hbm_bytes_per_chip", 16 * 2**30))
    # ~1 MB activation budget per row at seq 512 / d_model 512 in bf16, padded
    # generously; cap the per-chip batch to keep compile shapes reasonable.
    per_chip = max(8, min(1024, hbm // (64 * 2**20)))
    batch = per_chip * chips
    return {"suggested_batch": batch, "suggested_shard_rows": batch * 16}


def build_worker_profile(config: Optional[Config] = None) -> Dict[str, Any]:
    """Assemble the worker profile shipped with every lease request
    (reference ``worker_sizing.py:221-256`` + the static profile it was meant
    to replace, reference ``app.py:101-109``)."""
    config = config or Config()
    cpu = detect_cpu(config.sizing)
    gpu = detect_gpu()
    tpu = detect_tpu(config.device)

    tpu_only = config.device.tpu_only or env_bool("TPU_ONLY", False)
    if tpu_only:
        # Keep cpu/gpu keys (schema stability, reference :224-225) but prevent
        # accidental host-side scheduling (reference :233-240).
        cpu = dict(cpu, max_cpu_workers=1, target_inflight=1)
        gpu = dict(gpu, gpu_present=False, gpus=[], max_gpu_workers=0)

    tier = "tpu-pod" if tpu.get("n_chips", 0) > 1 else (
        "tpu" if tpu["tpu_present"] else "cpu"
    )
    profile: Dict[str, Any] = {
        "schema": "worker_profile/v2",
        "tier": tier,
        "cpu": cpu,
        "gpu": gpu,
        "tpu": dict(tpu, kind=config.agent.tpu_kind),
        "max_total_workers": (
            cpu["max_cpu_workers"] + gpu["max_gpu_workers"] + tpu["max_tpu_workers"]
        ),
        "limits": {
            "max_payload_bytes": MAX_PAYLOAD_BYTES,
            "max_tokens": MAX_TOKENS,
        },
    }
    if tpu["tpu_present"]:
        profile["tpu"].update(_tpu_batch_hints(tpu))
    return profile
