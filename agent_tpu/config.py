"""Typed configuration for the whole framework.

The reference configures everything through ~25 environment variables read ad hoc
at import time (reference ``app.py:19-44``, ``worker_sizing.py:12-41``,
``ops/_tpu_runtime.py:29``, ``ops/map_summarize.py:9-10``). That env surface is a
compatibility contract (containers are launched with these vars), so we keep every
variable name and default — but read them in exactly one place, behind dataclasses,
at a controlled time (``AgentConfig.from_env()``), never at import.
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple


def env_str(name: str, default: str) -> str:
    v = os.environ.get(name)
    return v if v is not None and v != "" else default


def env_int(name: str, default: int) -> int:
    """Forgiving int parse (bad values fall back, like reference worker_sizing.py:12-20)."""
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(float(v))
    except (TypeError, ValueError):
        return default


def env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


# Truthy string tokens of the env/label grammar (reference
# worker_sizing.py:31-41) — shared by env_bool and controller label matching
# so the two can never diverge.
TRUTHY_TOKENS = ("1", "true", "yes", "on", "y")


def env_bool(name: str, default: bool) -> bool:
    """Truthy strings per reference worker_sizing.py:31-41 ("1", "true", "yes", "on")."""
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return v.strip().lower() in TRUTHY_TOKENS


def parse_labels(raw: str) -> Dict[str, Any]:
    """``"k=v,k2=v2,flag"`` → ``{"k": "v", "k2": "v2", "flag": True}``.

    Same grammar as the reference label parser (reference ``app.py:49-63``):
    comma-separated, ``k=v`` pairs become strings, bare tokens become ``True``.
    """
    labels: Dict[str, Any] = {}
    for tok in (raw or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" in tok:
            k, _, v = tok.partition("=")
            k, v = k.strip(), v.strip()
            if k:
                labels[k] = v
        else:
            labels[tok] = True
    return labels


def parse_tasks(raw: str) -> Tuple[str, ...]:
    """TASKS env → ordered de-duplicated op-name tuple (reference ``app.py:86-98``).

    ``*`` / ``all`` and ``none`` sentinels are preserved verbatim for the registry
    gate (reference ``ops/__init__.py:42-57``) and resolved there, not here.
    """
    seen = []
    for tok in (raw or "").split(","):
        tok = tok.strip()
        if tok and tok not in seen:
            seen.append(tok)
    return tuple(seen)


@dataclass(frozen=True)
class AgentConfig:
    """Control-plane configuration (reference ``app.py:19-44``)."""

    controller_url: str = "http://10.11.12.54:8080"
    # Controller failover list (ISSUE 14): ordered candidates the agent
    # rotates through when the active one is unreachable (transport error)
    # — how spooled results redeliver to a promoted hot standby instead of
    # waiting out a dead primary. Empty = just controller_url.
    controller_urls: Tuple[str, ...] = ()
    # Partitioned control plane (ISSUE 18): an EXPLICIT partition map
    # ("p0=http://a|http://a-standby,p1=http://b") makes the agent run the
    # router's placement/steal/result-routing logic in-process instead of
    # needing a router hop — CONTROLLER_URLS generalizes to either a
    # router URL (leave this empty) or this map. See
    # controller/partition.PartitionSession.
    controller_partition_map: str = ""
    agent_name: str = field(default_factory=socket.gethostname)
    http_timeout_sec: float = 10.0
    idle_sleep_sec: float = 0.25
    # The reference leases one task at a time ("TPU agents should usually lease 1
    # task at a time", reference app.py:30-31). We keep that default: a task is now
    # a *batched shard*, so one in-flight task saturates the mesh; raise it to
    # overlap host staging of the next shard with device compute.
    max_tasks: int = 1
    lease_timeout_ms: int = 3000
    error_log_every_sec: float = 10.0
    error_backoff_sec: float = 1.0
    tasks: Tuple[str, ...] = ("echo", "map_classify_tpu")
    labels: Dict[str, Any] = field(default_factory=dict)
    tpu_kind: str = "tpu-v5e"
    # Host-side double buffering (agent/pipeline.py): depth of the staged-task
    # queue between the stager thread and the device loop. 0 = serial loop.
    # Single-host only; multi-host lockstep broadcast stays serial.
    pipeline_depth: int = 2
    # Data plane (ISSUE 6). Staging-pool worker count: 0 = auto
    # (min(4, cpu_count)); 1 reproduces the single-stager pipeline.
    stage_workers: int = 0                    # STAGE_WORKERS
    # Autotune the staging parallelism + prefetch depth from the live
    # task_phase_seconds{phase=stage}/{phase=execute} ratio.
    stage_autotune: bool = True               # STAGE_AUTOTUNE
    # Double-buffered device feed: the next staged item's host→device
    # transfer is issued (async) before the current item executes.
    feed_double_buffer: bool = True           # FEED_DOUBLE_BUFFER
    # Advertise the compact binary shard wire (data/wire.py) in lease
    # capabilities; a controller that negotiates it gets binary-encoded
    # result columns (and may binary-encode task payloads).
    wire_binary: bool = True                  # WIRE_BINARY
    # Fault tolerance (ISSUE 3). Backoff for lease errors and result
    # redelivery: capped exponential with decorrelated jitter
    # (utils/retry.py); error_backoff_sec above is kept as the legacy name
    # for the lease-retry *base* when RETRY_BASE_SEC is unset.
    retry_base_sec: float = 0.5               # RETRY_BASE_SEC
    retry_max_sec: float = 30.0               # RETRY_MAX_SEC
    # Oldest-entry redelivery deadline for spooled results (0 = keep trying
    # until delivered or evicted by the ring bound).
    retry_deadline_sec: float = 0.0           # RETRY_DEADLINE_SEC
    # Result spool: completed results that failed to post are kept in a
    # bounded ring (and optionally a JSONL file that survives restarts)
    # and redelivered with backoff instead of dropped.
    result_spool_path: str = ""               # RESULT_SPOOL_PATH ("" = memory)
    result_spool_max: int = 512               # RESULT_SPOOL_MAX

    @staticmethod
    def from_env() -> "AgentConfig":
        urls = tuple(
            u.strip().rstrip("/")
            for u in env_str("CONTROLLER_URLS", "").split(",")
            if u.strip()
        )
        return AgentConfig(
            # The failover list's head doubles as the primary, so setting
            # CONTROLLER_URLS alone is enough; CONTROLLER_URL wins when
            # both are set (the historical contract).
            controller_url=env_str(
                "CONTROLLER_URL", urls[0] if urls else "http://10.11.12.54:8080"
            ).rstrip("/"),
            controller_urls=urls,
            controller_partition_map=env_str(
                "CONTROLLER_PARTITION_MAP", ""
            ).strip(),
            agent_name=env_str("AGENT_NAME", socket.gethostname()),
            http_timeout_sec=env_float("HTTP_TIMEOUT_SEC", 10.0),
            idle_sleep_sec=env_float("IDLE_SLEEP_SEC", 0.25),
            max_tasks=max(1, env_int("MAX_TASKS", 1)),
            lease_timeout_ms=env_int("LEASE_TIMEOUT_MS", 3000),
            error_log_every_sec=env_float("ERROR_LOG_EVERY_SEC", 10.0),
            error_backoff_sec=env_float("ERROR_BACKOFF_SEC", 1.0),
            tasks=parse_tasks(env_str("TASKS", "echo,map_classify_tpu")),
            labels=parse_labels(os.environ.get("AGENT_LABELS", "")),
            tpu_kind=env_str("TPU_KIND", "tpu-v5e"),
            pipeline_depth=max(0, env_int("PIPELINE_DEPTH", 2)),
            stage_workers=max(0, env_int("STAGE_WORKERS", 0)),
            stage_autotune=env_bool("STAGE_AUTOTUNE", True),
            feed_double_buffer=env_bool("FEED_DOUBLE_BUFFER", True),
            wire_binary=env_bool("WIRE_BINARY", True),
            retry_base_sec=env_float("RETRY_BASE_SEC", 0.5),
            retry_max_sec=env_float("RETRY_MAX_SEC", 30.0),
            retry_deadline_sec=env_float("RETRY_DEADLINE_SEC", 0.0),
            result_spool_path=env_str("RESULT_SPOOL_PATH", ""),
            result_spool_max=max(1, env_int("RESULT_SPOOL_MAX", 512)),
        )


@dataclass(frozen=True)
class DeviceConfig:
    """Device/runtime configuration (reference ``_tpu_runtime.py:29``,
    ``worker_sizing.py:195-200,226``, plus new mesh knobs)."""

    model_path: Optional[str] = None          # TPU_MODEL_PATH
    tpu_disabled: bool = False                # TPU_DISABLED kill-switch
    tpu_only: bool = False                    # TPU_ONLY scheduling mode
    platform_hint: Optional[str] = None       # JAX_PLATFORM_NAME (hint, never proof)
    tpu_name: Optional[str] = None            # TPU_NAME (hint)
    tpu_type: Optional[str] = None            # TPU_TYPE (hint)
    # New (TPU-native) knobs. MESH_SHAPE like "dp=2,tp=2,sp=2"; empty → derived
    # from topology by sizing.
    mesh_shape: Dict[str, int] = field(default_factory=dict)
    # Dtype for model compute on device; bf16 is the MXU-native choice.
    compute_dtype: str = "bfloat16"
    # Fleet-default quantized execution mode (TPU_QUANT): "" = unset (serve
    # each model config's own default), "none"/"int8"/"w8a16" otherwise.
    # The op-level precedence (payload model_config.quant > env > config
    # default) and the strict fail-the-shard validation of a bad env value
    # live in ops/_model_common.apply_quant_env; this field is the typed,
    # read-once view for telemetry (runtime.describe) and operators.
    quant: str = ""
    # Persistent XLA compilation cache directory ("" disables).
    compile_cache_dir: str = ""
    # Fused Pallas attention kernel on TPU (PALLAS_ATTN=0 falls back to the
    # XLA dot-product path; CPU/GPU always use the XLA path).
    pallas_attn: bool = True
    # Device-pinned fleets (ISSUE 7): "start:count" slice of this host's
    # visible devices the runtime may own ("" = all of them). The fleet
    # launcher (agent/fleet.py) gives each agent process a disjoint slice so
    # N single-slice agents share one host without fighting over chips; on
    # TPU hardware the launcher additionally pins visibility at the process
    # level (TPU_VISIBLE_DEVICES), making the in-process slice an identity
    # check rather than the only fence.
    chip_slice: str = ""                        # CHIP_SLICE "start:count"
    # Multi-host SPMD (jax.distributed.initialize trio); unset → single host.
    coordinator_address: Optional[str] = None   # COORDINATOR_ADDRESS host:port
    num_processes: Optional[int] = None         # NUM_PROCESSES
    process_id: Optional[int] = None            # PROCESS_ID
    # Profiling (SURVEY.md §5.1). PROFILE_DIR: capture XProf traces of the
    # first PROFILE_TASKS tasks there; PROFILE_PORT: live profiler server.
    profile_dir: str = ""                       # PROFILE_DIR ("" disables)
    profile_port: int = 0                       # PROFILE_PORT (0 disables)
    profile_tasks: int = 1                      # PROFILE_TASKS

    @staticmethod
    def from_env() -> "DeviceConfig":
        mesh: Dict[str, int] = {}
        for k, v in parse_labels(os.environ.get("MESH_SHAPE", "")).items():
            try:
                mesh[k] = int(v)
            except (TypeError, ValueError):
                pass
        # PROCESS_ID: forgiving parse like every other int env (env_int), but
        # unset/unparseable must stay None (= let jax auto-detect), not 0.
        process_id = (
            env_int("PROCESS_ID", -1) if os.environ.get("PROCESS_ID") else -1
        )
        return DeviceConfig(
            model_path=os.environ.get("TPU_MODEL_PATH") or None,
            tpu_disabled=env_bool("TPU_DISABLED", False),
            tpu_only=env_bool("TPU_ONLY", False),
            platform_hint=os.environ.get("JAX_PLATFORM_NAME") or None,
            tpu_name=os.environ.get("TPU_NAME") or None,
            tpu_type=os.environ.get("TPU_TYPE") or None,
            mesh_shape=mesh,
            compute_dtype=env_str("COMPUTE_DTYPE", "bfloat16"),
            quant=env_str("TPU_QUANT", "").strip().lower(),
            compile_cache_dir=env_str("JAX_COMPILATION_CACHE_DIR", ""),
            pallas_attn=env_bool("PALLAS_ATTN", True),
            chip_slice=env_str("CHIP_SLICE", "").strip(),
            coordinator_address=os.environ.get("COORDINATOR_ADDRESS") or None,
            num_processes=(
                env_int("NUM_PROCESSES", 0) or None
            ),
            process_id=process_id if process_id >= 0 else None,
            profile_dir=env_str("PROFILE_DIR", ""),
            profile_port=env_int("PROFILE_PORT", 0),
            profile_tasks=env_int("PROFILE_TASKS", 1),
        )


@dataclass(frozen=True)
class SizingConfig:
    """Host-sizing knobs (reference ``worker_sizing.py:44-124``)."""

    cpu_reserved_cores_floor: int = 1
    cpu_reserved_cores_cap: int = 4
    cpu_pipeline_factor: float = 4.0
    cpu_min_workers: int = 1
    cpu_soft_cap_multiplier: int = 8
    cpu_per_worker_bytes: int = 32 * 1024 * 1024

    @staticmethod
    def from_env() -> "SizingConfig":
        return SizingConfig(
            cpu_reserved_cores_floor=env_int("CPU_RESERVED_CORES_FLOOR", 1),
            cpu_reserved_cores_cap=env_int("CPU_RESERVED_CORES_CAP", 4),
            cpu_pipeline_factor=env_float("CPU_PIPELINE_FACTOR", 4.0),
            cpu_min_workers=env_int("CPU_MIN_WORKERS", 1),
            cpu_soft_cap_multiplier=env_int("CPU_SOFT_CAP_MULTIPLIER", 8),
            cpu_per_worker_bytes=env_int("CPU_PER_WORKER_BYTES", 32 * 1024 * 1024),
        )


@dataclass(frozen=True)
class JournalConfig:
    """Controller journal durability knobs (ISSUE 14 — the JOURNAL_* /
    SNAPSHOT_* env surface, consumed by ``controller/journal.py``).

    Everything defaults to the historical behavior: one append-only JSONL
    file at ``CONTROLLER_JOURNAL``, flushed but never fsynced, never
    rotated. Setting any segmentation/snapshot knob switches the journal
    to bounded ``<path>.seg-NNNNNNNN`` segments with periodic atomic
    ``<path>.snapshot`` images, after which replay cost is O(live state +
    uncovered tail) instead of O(history) and covered segments are
    garbage-collected."""

    # Rotate the active segment past this size / event count (0 = never —
    # the legacy single-file journal).
    segment_max_bytes: int = 0            # JOURNAL_SEGMENT_MAX_BYTES
    segment_max_events: int = 0           # JOURNAL_SEGMENT_MAX_EVENTS
    # Take a compacting snapshot every N journal appends (0 = never).
    # Implies segmentation (default 4 MiB segments when no bound is set).
    snapshot_every_events: int = 0        # SNAPSHOT_EVERY_EVENTS
    # Terminal-job retention in snapshots: 0 = keep every terminal job
    # forever (full restart fidelity, unbounded snapshot growth); N =
    # snapshots keep only the N most recent *droppable* terminal jobs
    # (jobs a non-terminal job depends on are never dropped). A restart
    # then forgets older completed jobs: late duplicate results for them
    # reject as `unknown job` instead of `already complete` — the same
    # at-most-once outcome — and this is what makes restart cost O(live
    # state) instead of O(every job ever submitted).
    snapshot_retain_terminal: int = 0     # SNAPSHOT_RETAIN_TERMINAL
    # fdatasync journal appends: off by default — the journal protects
    # against process death (flushed OS buffers survive SIGKILL), not
    # kernel/power loss; turning this on buys the latter at per-append
    # syscall cost. fsync_every=N batches the sync (group commit).
    fsync: bool = False                   # JOURNAL_FSYNC
    fsync_every: int = 1                  # JOURNAL_FSYNC_EVERY

    @staticmethod
    def from_env() -> "JournalConfig":
        return JournalConfig(
            segment_max_bytes=max(
                0, env_int("JOURNAL_SEGMENT_MAX_BYTES", 0)
            ),
            segment_max_events=max(
                0, env_int("JOURNAL_SEGMENT_MAX_EVENTS", 0)
            ),
            snapshot_every_events=max(
                0, env_int("SNAPSHOT_EVERY_EVENTS", 0)
            ),
            snapshot_retain_terminal=max(
                0, env_int("SNAPSHOT_RETAIN_TERMINAL", 0)
            ),
            fsync=env_bool("JOURNAL_FSYNC", False),
            fsync_every=max(1, env_int("JOURNAL_FSYNC_EVERY", 1)),
        )


@dataclass(frozen=True)
class SchedConfig:
    """Controller scheduler knobs (ISSUE 4 — the SCHED_* env surface).

    ``policy="fifo"`` (the default) is bit-compatible with the
    pre-scheduler controller: priority/tenant fields are accepted and
    recorded but dispatch order is pure arrival order and admission is
    unbounded unless a budget is set. ``policy="fair"`` enables priority
    tiers + weighted tenant fair-share + load-aware placement
    (``agent_tpu/sched/fair.py``).
    """

    policy: str = "fifo"                 # SCHED_POLICY: fifo | fair
    # Default priority for submits that don't carry one (0–9, 9 = urgent).
    default_priority: int = 4            # SCHED_DEFAULT_PRIORITY
    # Admission control: pending-queue budgets; 0 = unbounded. Submits past
    # a bound get HTTP 429 + retry_after_ms (transient per utils/retry.py).
    max_pending: int = 0                 # SCHED_MAX_PENDING (global)
    max_pending_per_tenant: int = 0      # SCHED_MAX_PENDING_PER_TENANT
    retry_after_ms: int = 1000           # SCHED_RETRY_AFTER_MS (429 hint)
    # Fair-share weights, "tenantA=3,tenantB=1" (absent tenants weigh 1).
    tenant_weights: Dict[str, float] = field(default_factory=dict)
    # Placement: how many leases a preferred-elsewhere job may be deferred
    # before any capable agent takes it (0 = placement is advisory only).
    placement_patience: int = 3          # SCHED_PLACEMENT_PATIENCE
    # Staged-queue depth beyond which an agent counts as busy: bulk shards
    # defer and grants shrink by the excess.
    busy_queue_depth: int = 2            # SCHED_BUSY_QUEUE_DEPTH
    # Deadline escalation: once this fraction of deadline_sec has elapsed a
    # still-pending job is bumped one priority tier (once).
    escalate_frac: float = 0.75          # SCHED_ESCALATE_FRAC

    @staticmethod
    def from_env() -> "SchedConfig":
        weights: Dict[str, float] = {}
        for k, v in parse_labels(
            os.environ.get("SCHED_TENANT_WEIGHTS", "")
        ).items():
            try:
                weights[k] = float(v)
            except (TypeError, ValueError):
                pass
        return SchedConfig(
            policy=env_str("SCHED_POLICY", "fifo").strip().lower(),
            default_priority=min(
                9, max(0, env_int("SCHED_DEFAULT_PRIORITY", 4))
            ),
            max_pending=max(0, env_int("SCHED_MAX_PENDING", 0)),
            max_pending_per_tenant=max(
                0, env_int("SCHED_MAX_PENDING_PER_TENANT", 0)
            ),
            retry_after_ms=max(0, env_int("SCHED_RETRY_AFTER_MS", 1000)),
            tenant_weights=weights,
            placement_patience=max(0, env_int("SCHED_PLACEMENT_PATIENCE", 3)),
            busy_queue_depth=max(0, env_int("SCHED_BUSY_QUEUE_DEPTH", 2)),
            escalate_frac=min(
                1.0, max(0.0, env_float("SCHED_ESCALATE_FRAC", 0.75))
            ),
        )


@dataclass(frozen=True)
class SloConfig:
    """Fleet health / SLO engine knobs (ISSUE 8 — the SLO_* env surface).

    ``enabled=False`` (``SLO_ENABLED=0``) no-ops the whole judgment path:
    no tracker is built, ``observe`` never runs, and ``GET /v1/health``
    reports ``slo.enabled: false`` while still serving the fleet/queue
    signals. ``spec`` is the declarative objective list
    (``SLO_SPEC='[{"tier":8,"p99_ms":250,"availability":0.999}]'``; empty
    = the built-in interactive-tier default, see ``obs/slo.py``).
    """

    enabled: bool = True                  # SLO_ENABLED
    spec: str = ""                        # SLO_SPEC (JSON; "" = default)
    # Google-SRE multi-window burn-rate alerting: the short window catches
    # fast burns, the long window stops one bad minute from paging.
    window_short_sec: float = 300.0       # SLO_WINDOW_SHORT_SEC
    window_long_sec: float = 3600.0       # SLO_WINDOW_LONG_SEC
    burn_warn: float = 3.0                # SLO_BURN_WARN (enter `warn`)
    burn_page: float = 10.0               # SLO_BURN_PAGE (enter `page`)
    # Hysteresis: a level exits only once the short-window burn falls below
    # enter_threshold * this fraction — oscillation around the line holds.
    burn_exit_frac: float = 0.5           # SLO_BURN_EXIT_FRAC
    # Agents silent longer than this count stale in the /v1/health verdict.
    agent_stale_sec: float = 60.0         # HEALTH_AGENT_STALE_SEC

    @staticmethod
    def from_env() -> "SloConfig":
        short = max(0.1, env_float("SLO_WINDOW_SHORT_SEC", 300.0))
        return SloConfig(
            enabled=env_bool("SLO_ENABLED", True),
            spec=env_str("SLO_SPEC", ""),
            window_short_sec=short,
            window_long_sec=max(
                short, env_float("SLO_WINDOW_LONG_SEC", 3600.0)
            ),
            burn_warn=max(0.0, env_float("SLO_BURN_WARN", 3.0)),
            burn_page=max(0.0, env_float("SLO_BURN_PAGE", 10.0)),
            burn_exit_frac=min(
                1.0, max(0.0, env_float("SLO_BURN_EXIT_FRAC", 0.5))
            ),
            agent_stale_sec=max(
                1.0, env_float("HEALTH_AGENT_STALE_SEC", 60.0)
            ),
        )


@dataclass(frozen=True)
class ObsConfig:
    """Resource accounting, trend retention & continuous profiling knobs
    (ISSUE 9 — the USAGE_* / TSDB_* / PROFILE_* env surface).

    Everything defaults ON with bounded memory: the ledger is a small
    aggregate map + a capped per-job table, the time-series ring holds
    ``window/interval`` flattened samples, and the host profiler starts
    LAZILY on the first ``GET /v1/profile/host`` (a controller that is never
    asked for a flamegraph never spawns the sampler thread)."""

    # Usage accounting (GET /v1/usage): per-{tenant,tier,op} + per-job
    # billing of accepted result applications.
    usage_enabled: bool = True             # USAGE_ENABLED
    usage_top_k: int = 10                  # USAGE_TOP_K (top jobs in report)
    usage_max_jobs: int = 4096             # USAGE_MAX_JOBS (per-job table cap)
    # $/chip-hour for the report's est_cost lines; 0 = no cost estimate.
    usage_cost_per_chip_hour: float = 0.0  # USAGE_COST_PER_CHIP_HOUR
    # Controller time-series ring (GET /v1/timeseries): periodic registry
    # snapshots spanning TSDB_WINDOW at TSDB_INTERVAL cadence.
    tsdb_enabled: bool = True              # TSDB_ENABLED
    tsdb_window_sec: float = 900.0         # TSDB_WINDOW
    tsdb_interval_sec: float = 10.0        # TSDB_INTERVAL
    # Durable on-disk store (ISSUE 20): "" keeps the ring in-memory only;
    # a directory persists every sample with tiered downsampling.
    tsdb_dir: str = ""                     # TSDB_DIR
    tsdb_segment_bytes: int = 1 << 20      # TSDB_SEGMENT_BYTES
    tsdb_retention_raw_sec: float = 3600.0      # TSDB_RETENTION_RAW_SEC
    tsdb_retention_1m_sec: float = 86400.0      # TSDB_RETENTION_1M_SEC
    tsdb_retention_10m_sec: float = 604800.0    # TSDB_RETENTION_10M_SEC
    tsdb_max_bytes: int = 256 << 20        # TSDB_MAX_BYTES (0 = uncapped)
    # Rolling-baseline anomaly detection over the sample stream.
    anomaly_enabled: bool = True           # ANOMALY_ENABLED
    anomaly_window: int = 60               # ANOMALY_WINDOW (baseline n)
    anomaly_warmup: int = 12               # ANOMALY_WARMUP (gate)
    anomaly_z: float = 8.0                 # ANOMALY_Z (MAD z threshold)
    anomaly_confirm: int = 2               # ANOMALY_CONFIRM (consecutive)
    anomaly_clear: int = 5                 # ANOMALY_CLEAR (episode close)
    # Incident forensics bundles (GET /v1/incidents).
    incident_enabled: bool = True          # INCIDENT_ENABLED
    incident_dir: str = ""                 # INCIDENT_DIR ("" = memory only)
    incident_capacity: int = 32            # INCIDENT_CAPACITY
    incident_min_interval_sec: float = 60.0  # INCIDENT_MIN_INTERVAL_SEC
    incident_worst_k: int = 3              # INCIDENT_WORST_K (traces kept)
    # Host sampling profiler (GET /v1/profile/host): collapsed-stack
    # flamegraph of the controller process, lazily started.
    profile_host_enabled: bool = True      # PROFILE_HOST_ENABLED
    profile_host_hz: float = 19.0          # PROFILE_HOST_HZ
    # Where agents write on-demand jax.profiler capture artifacts
    # ("" = a per-capture tempdir).
    profile_capture_dir: str = ""          # PROFILE_CAPTURE_DIR

    @staticmethod
    def from_env() -> "ObsConfig":
        interval = max(0.05, env_float("TSDB_INTERVAL", 10.0))
        return ObsConfig(
            usage_enabled=env_bool("USAGE_ENABLED", True),
            usage_top_k=max(1, env_int("USAGE_TOP_K", 10)),
            usage_max_jobs=max(16, env_int("USAGE_MAX_JOBS", 4096)),
            usage_cost_per_chip_hour=max(
                0.0, env_float("USAGE_COST_PER_CHIP_HOUR", 0.0)
            ),
            tsdb_enabled=env_bool("TSDB_ENABLED", True),
            tsdb_window_sec=max(
                interval, env_float("TSDB_WINDOW", 900.0)
            ),
            tsdb_interval_sec=interval,
            tsdb_dir=env_str("TSDB_DIR", "").strip(),
            tsdb_segment_bytes=max(
                4096, env_int("TSDB_SEGMENT_BYTES", 1 << 20)
            ),
            tsdb_retention_raw_sec=max(
                0.0, env_float("TSDB_RETENTION_RAW_SEC", 3600.0)
            ),
            tsdb_retention_1m_sec=max(
                0.0, env_float("TSDB_RETENTION_1M_SEC", 86400.0)
            ),
            tsdb_retention_10m_sec=max(
                0.0, env_float("TSDB_RETENTION_10M_SEC", 604800.0)
            ),
            tsdb_max_bytes=max(0, env_int("TSDB_MAX_BYTES", 256 << 20)),
            anomaly_enabled=env_bool("ANOMALY_ENABLED", True),
            anomaly_window=max(4, env_int("ANOMALY_WINDOW", 60)),
            anomaly_warmup=max(2, env_int("ANOMALY_WARMUP", 12)),
            anomaly_z=max(1.0, env_float("ANOMALY_Z", 8.0)),
            anomaly_confirm=max(1, env_int("ANOMALY_CONFIRM", 2)),
            anomaly_clear=max(1, env_int("ANOMALY_CLEAR", 5)),
            incident_enabled=env_bool("INCIDENT_ENABLED", True),
            incident_dir=env_str("INCIDENT_DIR", "").strip(),
            incident_capacity=max(1, env_int("INCIDENT_CAPACITY", 32)),
            incident_min_interval_sec=max(
                0.0, env_float("INCIDENT_MIN_INTERVAL_SEC", 60.0)
            ),
            incident_worst_k=max(0, env_int("INCIDENT_WORST_K", 3)),
            profile_host_enabled=env_bool("PROFILE_HOST_ENABLED", True),
            profile_host_hz=max(0.1, env_float("PROFILE_HOST_HZ", 19.0)),
            profile_capture_dir=env_str("PROFILE_CAPTURE_DIR", "").strip(),
        )


@dataclass(frozen=True)
class LoadgenConfig:
    """Open-loop traffic generator knobs (ISSUE 10 — the LOADGEN_* env
    surface, consumed by ``agent_tpu/loadgen.py``).

    Arrivals follow a seeded non-homogeneous Poisson process:
    ``rate(t) = base_rate · (1 + diurnal_amplitude·sin(2πt/period)) ·
    burst_factor(t)`` — the diurnal term models the day/night swing of a
    planet-scale user base, the burst window the 10× thundering herd the
    autoscaler must absorb. The same seed always produces the same
    arrival schedule (open loop: arrivals never wait on completions)."""

    seed: int = 0                          # LOADGEN_SEED
    base_rate: float = 2.0                 # LOADGEN_RATE (jobs/sec)
    duration_sec: float = 30.0             # LOADGEN_DURATION_SEC
    # One burst window: rate multiplies by burst_factor inside
    # [burst_at_sec, burst_at_sec + burst_len_sec). factor 1 / len 0 = off.
    burst_factor: float = 10.0             # LOADGEN_BURST_FACTOR
    burst_at_sec: float = 0.0              # LOADGEN_BURST_AT_SEC
    burst_len_sec: float = 0.0             # LOADGEN_BURST_LEN_SEC
    # Sinusoidal diurnal modulation (0 = flat; 1 = full swing to zero).
    diurnal_amplitude: float = 0.0         # LOADGEN_DIURNAL_AMPLITUDE
    diurnal_period_sec: float = 86400.0    # LOADGEN_DIURNAL_PERIOD_SEC

    @staticmethod
    def from_env() -> "LoadgenConfig":
        return LoadgenConfig(
            seed=env_int("LOADGEN_SEED", 0),
            base_rate=max(0.0, env_float("LOADGEN_RATE", 2.0)),
            duration_sec=max(0.0, env_float("LOADGEN_DURATION_SEC", 30.0)),
            burst_factor=max(0.0, env_float("LOADGEN_BURST_FACTOR", 10.0)),
            burst_at_sec=max(0.0, env_float("LOADGEN_BURST_AT_SEC", 0.0)),
            burst_len_sec=max(0.0, env_float("LOADGEN_BURST_LEN_SEC", 0.0)),
            diurnal_amplitude=min(
                1.0, max(0.0, env_float("LOADGEN_DIURNAL_AMPLITUDE", 0.0))
            ),
            diurnal_period_sec=max(
                1e-3, env_float("LOADGEN_DIURNAL_PERIOD_SEC", 86400.0)
            ),
        )


@dataclass(frozen=True)
class AutoscaleConfig:
    """Elastic-fleet control loop knobs (ISSUE 10 — the AUTOSCALE_* env
    surface, consumed by ``agent_tpu/autoscale.py``).

    The loop scales up on queue pressure / SLO burn / starvation and down
    only after ``down_idle_evals`` consecutive idle judgments, with
    separate up/down cooldowns so a noisy signal cannot flap the fleet."""

    min_agents: int = 1                    # AUTOSCALE_MIN
    max_agents: int = 4                    # AUTOSCALE_MAX
    interval_sec: float = 2.0              # AUTOSCALE_INTERVAL_SEC
    # Scale up when queued jobs per live agent exceed this...
    up_queue_per_agent: float = 4.0        # AUTOSCALE_UP_QUEUE_PER_AGENT
    # ...or the oldest queued job has waited longer than this.
    up_starvation_sec: float = 10.0        # AUTOSCALE_UP_STARVATION_SEC
    # Members added per scale-up decision (capacity replacement after a
    # reclaim is separate and always allowed up to `max_agents`).
    step_up: int = 2                       # AUTOSCALE_STEP_UP
    step_down: int = 1                     # AUTOSCALE_STEP_DOWN
    # Scale down only after this many consecutive idle evaluations
    # (queue empty AND every live agent's duty cycle below down_max_duty).
    down_idle_evals: int = 3               # AUTOSCALE_DOWN_IDLE_EVALS
    down_max_duty: float = 0.10            # AUTOSCALE_DOWN_MAX_DUTY
    # Hysteresis: no scale-up within up_cooldown of the last scale-up; no
    # scale-down within down_cooldown of the last scale event either way.
    up_cooldown_sec: float = 5.0           # AUTOSCALE_UP_COOLDOWN_SEC
    down_cooldown_sec: float = 10.0        # AUTOSCALE_DOWN_COOLDOWN_SEC

    @staticmethod
    def from_env() -> "AutoscaleConfig":
        min_agents = max(0, env_int("AUTOSCALE_MIN", 1))
        return AutoscaleConfig(
            min_agents=min_agents,
            max_agents=max(min_agents, env_int("AUTOSCALE_MAX", 4)),
            interval_sec=max(0.05, env_float("AUTOSCALE_INTERVAL_SEC", 2.0)),
            up_queue_per_agent=max(
                0.1, env_float("AUTOSCALE_UP_QUEUE_PER_AGENT", 4.0)
            ),
            up_starvation_sec=max(
                0.1, env_float("AUTOSCALE_UP_STARVATION_SEC", 10.0)
            ),
            step_up=max(1, env_int("AUTOSCALE_STEP_UP", 2)),
            step_down=max(1, env_int("AUTOSCALE_STEP_DOWN", 1)),
            down_idle_evals=max(1, env_int("AUTOSCALE_DOWN_IDLE_EVALS", 3)),
            down_max_duty=min(
                1.0, max(0.0, env_float("AUTOSCALE_DOWN_MAX_DUTY", 0.10))
            ),
            up_cooldown_sec=max(
                0.0, env_float("AUTOSCALE_UP_COOLDOWN_SEC", 5.0)
            ),
            down_cooldown_sec=max(
                0.0, env_float("AUTOSCALE_DOWN_COOLDOWN_SEC", 10.0)
            ),
        )


@dataclass(frozen=True)
class ServeConfig:
    """Online-serving front door knobs (ISSUE 15 — the SERVE_* env surface).

    ``POST /v1/infer`` requests coalesce into length-bucketed batches under
    a ``max_wait_ms`` deadline / ``max_batch`` cap at the controller, then
    ride the ordinary job queue as interactive-tier jobs; agent-side, the
    continuous-batching decode engine runs ``decode_slots`` requests ×
    ``num_beams`` beam rows as its fixed-capacity running batch."""

    enabled: bool = True                   # SERVE_ENABLED
    # Batch coalescing: a bucket flushes the moment it holds max_batch
    # requests, or when its oldest request has waited max_wait_ms.
    max_wait_ms: float = 25.0              # SERVE_MAX_WAIT_MS
    max_batch: int = 16                    # SERVE_MAX_BATCH
    # Admission: queued-or-batched infer requests past this bound get the
    # existing 429 + retry_after_ms backpressure answer (0 = unbounded).
    max_pending: int = 1024                # SERVE_MAX_PENDING
    # Interactive-tier priority the flushed batch jobs carry (the fair
    # scheduler's tier lane; the default SLO objectives judge tier 8).
    priority: int = 8                      # SERVE_PRIORITY
    # Length buckets (input bytes) — padding waste per batch is bounded by
    # the gap to the next bucket edge.
    len_buckets: Tuple[int, ...] = (64, 128, 256, 512, 1024)
    # Agent-side: running-batch capacity (requests) of the continuous
    # decode engine.
    decode_slots: int = 8                  # SERVE_DECODE_SLOTS
    # Decode iterations fused per engine dispatch: 1 = pure iteration-level
    # batching (membership may change between every step); >1 amortizes
    # per-step dispatch overhead where it dominates (tiny models, CPU,
    # tunneled chips) — joins/exits then happen between chunks.
    decode_micro_steps: int = 1            # SERVE_MICRO_STEPS
    # HTTP long-poll cap for blocking POST /v1/infer / ?wait_ms GETs.
    wait_timeout_sec: float = 60.0         # SERVE_WAIT_TIMEOUT_SEC
    # ---- decode-path raw speed (ISSUE 16) ----
    # KV layout of the continuous decode engine: "paged" allocates
    # fixed-size KV blocks from a shared pool per layer (block table per
    # slot row), so resident HBM scales with live tokens instead of
    # slots × max_tgt_len; "dense" keeps the per-slot full-length
    # reservation (the bit-identical equivalence reference).
    kv_layout: str = "paged"               # SERVE_KV_LAYOUT
    kv_block_size: int = 16                # KV_BLOCK_SIZE (tokens per block)
    # Pool size in blocks per decoder layer; 0 = auto (dense parity:
    # rows × blocks-per-row + trash — never stalls admission). Shrink to
    # trade admission headroom for HBM.
    kv_pool_blocks: int = 0                # KV_POOL_BLOCKS
    # Content-hashed prefix cache: repeated prompts skip prefill entirely.
    prefix_cache_enabled: bool = True      # PREFIX_CACHE_ENABLED
    prefix_cache_entries: int = 512        # PREFIX_CACHE_ENTRIES
    prefix_cache_mb: float = 256.0         # PREFIX_CACHE_MB
    # Disaggregated serving pools: serve_summarize batches split into a
    # serve_prefill job (encode, b1 binary KV/encoded handoff) dep-gated
    # into a serve_decode job — prefill-heavy work steers away from decode
    # agents so bulk prefills can't stall the running batch.
    disaggregated: bool = False            # SERVE_DISAGG
    # ---- wide-event request log (ISSUE 17) ----
    # Tail-based sampling of the per-request record ring: errors and the
    # slowest-TTFT decile are ALWAYS kept; the healthy/fast remainder is
    # kept with this probability (1.0 = keep everything, 0.0 = tail only).
    reqlog_sample: float = 1.0             # SERVE_REQLOG_SAMPLE
    # Bounded record ring capacity (memory is O(capacity), not O(requests)).
    reqlog_capacity: int = 2048            # SERVE_REQLOG_CAPACITY

    @staticmethod
    def from_env() -> "ServeConfig":
        buckets = []
        for tok in env_str("SERVE_LEN_BUCKETS", "").split(","):
            tok = tok.strip()
            if tok:
                try:
                    buckets.append(int(tok))
                except ValueError:
                    pass
        buckets = tuple(sorted(b for b in buckets if b > 0))
        return ServeConfig(
            enabled=env_bool("SERVE_ENABLED", True),
            max_wait_ms=max(0.0, env_float("SERVE_MAX_WAIT_MS", 25.0)),
            max_batch=max(1, env_int("SERVE_MAX_BATCH", 16)),
            max_pending=max(0, env_int("SERVE_MAX_PENDING", 1024)),
            priority=min(9, max(0, env_int("SERVE_PRIORITY", 8))),
            len_buckets=buckets or ServeConfig.len_buckets,
            decode_slots=max(1, env_int("SERVE_DECODE_SLOTS", 8)),
            decode_micro_steps=max(1, env_int("SERVE_MICRO_STEPS", 1)),
            wait_timeout_sec=max(
                0.1, env_float("SERVE_WAIT_TIMEOUT_SEC", 60.0)
            ),
            kv_layout=(
                "dense"
                if env_str("SERVE_KV_LAYOUT", "paged").strip().lower()
                == "dense" else "paged"
            ),
            kv_block_size=max(1, env_int("KV_BLOCK_SIZE", 16)),
            kv_pool_blocks=max(0, env_int("KV_POOL_BLOCKS", 0)),
            prefix_cache_enabled=env_bool("PREFIX_CACHE_ENABLED", True),
            prefix_cache_entries=max(
                0, env_int("PREFIX_CACHE_ENTRIES", 512)
            ),
            prefix_cache_mb=max(0.0, env_float("PREFIX_CACHE_MB", 256.0)),
            disaggregated=env_bool("SERVE_DISAGG", False),
            reqlog_sample=min(
                1.0, max(0.0, env_float("SERVE_REQLOG_SAMPLE", 1.0))
            ),
            reqlog_capacity=max(1, env_int("SERVE_REQLOG_CAPACITY", 2048)),
        )


@dataclass(frozen=True)
class OpsConfig:
    """Per-op knobs (reference ``ops/map_summarize.py:9-10``, trigger envs)."""

    summarize_model: str = "t5-small-swarm"   # BART_MODEL slot in the reference
    # Deliberate inversion of the reference default (ref :10 was CPU-on):
    # BASELINE.json's north star is zero CPU-side model execution, so the
    # kill-switch defaults OFF. The op reads this field (through ctx.config
    # or OpsConfig.from_env), so this is the single source of the default.
    summarize_force_cpu: bool = False         # SUMMARIZE_FORCE_CPU
    sap_host: Optional[str] = None
    sap_user: Optional[str] = None
    sap_pass: Optional[str] = None
    oracle_host: Optional[str] = None
    oracle_user: Optional[str] = None
    oracle_pass: Optional[str] = None

    @staticmethod
    def from_env() -> "OpsConfig":
        return OpsConfig(
            summarize_model=env_str("BART_MODEL", "t5-small-swarm"),
            summarize_force_cpu=env_bool("SUMMARIZE_FORCE_CPU", False),
            sap_host=os.environ.get("SAP_HOST") or None,
            sap_user=os.environ.get("SAP_USER") or None,
            sap_pass=os.environ.get("SAP_PASS") or None,
            oracle_host=os.environ.get("ORACLE_HOST") or None,
            oracle_user=os.environ.get("ORA_USER") or None,
            oracle_pass=os.environ.get("ORA_PASS") or None,
        )


@dataclass(frozen=True)
class PartitionConfig:
    """Partitioned control plane knobs (ISSUE 18 — PARTITIONS/ROUTER_*).

    The router process (``python -m agent_tpu.controller.router``) fronts
    either an EXISTING fleet of partition controllers (``partition_urls``
    names them, ``|``-separated alternates per partition for the hot
    standby's slot) or, when only ``partitions`` is set, N in-process
    partitions it boots itself — the single-host convenience mode.
    The steal decision's own knobs (STEAL_ENABLED / STEAL_MIN_ADVANTAGE)
    live with the policy in ``sched/steal.py``.
    """

    partitions: int = 0                   # PARTITIONS (0 = unpartitioned)
    partition_urls: str = ""              # PARTITION_URLS ("p0=url|alt,p1=url")
    router_host: str = "0.0.0.0"          # ROUTER_HOST
    router_port: int = 8800               # ROUTER_PORT
    # Steal-probe depth sample TTL: how stale the per-partition leasable
    # depths the router steals against may be.
    depth_cache_sec: float = 0.25         # ROUTER_DEPTH_CACHE_SEC
    # Per-proxied-request upstream timeout.
    timeout_sec: float = 30.0             # ROUTER_TIMEOUT_SEC

    @staticmethod
    def from_env() -> "PartitionConfig":
        return PartitionConfig(
            partitions=max(0, env_int("PARTITIONS", 0)),
            partition_urls=env_str("PARTITION_URLS", "").strip(),
            router_host=env_str("ROUTER_HOST", "0.0.0.0"),
            router_port=env_int("ROUTER_PORT", 8800),
            depth_cache_sec=max(
                0.0, env_float("ROUTER_DEPTH_CACHE_SEC", 0.25)
            ),
            timeout_sec=max(0.1, env_float("ROUTER_TIMEOUT_SEC", 30.0)),
        )


@dataclass(frozen=True)
class FlowConfig:
    """Workflow DAG engine + result cache knobs (ISSUE 19 — FLOW_*/CACHE_*).

    The DAG limits bound what one ``POST /v1/workflows`` may expand into
    (stages x fan-out, before admission control sees the jobs); the cache
    knobs size the content-addressed result cache and pin the model
    version that fences its key space (bump => invalidate)."""

    enabled: bool = True                  # FLOW_ENABLED
    max_stages: int = 32                  # FLOW_MAX_STAGES
    max_width: int = 64                   # FLOW_MAX_WIDTH
    cache_enabled: bool = True            # CACHE_ENABLED
    cache_capacity: int = 4096            # CACHE_CAPACITY (entries; 0 = off)
    cache_model_version: str = "v1"       # CACHE_MODEL_VERSION
    # Billed est-cost per cache hit in the usage ledger — the "cache price"
    # a deduped result charges instead of chip-seconds.
    cache_price_per_hit: float = 0.0      # CACHE_PRICE_PER_HIT

    @staticmethod
    def from_env() -> "FlowConfig":
        return FlowConfig(
            enabled=env_bool("FLOW_ENABLED", True),
            max_stages=max(1, env_int("FLOW_MAX_STAGES", 32)),
            max_width=max(1, env_int("FLOW_MAX_WIDTH", 64)),
            cache_enabled=env_bool("CACHE_ENABLED", True),
            cache_capacity=max(0, env_int("CACHE_CAPACITY", 4096)),
            cache_model_version=env_str("CACHE_MODEL_VERSION", "v1"),
            cache_price_per_hit=max(
                0.0, env_float("CACHE_PRICE_PER_HIT", 0.0)
            ),
        )


@dataclass(frozen=True)
class Config:
    """Aggregate, built once at process start and passed down explicitly."""

    agent: AgentConfig = field(default_factory=AgentConfig)
    device: DeviceConfig = field(default_factory=DeviceConfig)
    sizing: SizingConfig = field(default_factory=SizingConfig)
    ops: OpsConfig = field(default_factory=OpsConfig)
    sched: SchedConfig = field(default_factory=SchedConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    partition: PartitionConfig = field(default_factory=PartitionConfig)
    flow: FlowConfig = field(default_factory=FlowConfig)

    @staticmethod
    def from_env() -> "Config":
        return Config(
            agent=AgentConfig.from_env(),
            device=DeviceConfig.from_env(),
            sizing=SizingConfig.from_env(),
            ops=OpsConfig.from_env(),
            sched=SchedConfig.from_env(),
            serve=ServeConfig.from_env(),
            partition=PartitionConfig.from_env(),
            flow=FlowConfig.from_env(),
        )
