"""agent_tpu — a TPU-native distributed job-swarm framework.

A ground-up rebuild of the capabilities of the reference worker agent
(``distributed-swarm/agent-tpu``): a lease-driven swarm agent that executes
named ops against a controller's ``/v1/leases`` + ``/v1/results`` protocol —
re-founded on JAX/XLA over a TPU device mesh instead of a one-row-at-a-time
host loop around an Edge TPU interpreter.

Layering (bottom-up; see SURVEY.md §7 for the design rationale):

- ``agent_tpu.runtime``    device manager, mesh construction, compiled-op cache
  (successor of reference ``ops/_tpu_runtime.py``).
- ``agent_tpu.sizing``     topology-derived batching/sharding + worker profile
  (successor of reference ``worker_sizing.py``).
- ``agent_tpu.parallel``   sharding specs, collectives, ring attention, pipeline.
- ``agent_tpu.models``     tokenizers and pure-JAX model families (encoder,
  seq2seq, HF BERT/BART/T5 imports) with shared decode engines.
- ``agent_tpu.data``       byte-offset CSV sharding + double-buffered prefetch
  (successor of reference ``ops/csv_shard.py`` skip-scan reader).
- ``agent_tpu.ops``        the op registry and the op set (successor of reference
  ``ops/__init__.py`` + ``ops_loader.py`` with its wiring gaps fixed).
- ``agent_tpu.agent``      the lease→execute→report loop (successor of ``app.py``).
- ``agent_tpu.controller`` in-repo controller speaking the same wire protocol
  (not present in the reference; required for a self-contained framework).

This module deliberately imports nothing heavy: importing ``agent_tpu`` must not
initialize JAX (the reference boots without pycoral for the same reason,
reference ``ops/_tpu_runtime.py:45-46``).
"""

__version__ = "0.1.0"

__all__ = ["__version__"]
