"""Flight recorder — a bounded ring buffer of recent structured events.

The diagnosable-after-the-fact channel (ISSUE 2 tentpole 4): the agent and
the controller each keep the last ``capacity`` events (leases, phase
transitions, epoch fences, errors) in memory — O(capacity), NOT O(tasks),
so a 10M-row drain costs the same RAM as a 10-row one — and dump them as
JSONL:

- on demand: ``SIGUSR1`` in the agent (``install_sigusr1_dump``),
  ``GET /v1/debug/events`` on the controller;
- on fatal errors: the agent's ``main()`` dumps before re-raising, so a
  wedged or crashed drain leaves its last moves on disk without re-running
  it under extra logging.

Events carry the task trace fields (``job_id``, ``lease_id``, ``attempt``)
stamped at lease time, so one job's life greps across the controller
journal, agent logs, and both recorders.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

DEFAULT_CAPACITY = 2048

# Process-wide event sequence (ISSUE 5 satellite): every recorder instance
# draws from ONE counter, so controller and agent rings in the same process
# interleave deterministically by `seq` (cross-process dumps interleave on
# the `ts`/`mono` pair, with `seq` breaking same-process ties).
_global_seq = itertools.count(1)


class FlightRecorder:
    """Thread-safe bounded event ring. ``record`` is called on hot paths —
    it must never raise and never grow beyond ``capacity``."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        clock=time.time,
    ) -> None:
        self.capacity = max(1, int(capacity))
        self._clock = clock
        self._lock = threading.Lock()
        self._events: "collections.deque" = collections.deque(
            maxlen=self.capacity
        )
        self._dropped = 0  # events pushed out of the ring

    def record(self, kind: str, **fields: Any) -> None:
        # `ts` (wall, or the injected clock) + `mono` + process-global `seq`
        # let controller and agent dumps interleave deterministically
        # (ISSUE 5 satellite): sort on (ts, seq) across files.
        event = {
            "ts": self._clock(),
            "mono": time.monotonic(),
            "seq": next(_global_seq),
            "kind": kind,
        }
        event.update(fields)
        with self._lock:
            if len(self._events) == self.capacity:
                self._dropped += 1
            self._events.append(event)

    def events(
        self,
        job_id: Optional[str] = None,
        req_id: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        """All buffered events, optionally filtered to one job's life or
        one serving request's (the ``GET /v1/debug/events?job_id=`` /
        ``?req_id=`` surfaces). Both filters AND together."""
        with self._lock:
            out = list(self._events)
        if job_id is not None:
            out = [e for e in out if e.get("job_id") == job_id]
        if req_id is not None:
            out = [e for e in out if e.get("req_id") == req_id]
        return out

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def dump(self, path: str) -> int:
        """Write the ring as JSONL (oldest first); returns events written.
        Non-JSON field values stringify (``default=str``) — a dump must
        never fail on an exotic payload."""
        events = self.events()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            for ev in events:
                f.write(json.dumps(ev, default=str) + "\n")
        os.replace(tmp, path)
        return len(events)


# ---- process-global default (injectable instances preferred in tests) ----

_default_recorder = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _default_recorder


def default_dump_path(tag: str) -> str:
    """Where on-demand/fatal dumps land: ``$FLIGHT_RECORDER_DIR`` or the
    system temp dir, one file per tag+pid (restarts never clobber a prior
    incarnation's post-mortem)."""
    base = os.environ.get("FLIGHT_RECORDER_DIR") or tempfile.gettempdir()
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in tag)
    return os.path.join(base, f"agent_tpu_flight_{safe}_{os.getpid()}.jsonl")


def install_sigusr1_dump(
    recorder: FlightRecorder, path: str
) -> Optional[str]:
    """Arm ``SIGUSR1`` → dump ``recorder`` to ``path``. Returns the path, or
    None where unsupported (non-main thread, platforms without SIGUSR1) —
    callers treat that as a soft degrade, not an error."""
    import signal

    if not hasattr(signal, "SIGUSR1"):
        return None

    def _dump(*_args: Any) -> None:
        try:
            n = recorder.dump(path)
            print(
                f"[agent-tpu] flight recorder dumped {n} events to {path}",
                flush=True,
            )
        except OSError:
            pass  # a failing dump must not kill the drain

    try:
        signal.signal(signal.SIGUSR1, _dump)
    except ValueError:  # not the main thread
        return None
    return path
