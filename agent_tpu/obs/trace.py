"""End-to-end distributed tracing — causal spans from submit to result-apply.

PR 2 gave the swarm aggregate metrics and a flight recorder; this module
(ISSUE 5 tentpole) assembles the ``trace={job_id, attempt, lease_id}`` tags
those layers already stamp into a *causal timeline*: one span tree per job,
``trace_id = job_id``, covering controller ``submit`` (the root), scheduler
decisions, the lease window, the agent-side ``stage``/``queue``/``execute``/
``post`` phases (the PipelineRunner's existing wall-clock measurements,
converted to spans instead of re-clocked), XLA compile cost
(``xla.compile`` spans emitted by the executor's compile cache on every
miss), spool redeliveries, and controller ``apply``.

Dependency-free by the same rule as ``obs.metrics``: stdlib only.

Shapes:

- **Span** — ``trace_id``/``span_id``/``parent_span_id`` plus a
  monotonic-start + duration pair for exact intra-process math and a
  wall-clock anchor (``start_wall``) for cross-process ordering. The wire
  format is the plain dict (``Span.to_wire`` / any dict with the same keys).
  A span may additionally carry ``links`` — causal references to spans in
  *other* traces (ISSUE 17: a coalesced serving batch job links back to
  each rider request's trace). Links never replace the single parent; the
  key is emitted only when non-empty, so legacy span bytes are unchanged
  when no links exist.
- **SpanBuffer** — the per-process bounded ring agents record into
  (O(capacity) like the flight recorder). ``drain()`` pops everything
  pending so the agent can piggyback spans onto ``POST /v1/results`` and
  the metrics-only flush lease the same way metric snapshots ship;
  ``requeue`` puts them back when the post fails.
- **TraceContext** (a contextvar) — the ambient ``(trace_id,
  parent_span_id, tracer, registry)`` the agent sets around op execution so
  deep layers (the executor's compile cache) can attribute their spans to
  the task that triggered them without plumbing arguments through jax.
- **TraceStore** — the controller-side assembly point: bounded per-trace
  span maps (dedup by ``span_id``, so redelivered piggybacks are
  idempotent), ``assemble()`` returning sorted spans with orphans flagged.
- **Exporters** — Chrome-trace/Perfetto JSON (``to_chrome_trace`` +
  ``validate_chrome_trace``) and JSONL round-trip.

``TRACE_ENABLED=0`` short-circuits every record path to a no-op (ISSUE 5
satellite): ``SpanBuffer.add``/``TraceStore.open`` return immediately, so a
tracing-off drain pays only the env check.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import (
    Any,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

from agent_tpu.config import TRUTHY_TOKENS

DEFAULT_BUFFER_CAPACITY = 4096
DEFAULT_MAX_TRACES = 512
DEFAULT_MAX_SPANS_PER_TRACE = 1024

# ---- global enable switch (TRACE_ENABLED, default on) ----

_forced_enabled: Optional[bool] = None
_env_enabled: Optional[bool] = None  # memoized env read (hot path)


def set_enabled(value: Optional[bool]) -> None:
    """Override the TRACE_ENABLED env check (tests); ``None`` restores it
    (and re-reads the env on the next :func:`enabled` call)."""
    global _forced_enabled, _env_enabled
    _forced_enabled = value
    _env_enabled = None


def enabled() -> bool:
    if _forced_enabled is not None:
        return _forced_enabled
    # enabled() runs several times per task; memoize the env read (an
    # os.environ hit per call is measurable). set_enabled(None) re-arms it.
    global _env_enabled
    if _env_enabled is None:
        v = os.environ.get("TRACE_ENABLED")
        _env_enabled = (
            True if v is None or v == ""
            else v.strip().lower() in TRUTHY_TOKENS
        )
    return _env_enabled


def new_span_id() -> str:
    # os.urandom is ~5x cheaper than uuid4 and this runs several times per
    # task on the drain hot path; 64 random bits is the OTel span-id width.
    return os.urandom(8).hex()


# ---- the span model ----

@dataclass
class Span:
    """One timed operation. ``start_mono``/``duration_ms`` are the exact
    measurement (monotonic clock, immune to wall adjustments);
    ``start_wall`` anchors the span on the shared wall clock so spans from
    different processes sort into one timeline. ``duration_ms=None`` means
    the span is still open (assembly flags the trace incomplete)."""

    trace_id: str
    span_id: str
    name: str
    parent_span_id: Optional[str] = None
    start_wall: float = 0.0
    start_mono: float = 0.0
    duration_ms: Optional[float] = None
    process: str = ""
    attributes: Dict[str, Any] = field(default_factory=dict)
    # Cross-trace causal references (ISSUE 17): each entry is
    # {"trace_id": ..., "span_id": ...?, "attributes": {...}?}. Links do
    # NOT participate in the parent/child tree — assembly ignores them —
    # and the wire key is omitted entirely when the list is empty so a
    # link-free span serializes byte-identically to the pre-links schema.
    links: List[Dict[str, Any]] = field(default_factory=list)

    def to_wire(self) -> Dict[str, Any]:
        wire = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "name": self.name,
            "start_wall": self.start_wall,
            "start_mono": self.start_mono,
            "duration_ms": self.duration_ms,
            "process": self.process,
            "attributes": dict(self.attributes),
        }
        if self.links:
            wire["links"] = [dict(link) for link in self.links]
        return wire


def make_span(
    name: str,
    trace_id: str,
    parent_span_id: Optional[str] = None,
    *,
    start_mono: Optional[float] = None,
    duration_s: Optional[float] = None,
    process: str = "",
    span_id: Optional[str] = None,
    attributes: Optional[Mapping[str, Any]] = None,
    links: Optional[Sequence[Mapping[str, Any]]] = None,
) -> Dict[str, Any]:
    """A closed span wire dict from a measured ``(start_mono, duration)``
    pair, back-deriving the wall anchor from the current clocks so callers
    never run two clocks for one measurement. Builds the wire dict directly
    (no ``Span`` round-trip): this runs several times per task on the drain
    hot path. ``links`` is emitted only when non-empty (legacy bytes)."""
    now_mono = time.monotonic()
    start_mono = now_mono if start_mono is None else float(start_mono)
    span = {
        "trace_id": trace_id,
        "span_id": span_id or new_span_id(),
        "parent_span_id": parent_span_id,
        "name": name,
        "start_wall": time.time() - max(0.0, now_mono - start_mono),
        "start_mono": start_mono,
        "duration_ms": (
            None if duration_s is None else round(float(duration_s) * 1e3, 3)
        ),
        "process": process,
        "attributes": dict(attributes or {}),
    }
    if links:
        span["links"] = [dict(link) for link in links]
    return span


def span_link(
    trace_id: str,
    span_id: Optional[str] = None,
    **attributes: Any,
) -> Dict[str, Any]:
    """One link entry for a span's ``links`` list: a causal reference into
    ANOTHER trace (the serving batch job ↔ rider request association).
    ``span_id``/``attributes`` are optional and omitted when empty."""
    link: Dict[str, Any] = {"trace_id": str(trace_id)}
    if span_id:
        link["span_id"] = str(span_id)
    if attributes:
        link["attributes"] = dict(attributes)
    return link


def _valid_span(span: Any) -> bool:
    # dict first: the typing.Mapping ABC check costs ~3µs and every span on
    # the wire is a plain dict; the ABC path survives only for odd callers.
    if type(span) is not dict and not isinstance(span, Mapping):
        return False
    return (
        isinstance(span.get("trace_id"), str)
        and span["trace_id"] != ""
        and isinstance(span.get("span_id"), str)
        and span["span_id"] != ""
        and isinstance(span.get("name"), str)
        and span["name"] != ""
    )


# ---- per-process span ring (the agent side) ----

class SpanBuffer:
    """Thread-safe bounded ring of span wire dicts. ``add`` is on hot paths:
    it must never raise, never block beyond the lock, and stay O(1)."""

    def __init__(self, capacity: int = DEFAULT_BUFFER_CAPACITY) -> None:
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._spans: "collections.deque" = collections.deque(
            maxlen=self.capacity
        )
        self._dropped = 0

    def add(self, span: Any) -> None:
        """Buffer one span. Ownership transfers: a plain dict is stored
        as-is (``make_span`` hands over fresh dicts on the hot path);
        callers that keep a reference must not mutate it after ``add``."""
        if not enabled():
            return
        if isinstance(span, Span):
            span = span.to_wire()
        if not _valid_span(span):
            return
        if type(span) is not dict:
            span = dict(span)
        with self._lock:
            if len(self._spans) == self.capacity:
                self._dropped += 1
            self._spans.append(span)

    def drain(self) -> List[Dict[str, Any]]:
        """Pop everything pending (the piggyback ship). Callers that fail to
        deliver must ``requeue`` what they took."""
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
        return out

    def requeue(self, spans: Iterable[Mapping[str, Any]]) -> None:
        """Put undelivered spans back (order within the ring is irrelevant —
        assembly sorts by time). Ring bound still applies."""
        with self._lock:
            for s in spans:
                if len(self._spans) == self.capacity:
                    self._dropped += 1
                self._spans.append(dict(s))

    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._spans)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


_default_tracer = SpanBuffer()


def get_tracer() -> SpanBuffer:
    return _default_tracer


# ---- ambient trace context (compile-cost attribution) ----

@dataclass(frozen=True)
class TraceContext:
    """What a deep layer needs to attribute a span to the current task:
    where to record (``tracer``/``registry``) and what to parent to."""

    trace_id: str = ""
    parent_span_id: Optional[str] = None
    tracer: Optional[SpanBuffer] = None
    registry: Any = None
    process: str = ""


_current: "contextvars.ContextVar[Optional[TraceContext]]" = (
    contextvars.ContextVar("agent_tpu_trace_ctx", default=None)
)


def current() -> Optional[TraceContext]:
    return _current.get()


@contextlib.contextmanager
def use_context(ctx: Optional[TraceContext]):
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def record_compile(
    key: Sequence[Any], seconds: float, name: str = "xla.compile"
) -> None:
    """Called by ``ExecutableCache`` on every build (cache miss): emit an
    ``xla.compile`` span attributed to the ambient task context and tick
    ``runtime_compile_seconds_total{op}``. Key convention: ``key[0]`` is the
    op name, the rest is the shape/dtype/mesh signature. Must never raise —
    a broken trace path must not fail a compile that already succeeded."""
    try:
        ctx = current()
        op = str(key[0]) if key else "?"
        shape_key = ",".join(str(k) for k in key[1:])
        registry = getattr(ctx, "registry", None)
        if registry is None:
            from agent_tpu.obs.metrics import get_registry

            registry = get_registry()
        registry.counter(
            "runtime_compile_seconds_total",
            "Seconds spent in XLA compiles (executable-cache misses)",
            ("op",),
        ).inc(max(0.0, float(seconds)), op=op)
        if not enabled():
            return
        tracer = (ctx.tracer if ctx and ctx.tracer is not None
                  else get_tracer())
        tracer.add(make_span(
            name,
            trace_id=ctx.trace_id if ctx else "",
            parent_span_id=ctx.parent_span_id if ctx else None,
            start_mono=time.monotonic() - max(0.0, float(seconds)),
            duration_s=seconds,
            process=ctx.process if ctx else "",
            attributes={"op": op, "shape_key": shape_key},
        ))
    except Exception:  # noqa: BLE001 — tracing must never break a build
        pass


def record_cache_event(key: Sequence[Any], hit: bool, registry: Any = None
                       ) -> None:
    """Executable-cache hit/miss counters (``runtime_compile_cache_total``),
    landing in the ambient context's registry when one is set."""
    try:
        if registry is None:
            ctx = current()
            registry = getattr(ctx, "registry", None)
        if registry is None:
            from agent_tpu.obs.metrics import get_registry

            registry = get_registry()
        registry.counter(
            "runtime_compile_cache_total",
            "Executable-cache lookups by op and outcome",
            ("op", "outcome"),
        ).inc(op=str(key[0]) if key else "?",
              outcome="hit" if hit else "miss")
    except Exception:  # noqa: BLE001
        pass


# ---- controller-side assembly ----

class TraceStore:
    """Bounded per-trace span store — the controller's assembly point.

    Traces evict oldest-first past ``max_traces`` (same O(capacity) deal as
    the flight recorder: a 10M-shard drain keeps the newest window, not the
    whole history). Spans dedup by ``span_id``, so a piggyback redelivered
    after a lost response re-ingests idempotently.
    """

    def __init__(
        self,
        max_traces: int = DEFAULT_MAX_TRACES,
        max_spans_per_trace: int = DEFAULT_MAX_SPANS_PER_TRACE,
    ) -> None:
        self.max_traces = max(1, int(max_traces))
        self.max_spans_per_trace = max(1, int(max_spans_per_trace))
        self._lock = threading.Lock()
        # trace_id -> {span_id: span dict}; OrderedDict for FIFO eviction.
        self._traces: "collections.OrderedDict[str, Dict[str, Dict[str, Any]]]" = (
            collections.OrderedDict()
        )
        self.dropped_traces = 0
        self.dropped_spans = 0

    def add(self, span: Any) -> bool:
        """Ingest one span wire dict; False = rejected (malformed/bounds).
        Ownership transfers like :meth:`SpanBuffer.add`: a plain dict is
        stored without copying (``finish`` mutates it in place)."""
        if not enabled():
            return False
        if isinstance(span, Span):
            span = span.to_wire()
        if not _valid_span(span):
            return False
        if type(span) is not dict:
            span = dict(span)
        with self._lock:
            spans = self._traces.get(span["trace_id"])
            if spans is None:
                while len(self._traces) >= self.max_traces:
                    self._traces.popitem(last=False)
                    self.dropped_traces += 1
                spans = {}
                self._traces[span["trace_id"]] = spans
            if (
                span["span_id"] not in spans
                and len(spans) >= self.max_spans_per_trace
            ):
                self.dropped_spans += 1
                return False
            spans[span["span_id"]] = span
        return True

    def ingest(self, spans: Any) -> int:
        """Bulk ``add`` for a piggybacked batch; returns spans accepted."""
        if not isinstance(spans, (list, tuple)):
            return 0
        return sum(1 for s in spans if self.add(s))

    def open(
        self,
        trace_id: str,
        name: str,
        parent_span_id: Optional[str] = None,
        *,
        start_clock: float = 0.0,
        process: str = "controller",
        attributes: Optional[Mapping[str, Any]] = None,
        span_id: Optional[str] = None,
        links: Optional[Sequence[Mapping[str, Any]]] = None,
    ) -> Optional[str]:
        """Record an OPEN span (duration unknown yet) and return its id, or
        None when tracing is disabled. ``start_clock`` is whatever monotonic
        clock the caller will later pass to :meth:`finish` — the controller
        uses its own (injectable) clock."""
        if not enabled():
            return None
        sid = span_id or new_span_id()
        span: Dict[str, Any] = {
            "trace_id": trace_id,
            "span_id": sid,
            "parent_span_id": parent_span_id,
            "name": name,
            "start_wall": time.time(),
            "start_mono": float(start_clock),
            "duration_ms": None,
            "process": process,
            "attributes": dict(attributes or {}),
        }
        if links:
            span["links"] = [dict(link) for link in links]
        ok = self.add(span)
        return sid if ok else None

    def add_links(
        self,
        trace_id: str,
        span_id: Optional[str],
        links: Sequence[Mapping[str, Any]],
    ) -> None:
        """Append cross-trace links to a stored span (the serving batch
        job's root learns its riders only after the job is submitted, so
        links land post-``open``). No-op when the span is absent."""
        if span_id is None or not links:
            return
        with self._lock:
            span = self._traces.get(trace_id, {}).get(span_id)
            if span is None:
                return
            span.setdefault("links", []).extend(dict(link) for link in links)

    def links(self, trace_id: str, span_id: str) -> List[Dict[str, Any]]:
        """The stored links of one span (empty when absent/link-free)."""
        with self._lock:
            span = self._traces.get(trace_id, {}).get(span_id)
            return [dict(link) for link in span.get("links", [])] \
                if span else []

    def finish(
        self,
        trace_id: str,
        span_id: Optional[str],
        end_clock: float,
        attributes: Optional[Mapping[str, Any]] = None,
    ) -> None:
        """Close an open span: duration = ``end_clock`` − its
        ``start_mono`` (same clock as :meth:`open`'s ``start_clock``)."""
        if span_id is None:
            return
        with self._lock:
            span = self._traces.get(trace_id, {}).get(span_id)
            if span is None:
                return
            span["duration_ms"] = round(
                max(0.0, float(end_clock) - float(span.get("start_mono", 0.0)))
                * 1e3, 3,
            )
            if attributes:
                span.setdefault("attributes", {}).update(attributes)

    def spans(self, trace_id: str) -> Optional[List[Dict[str, Any]]]:
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is None:
                return None
            return [dict(s) for s in spans.values()]

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def assemble(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """The ``GET /v1/trace/{job_id}`` body: spans sorted by wall start,
        orphans (dangling ``parent_span_id``) flagged, completeness = one
        root + no orphans + every span closed."""
        spans = self.spans(trace_id)
        if spans is None:
            return None
        return assemble(trace_id, spans)

    def summaries(self, limit: int = 20) -> List[Dict[str, Any]]:
        """Newest-first trace listing for ``GET /v1/traces``."""
        with self._lock:
            items = [
                (tid, [dict(s) for s in spans.values()])
                for tid, spans in self._traces.items()
            ]
        out: List[Dict[str, Any]] = []
        for tid, spans in reversed(items):
            roots = [s for s in spans if s.get("parent_span_id") is None]
            root = min(
                roots, key=lambda s: s.get("start_wall", 0.0)
            ) if roots else None
            out.append({
                "trace_id": tid,
                "n_spans": len(spans),
                "root_name": root.get("name") if root else None,
                "root_duration_ms": root.get("duration_ms") if root else None,
                "complete": _complete(spans),
            })
            if len(out) >= max(1, int(limit)):
                break
        return out


def _complete(spans: Sequence[Mapping[str, Any]]) -> bool:
    ids = {s["span_id"] for s in spans}
    roots = [s for s in spans if s.get("parent_span_id") is None]
    orphans = [
        s for s in spans
        if s.get("parent_span_id") is not None
        and s["parent_span_id"] not in ids
    ]
    open_spans = [s for s in spans if s.get("duration_ms") is None]
    return len(roots) == 1 and not orphans and not open_spans


def assemble(
    trace_id: str, spans: Sequence[Mapping[str, Any]]
) -> Dict[str, Any]:
    ids = {s["span_id"] for s in spans}
    ordered = sorted(
        (dict(s) for s in spans),
        key=lambda s: (s.get("start_wall", 0.0), s.get("start_mono", 0.0)),
    )
    roots = [s["span_id"] for s in ordered
             if s.get("parent_span_id") is None]
    orphans = [
        s["span_id"] for s in ordered
        if s.get("parent_span_id") is not None
        and s["parent_span_id"] not in ids
    ]
    open_ids = [s["span_id"] for s in ordered if s.get("duration_ms") is None]
    return {
        "trace_id": trace_id,
        "spans": ordered,
        "root_span_id": roots[0] if len(roots) == 1 else None,
        "roots": roots,
        "orphans": orphans,
        "open_spans": open_ids,
        "complete": len(roots) == 1 and not orphans and not open_ids,
    }


# ---- exporters ----

def to_jsonl(spans: Iterable[Mapping[str, Any]]) -> str:
    return "".join(
        json.dumps(dict(s), sort_keys=True, default=str) + "\n"
        for s in spans
    )


def from_jsonl(text: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        span = json.loads(line)
        if _valid_span(span):
            out.append(span)
    return out


def to_chrome_trace(spans: Iterable[Mapping[str, Any]]) -> Dict[str, Any]:
    """Chrome-trace / Perfetto JSON object format: complete ("X") events in
    microseconds on the wall clock, one pid per producing process plus the
    ``process_name`` metadata events Perfetto uses for track labels. Open
    spans export with ``dur=0`` and ``args.incomplete`` so a live trace
    still loads."""
    pids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for s in spans:
        proc = str(s.get("process") or "unknown")
        pid = pids.get(proc)
        if pid is None:
            pid = len(pids) + 1
            pids[proc] = pid
            events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": proc},
            })
        dur_ms = s.get("duration_ms")
        ev: Dict[str, Any] = {
            "ph": "X",
            "name": str(s.get("name", "?")),
            "cat": "agent-tpu",
            "ts": float(s.get("start_wall", 0.0)) * 1e6,
            "dur": max(0.0, float(dur_ms or 0.0)) * 1e3,
            "pid": pid,
            "tid": 0,
            "args": {
                "trace_id": s.get("trace_id"),
                "span_id": s.get("span_id"),
                "parent_span_id": s.get("parent_span_id"),
                **(s.get("attributes") or {}),
            },
        }
        if dur_ms is None:
            ev["args"]["incomplete"] = True
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_chrome_trace(obj: Any) -> List[str]:
    """Structural check of a Chrome-trace export (the schema Perfetto's
    legacy JSON importer requires); returns problems, empty = loads."""
    problems: List[str] = []
    if not isinstance(obj, Mapping):
        return ["trace is not a JSON object"]
    events = obj.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, Mapping):
            problems.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "M"):
            problems.append(f"event {i}: unsupported ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            problems.append(f"event {i}: missing name")
        if not isinstance(ev.get("pid"), int):
            problems.append(f"event {i}: missing int pid")
        if ph == "X":
            for key in ("ts", "dur"):
                v = ev.get(key)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    problems.append(f"event {i}: missing numeric {key}")
                elif key == "dur" and v < 0:
                    problems.append(f"event {i}: negative dur")
    return problems


def phase_breakdown(assembled: Mapping[str, Any]) -> str:
    """One-line per-phase attribution of an assembled trace — the bench/
    drain report line ("where did this job's seconds go")."""
    spans = assembled.get("spans") or []
    totals: Dict[str, float] = {}
    order: List[str] = []
    for s in spans:
        dur = s.get("duration_ms")
        if dur is None:
            continue
        name = str(s.get("name", "?"))
        if name not in totals:
            order.append(name)
        totals[name] = totals.get(name, 0.0) + float(dur)
    root_id = assembled.get("root_span_id")
    root = next(
        (s for s in spans if s.get("span_id") == root_id), None
    )
    total = (root or {}).get("duration_ms")
    parts = " | ".join(
        f"{name} {totals[name]:.1f}ms"
        for name in order if name != (root or {}).get("name")
    )
    head = f"trace {assembled.get('trace_id')}"
    if total is not None:
        head += f": total {float(total):.1f}ms"
    return f"{head} = {parts}" if parts else head
