"""Durable on-disk time-series store (ISSUE 20 tentpole a).

The :class:`~agent_tpu.obs.timeseries.TimeSeriesRing` gave the controller
trend history, but it dies with the process: a restart or a standby
promotion silently loses every sample, and "what did queue depth look like
in the 10 minutes before the page" becomes unanswerable the moment the
page actually matters. :class:`TsdbStore` persists every ring sample to
disk, reusing the journal's proven segment machinery (append-only
``<dir>/tsdb.seg-NNNNNNNN`` files, atomic rotate, torn-tail sealing at
reopen) rather than inventing a second storage engine.

Layout — three segment streams inside ``TSDB_DIR``:

- ``tsdb.seg-*``      raw samples, one JSON line per sweep-time sample:
                      ``{"ev":"s","wall":t,"data":{family:{labelkey:v}}}``
- ``tsdb-60.seg-*``   1-minute aggregates
- ``tsdb-600.seg-*``  10-minute aggregates

Aggregate lines carry ``[sum, count, min, max, last]`` per series slot —
enough to recompute means (sum/count), counter rates (``last`` preserves
the cumulative value at bucket end), and merged-histogram quantiles
(per-bucket ``*_bucket`` counters are monotone, so the windowed increase
is ``max - min`` and feeds ``histogram_quantile`` unchanged; see
:func:`quantile_from_bucket_series`). Retention is whole-segment: segments
older than the tier's ``TSDB_RETENTION_*`` age are unlinked, and a global
byte cap evicts oldest-raw-first. The active (highest-seq) segment of a
tier is never deleted.

Dependency-free like the rest of ``agent_tpu.obs``; tolerant of torn
tails both at reopen (``open_for_append`` seals) and at read (unparsable
lines are skipped, never raised).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import (
    Any, Callable, Dict, Iterator, List, Mapping, Optional, Tuple,
)

from agent_tpu.obs.metrics import histogram_quantile
from agent_tpu.obs.timeseries import TimeSeriesRing, points_to_rates

if False:  # pragma: no cover — typing only
    from agent_tpu.controller.journal import SegmentedJournal  # noqa: F401


def _journal_machinery():
    """Deferred import: ``controller.core`` imports this module, and the
    ``agent_tpu.controller`` package __init__ imports core — importing
    the journal at module load would close that cycle."""
    from agent_tpu.controller.journal import (
        SegmentedJournal, list_segments,
    )
    return SegmentedJournal, list_segments


def list_tier_segments(base: str) -> List[Tuple[int, str]]:
    _, list_segments = _journal_machinery()
    return list_segments(base)

# Tier resolutions in seconds; 0 is the raw stream.
RESOLUTIONS: Tuple[int, ...] = (60, 600)

DEFAULT_SEGMENT_BYTES = 1 << 20
DEFAULT_RETENTION_RAW_SEC = 3600.0
DEFAULT_RETENTION_1M_SEC = 86400.0
DEFAULT_RETENTION_10M_SEC = 7 * 86400.0
DEFAULT_MAX_BYTES = 256 << 20
DEFAULT_GC_INTERVAL_SEC = 30.0
MAX_POINTS_PER_SERIES = 2000


def _tier_base(directory: str, res: int) -> str:
    return os.path.join(directory, "tsdb" if res == 0 else f"tsdb-{res}")


class TsdbStore:
    """Append-path cost is one JSON line per tier transition plus one per
    sample; reads scan segments (bounded by retention) — the store serves
    forensics and dashboards, not the hot path."""

    def __init__(
        self,
        directory: str,
        segment_max_bytes: int = DEFAULT_SEGMENT_BYTES,
        retention_raw_sec: float = DEFAULT_RETENTION_RAW_SEC,
        retention_1m_sec: float = DEFAULT_RETENTION_1M_SEC,
        retention_10m_sec: float = DEFAULT_RETENTION_10M_SEC,
        max_bytes: int = DEFAULT_MAX_BYTES,
        gc_interval_sec: float = DEFAULT_GC_INTERVAL_SEC,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._clock = clock
        self.retention = {
            0: max(0.0, float(retention_raw_sec)),
            60: max(0.0, float(retention_1m_sec)),
            600: max(0.0, float(retention_10m_sec)),
        }
        self.max_bytes = max(0, int(max_bytes))
        self.gc_interval_sec = max(1.0, float(gc_interval_sec))
        self._lock = threading.RLock()
        SegmentedJournal, _ = _journal_machinery()
        self._journals: Dict[int, "SegmentedJournal"] = {}
        for res in (0,) + RESOLUTIONS:
            j = SegmentedJournal(
                _tier_base(directory, res),
                segment_max_bytes=max(4096, int(segment_max_bytes)),
            )
            j.open_for_append()  # seals any torn tail from a crash
            self._journals[res] = j
        # Open aggregation buckets: {"t0", "n", "data": {fam: {key: slot}}}
        # where slot = [sum, count, min, max, last].
        self._agg_cur: Dict[int, Optional[Dict[str, Any]]] = {
            res: None for res in RESOLUTIONS
        }
        self._last_gc = 0.0
        self.samples_appended = 0
        self.append_errors = 0
        self.gc_segments_removed = 0
        self.closed = False

    # ---- write path ----

    def append_sample(
        self, wall: float, data: Mapping[str, Mapping[str, float]]
    ) -> None:
        """Persist one flattened sample (the ring's ``data`` dict). Never
        raises on I/O trouble — the sweep loop must survive a full disk;
        failures count in ``append_errors``."""
        with self._lock:
            if self.closed:
                return
            try:
                self._journals[0].append(
                    {"ev": "s", "wall": round(float(wall), 3), "data": data}
                )
                for res in RESOLUTIONS:
                    self._feed_agg(res, wall, data)
                self.samples_appended += 1
            except Exception:  # noqa: BLE001 — disk full / unlinked dir
                self.append_errors += 1
                return
            now = self._clock()
            if now - self._last_gc >= self.gc_interval_sec:
                self._last_gc = now
                try:
                    self.gc(now=now)
                except Exception:  # noqa: BLE001
                    self.append_errors += 1

    def _feed_agg(
        self, res: int, wall: float, data: Mapping[str, Mapping[str, float]]
    ) -> None:
        t0 = int(wall // res) * res
        cur = self._agg_cur[res]
        if cur is not None and t0 > cur["t0"]:
            self._flush_agg(res)
            cur = None
        if cur is None:
            cur = {"t0": t0, "n": 0, "data": {}}
            self._agg_cur[res] = cur
        cur["n"] += 1
        for fam, series in data.items():
            dst = cur["data"].setdefault(fam, {})
            for key, v in series.items():
                v = float(v)
                slot = dst.get(key)
                if slot is None:
                    dst[key] = [v, 1, v, v, v]
                else:
                    slot[0] += v
                    slot[1] += 1
                    if v < slot[2]:
                        slot[2] = v
                    if v > slot[3]:
                        slot[3] = v
                    slot[4] = v

    def _flush_agg(self, res: int) -> None:
        cur = self._agg_cur[res]
        if cur is None or not cur["n"]:
            return
        self._journals[res].append({
            "ev": "a", "res": res, "t0": cur["t0"], "t1": cur["t0"] + res,
            "n": cur["n"], "data": cur["data"],
        })
        self._agg_cur[res] = None

    def flush(self) -> None:
        """Force-flush open aggregation buckets (close path and tests —
        a reopened store merging a duplicate ``t0`` at read keeps this
        loss-free)."""
        with self._lock:
            for res in RESOLUTIONS:
                try:
                    self._flush_agg(res)
                except Exception:  # noqa: BLE001
                    self.append_errors += 1

    def close(self) -> None:
        with self._lock:
            if self.closed:
                return
            self.flush()
            for j in self._journals.values():
                try:
                    j.close()
                except Exception:  # noqa: BLE001
                    pass
            self.closed = True

    # ---- retention ----

    def gc(self, now: Optional[float] = None) -> int:
        """Whole-segment retention: per-tier age limit, then the global
        byte cap (evict oldest raw first, then 1m, then 10m). The active
        segment of each tier survives both passes. Returns segments
        removed."""
        if now is None:
            now = self._clock()
        removed = 0
        with self._lock:
            survivors: List[Tuple[int, int, str, float, int]] = []
            for res in (0,) + RESOLUTIONS:
                segs = list_tier_segments(
                    _tier_base(self.directory, res)
                )
                limit = self.retention[res]
                for seq, path in segs[:-1]:  # never the active segment
                    try:
                        st = os.stat(path)
                    except OSError:
                        continue
                    if limit > 0 and now - st.st_mtime > limit:
                        try:
                            os.remove(path)
                            removed += 1
                        except OSError:
                            pass
                        continue
                    survivors.append(
                        (res, seq, path, st.st_mtime, st.st_size)
                    )
                if segs:
                    try:
                        st = os.stat(segs[-1][1])
                        survivors.append(
                            (res, segs[-1][0], segs[-1][1],
                             st.st_mtime, st.st_size)
                        )
                    except OSError:
                        pass
            if self.max_bytes > 0:
                total = sum(s[4] for s in survivors)
                if total > self.max_bytes:
                    active = {
                        res: max(
                            (s[1] for s in survivors if s[0] == res),
                            default=-1,
                        )
                        for res in (0,) + RESOLUTIONS
                    }
                    # Oldest-first within raw, then 1m, then 10m.
                    evictable = sorted(
                        (
                            s for s in survivors
                            if s[1] != active[s[0]]
                        ),
                        key=lambda s: ((0,) + RESOLUTIONS).index(s[0]) * 1e12
                        + s[3],
                    )
                    for res, _seq, path, _mt, size in evictable:
                        if total <= self.max_bytes:
                            break
                        try:
                            os.remove(path)
                            total -= size
                            removed += 1
                        except OSError:
                            pass
            self.gc_segments_removed += removed
        return removed

    # ---- read path ----

    def _iter_events(self, res: int) -> Iterator[Dict[str, Any]]:
        base = _tier_base(self.directory, res)
        for _seq, path in list_tier_segments(base):
            try:
                f = open(path, "r", encoding="utf-8")
            except OSError:
                continue
            with f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue  # torn tail / partial flush — skip
                    if isinstance(ev, dict):
                        yield ev

    def samples(
        self,
        since: Optional[float] = None,
        until: Optional[float] = None,
    ) -> List[Dict[str, Any]]:
        """Raw samples (``{"wall", "data"}``) in append order, filtered
        to ``since <= wall <= until``."""
        out: List[Dict[str, Any]] = []
        for ev in self._iter_events(0):
            if ev.get("ev") != "s":
                continue
            wall = ev.get("wall")
            if not isinstance(wall, (int, float)):
                continue
            if since is not None and wall < since:
                continue
            if until is not None and wall > until:
                continue
            out.append({"wall": float(wall), "data": ev.get("data") or {}})
        return out

    def aggregates(
        self, res: int, since: Optional[float] = None
    ) -> List[Dict[str, Any]]:
        """Aggregate buckets for one tier, duplicate ``t0`` events merged
        (a flush-at-close followed by a reopen writing the same bucket
        again is a merge, not a double count... the slots re-merge:
        sums add, min/max widen, ``last`` takes the later event)."""
        merged: Dict[int, Dict[str, Any]] = {}
        for ev in self._iter_events(res):
            if ev.get("ev") != "a" or ev.get("res") != res:
                continue
            t0 = ev.get("t0")
            if not isinstance(t0, (int, float)):
                continue
            t0 = int(t0)
            if since is not None and t0 + res < since:
                continue
            data = ev.get("data") or {}
            have = merged.get(t0)
            if have is None:
                merged[t0] = {
                    "t0": t0, "t1": t0 + res,
                    "n": int(ev.get("n") or 0),
                    "data": {
                        fam: {k: list(slot) for k, slot in series.items()}
                        for fam, series in data.items()
                    },
                }
                continue
            have["n"] += int(ev.get("n") or 0)
            for fam, series in data.items():
                dst = have["data"].setdefault(fam, {})
                for key, slot in series.items():
                    old = dst.get(key)
                    if old is None:
                        dst[key] = list(slot)
                    else:
                        old[0] += slot[0]
                        old[1] += slot[1]
                        old[2] = min(old[2], slot[2])
                        old[3] = max(old[3], slot[3])
                        old[4] = slot[4]
        return [merged[t0] for t0 in sorted(merged)]

    def query(
        self,
        name: str,
        label_filter: Optional[Mapping[str, str]] = None,
        rate: bool = False,
        since: Optional[float] = None,
        until: Optional[float] = None,
        step: Optional[float] = None,
        max_points: int = MAX_POINTS_PER_SERIES,
    ) -> Dict[str, Any]:
        """Historical query. ``step`` picks the tier (>=600s → 10m
        aggregates, >=60s → 1m, else raw). Aggregate-tier series carry
        ``agg_points`` (``[t_end, sum, count, min, max]``) alongside the
        usual ``points`` (``[t, last]`` — counter-rate compatible)."""
        res = 0
        if step is not None:
            if step >= 600:
                res = 600
            elif step >= 60:
                res = 60
        grouped: Dict[str, List[Tuple[float, float]]] = {}
        agg_grouped: Dict[str, List[List[float]]] = {}
        if res == 0:
            for s in self.samples(since=since, until=until):
                for key, v in (s["data"].get(name) or {}).items():
                    grouped.setdefault(key, []).append((s["wall"], v))
        else:
            for bucket in self.aggregates(res, since=since):
                t = float(bucket["t1"])
                if until is not None and bucket["t0"] > until:
                    continue
                for key, slot in (bucket["data"].get(name) or {}).items():
                    grouped.setdefault(key, []).append((t, float(slot[4])))
                    agg_grouped.setdefault(key, []).append(
                        [t, slot[0], slot[1], slot[2], slot[3]]
                    )
        series: List[Dict[str, Any]] = []
        for key in sorted(grouped):
            try:
                labels = dict(json.loads(key))
            except ValueError:
                continue
            if label_filter and any(
                labels.get(k) != v for k, v in label_filter.items()
            ):
                continue
            pts = grouped[key]
            if rate:
                pts = points_to_rates(pts)
            entry: Dict[str, Any] = {
                "labels": labels,
                "points": [
                    [round(t, 3), round(v, 6)] for t, v in
                    pts[-max(1, int(max_points)):]
                ],
            }
            if key in agg_grouped:
                entry["agg_points"] = [
                    [round(p[0], 3)] + [round(x, 6) for x in p[1:]]
                    for p in agg_grouped[key][-max(1, int(max_points)):]
                ]
            series.append(entry)
        return {
            "name": name,
            "rate": bool(rate),
            "since": since,
            "step": res,
            "source": "tsdb",
            "series": series,
        }

    def stats(self) -> Dict[str, Any]:
        tiers: Dict[str, Any] = {}
        total_bytes = 0
        for res in (0,) + RESOLUTIONS:
            segs = list_tier_segments(_tier_base(self.directory, res))
            size = 0
            for _seq, path in segs:
                try:
                    size += os.path.getsize(path)
                except OSError:
                    pass
            total_bytes += size
            tiers["raw" if res == 0 else f"{res}s"] = {
                "segments": len(segs), "bytes": size,
            }
        return {
            "dir": self.directory,
            "tiers": tiers,
            "bytes": total_bytes,
            "max_bytes": self.max_bytes,
            "samples_appended": self.samples_appended,
            "append_errors": self.append_errors,
            "gc_segments_removed": self.gc_segments_removed,
        }


# ---- quantiles from aggregates ----

def quantile_from_bucket_series(
    series: List[Mapping[str, Any]], q: float
) -> Optional[float]:
    """Estimate the q-quantile of the observations a ``<name>_bucket``
    query window covers. Each per-``le`` slot is a monotone counter, so
    its windowed increase is ``last - first`` on raw points and
    ``max(maxes) - min(mins)`` on aggregate points — feeding
    ``histogram_quantile`` with the increases keeps the estimate within
    one bucket width of truth (same bound the live registry gives)."""
    increases: Dict[float, float] = {}
    inf_increase = 0.0
    saw_inf = False
    for s in series:
        le = (s.get("labels") or {}).get("le")
        if le is None:
            continue
        agg = s.get("agg_points")
        if agg:
            lo = min(p[3] for p in agg)
            hi = max(p[4] for p in agg)
            inc = max(0.0, hi - lo)
        else:
            pts = s.get("points") or []
            if len(pts) < 2:
                continue
            inc = max(0.0, float(pts[-1][1]) - float(pts[0][1]))
        if le == "+Inf":
            inf_increase += inc
            saw_inf = True
        else:
            try:
                edge = float(le)
            except (TypeError, ValueError):
                continue
            increases[edge] = increases.get(edge, 0.0) + inc
    if not increases and not saw_inf:
        return None
    edges = sorted(increases)
    counts = [increases[e] for e in edges] + [inf_increase]
    if sum(counts) <= 0:
        return None
    return histogram_quantile(edges, counts, q)


# ---- shared controller/router query view ----

def query_history(
    name: str,
    label_filter: Optional[Mapping[str, str]] = None,
    rate: bool = False,
    since: Optional[float] = None,
    step: Optional[float] = None,
    ring: Optional[TimeSeriesRing] = None,
    store: Optional["TsdbStore"] = None,
) -> Dict[str, Any]:
    """The ``GET /v1/timeseries?since=`` body: disk when a store is open
    (it holds everything the ring does — every ring sample is persisted),
    ring otherwise (bounded window, ``step`` approximated by keeping the
    last point per step bucket). Seamless for callers either way."""
    if store is not None:
        return store.query(
            name, label_filter=label_filter, rate=rate,
            since=since, step=step,
        )
    series: List[Dict[str, Any]] = []
    if ring is not None:
        for s in ring.series(name, label_filter):
            pts = [
                (float(p[0]), float(p[1])) for p in s["points"]
                if since is None or p[0] >= since
            ]
            if step is not None and step > 0 and pts:
                by_bucket: Dict[int, Tuple[float, float]] = {}
                for t, v in pts:
                    by_bucket[int(t // step)] = (t, v)
                pts = [by_bucket[b] for b in sorted(by_bucket)]
            if rate:
                pts = points_to_rates(pts)
            if pts:
                series.append({
                    "labels": s["labels"],
                    "points": [[round(t, 3), round(v, 6)] for t, v in pts],
                })
    return {
        "name": name,
        "rate": bool(rate),
        "since": since,
        "step": float(step) if step else 0,
        "source": "ring",
        "series": series,
    }
