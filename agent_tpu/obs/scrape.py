"""Scrape-side helpers: read ``GET /v1/metrics`` and ``/v1/trace`` back.

``bench.py`` and ``scripts/drain_at_scale.py`` attribute drain time per op
by scraping the controller's exposition instead of re-deriving spans from
result bodies (``utils/spans.py`` stays as the fallback when scraping is
unavailable — e.g. a controller predating the endpoint), and fetch the
slowest job's assembled trace for a per-phase breakdown line (ISSUE 5
satellite: a broken trace path fails loudly in bench runs instead of
rotting silently). Stdlib-only, like the rest of ``agent_tpu.obs``.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Dict, Iterable, Optional

from agent_tpu.obs.metrics import parse_exposition


def fetch_metrics_text(
    base_url: str, timeout: float = 10.0
) -> Optional[str]:
    """GET ``<base_url>/v1/metrics`` → exposition text, or None on any
    failure (callers fall back to result-body spans)."""
    url = base_url.rstrip("/") + "/v1/metrics"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            if resp.status != 200:
                return None
            return resp.read().decode("utf-8", errors="replace")
    except Exception:  # noqa: BLE001 — scrape is best-effort by contract
        return None


def op_phase_seconds(
    text: str,
    ops: Iterable[str],
    phases: Iterable[str] = ("execute", "fetch"),
) -> Dict[str, float]:
    """Sum ``task_phase_seconds_sum{op,phase}`` over ``phases`` per op —
    the scraped equivalent of ``utils.spans.op_span_ms`` (which sums
    ``device_ms + fetch_ms``; the execute phase is the device-dispatch
    span). Series carrying an ``agent`` label and the fleet-merged ones
    would double-count if both were summed; only unlabeled (fleet/merged)
    series count."""
    phases = set(phases)
    out = {op: 0.0 for op in ops}
    try:
        samples = parse_exposition(text)
    except ValueError:
        return out
    for labels, value in samples.get("task_phase_seconds_sum", []):
        if "agent" in labels:
            continue
        op = labels.get("op")
        if op in out and labels.get("phase") in phases:
            out[op] += value
    return out


def fetch_health(
    base_url: str, timeout: float = 10.0
) -> Optional[Dict[str, Any]]:
    """``GET /v1/health`` → the fleet verdict body (ISSUE 8), or None on
    any failure. Callers that promised health reporting (bench,
    drain_at_scale) must fail loudly on None instead of omitting the
    fields silently."""
    out = fetch_json(base_url, "/v1/health", timeout=timeout)
    return out if isinstance(out, dict) else None


# ---- trace endpoints (ISSUE 5) ----

def fetch_json(
    base_url: str, path: str, timeout: float = 10.0
) -> Optional[Any]:
    """GET ``<base_url><path>`` → parsed JSON, or None on any failure."""
    url = base_url.rstrip("/") + path
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            if resp.status != 200:
                return None
            return json.loads(resp.read().decode("utf-8", errors="replace"))
    except Exception:  # noqa: BLE001 — scrape is best-effort by contract
        return None


def fetch_trace(
    base_url: str, job_id: str, timeout: float = 10.0
) -> Optional[Dict[str, Any]]:
    """``GET /v1/trace/{job_id}`` → the assembled span tree, or None."""
    out = fetch_json(base_url, f"/v1/trace/{job_id}", timeout=timeout)
    return out if isinstance(out, dict) else None


def slowest_trace(
    base_url: str, limit: int = 64, timeout: float = 10.0
) -> Optional[Dict[str, Any]]:
    """The assembled trace of the slowest job in the controller's trace
    window (largest closed root duration) — what the bench/drain scripts
    print a phase-breakdown line for. None when the trace path is down."""
    listing = fetch_json(base_url, f"/v1/traces?limit={int(limit)}",
                         timeout=timeout)
    if not isinstance(listing, dict):
        return None
    candidates = [
        t for t in listing.get("traces", [])
        if isinstance(t, dict)
        and isinstance(t.get("root_duration_ms"), (int, float))
    ]
    if not candidates:
        return None
    worst = max(candidates, key=lambda t: t["root_duration_ms"])
    return fetch_trace(base_url, worst["trace_id"], timeout=timeout)


# ---- stage/execute overlap (ISSUE 6 satellite) ----

def _merge_intervals(ivals):
    """Sorted-union of (t0, t1) wall intervals."""
    merged = []
    for t0, t1 in sorted(ivals):
        if merged and t0 <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], t1))
        else:
            merged.append((t0, t1))
    return merged


def _overlap_seconds(ival, merged):
    t0, t1 = ival
    total = 0.0
    for m0, m1 in merged:
        lo, hi = max(t0, m0), min(t1, m1)
        if hi > lo:
            total += hi - lo
        if m0 >= t1:
            break
    return total


def overlap_from_spans(spans) -> Optional[Dict[str, Any]]:
    """Cross-job stage/execute concurrency from assembled trace spans: the
    fraction of stage wall time hidden under SOME execute span (across
    jobs — pipelining hides job B's staging behind job A's execute), plus
    per-phase p50s. The acceptance picture of the staging pool: overlap →
    1.0 and stage p50 ≤ execute p50 mean staging is invisible behind the
    device. None when no closed stage/execute spans exist."""
    stage, execute = [], []
    for span in spans:
        if not isinstance(span, dict):
            continue
        dur = span.get("duration_ms")
        start = span.get("start_wall")
        if not isinstance(dur, (int, float)) or \
                not isinstance(start, (int, float)):
            continue
        ival = (float(start), float(start) + float(dur) / 1e3)
        if span.get("name") == "stage":
            stage.append(ival)
        elif span.get("name") == "execute":
            execute.append(ival)
    if not stage or not execute:
        return None
    merged = _merge_intervals(execute)
    stage_total = sum(t1 - t0 for t0, t1 in stage)
    hidden = sum(_overlap_seconds(iv, merged) for iv in stage)

    def p50_ms(ivals):
        durs = sorted((t1 - t0) * 1e3 for t0, t1 in ivals)
        return durs[len(durs) // 2]

    return {
        "overlap_ratio": round(hidden / stage_total, 4) if stage_total else 1.0,
        "stage_total_s": round(stage_total, 3),
        "execute_total_s": round(
            sum(t1 - t0 for t0, t1 in execute), 3
        ),
        "stage_p50_ms": round(p50_ms(stage), 3),
        "execute_p50_ms": round(p50_ms(execute), 3),
        "n_stage_spans": len(stage),
        "n_execute_spans": len(execute),
    }


def collect_trace_spans(
    base_url: str, limit: int = 64, timeout: float = 10.0
) -> Optional[list]:
    """Every span of the controller's newest ``limit`` traces
    (``/v1/traces`` + per-job ``/v1/trace/{id}``), or None when the trace
    path is down."""
    listing = fetch_json(base_url, f"/v1/traces?limit={int(limit)}",
                         timeout=timeout)
    if not isinstance(listing, dict):
        return None
    spans: list = []
    for entry in listing.get("traces", []):
        if not isinstance(entry, dict) or not entry.get("trace_id"):
            continue
        assembled = fetch_trace(base_url, entry["trace_id"], timeout=timeout)
        if assembled:
            spans.extend(assembled.get("spans", []))
    return spans


def stage_execute_overlap(
    base_url: str, limit: int = 64, timeout: float = 10.0
) -> Optional[Dict[str, Any]]:
    """:func:`overlap_from_spans` over the controller's newest ``limit``
    traces. None when the trace path is down or no stage/execute spans
    assembled — callers that promised the breakdown (drain_at_scale) must
    fail loudly on None."""
    spans = collect_trace_spans(base_url, limit=limit, timeout=timeout)
    if spans is None:
        return None
    return overlap_from_spans(spans)


def overlap_by_process(spans) -> Dict[str, Dict[str, Any]]:
    """Per-AGENT stage/execute overlap (ISSUE 7): spans grouped by their
    emitting process (``"agent:<name>"``), each group fed through
    :func:`overlap_from_spans` — the fleet-drain attribution that tells a
    well-overlapped member from one whose staging starves its device.
    Controller spans (``process == "controller"``) carry no stage/execute
    phases and are skipped. ``{agent_name: overlap_dict}``; agents with no
    closed stage+execute pair are absent."""
    groups: Dict[str, list] = {}
    for span in spans or []:
        if not isinstance(span, dict):
            continue
        proc = span.get("process")
        if isinstance(proc, str) and proc.startswith("agent:"):
            groups.setdefault(proc[len("agent:"):], []).append(span)
    out: Dict[str, Dict[str, Any]] = {}
    for name, group in groups.items():
        overlap = overlap_from_spans(group)
        if overlap is not None:
            out[name] = overlap
    return out


def stage_execute_overlap_by_agent(
    base_url: str, limit: int = 64, timeout: float = 10.0
) -> Optional[Dict[str, Dict[str, Any]]]:
    """:func:`overlap_by_process` over the controller's newest ``limit``
    traces; None when the trace path is down."""
    spans = collect_trace_spans(base_url, limit=limit, timeout=timeout)
    if spans is None:
        return None
    return overlap_by_process(spans)
