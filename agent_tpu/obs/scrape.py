"""Scrape-side helpers: read ``GET /v1/metrics`` and ``/v1/trace`` back.

``bench.py`` and ``scripts/drain_at_scale.py`` attribute drain time per op
by scraping the controller's exposition instead of re-deriving spans from
result bodies (``utils/spans.py`` stays as the fallback when scraping is
unavailable — e.g. a controller predating the endpoint), and fetch the
slowest job's assembled trace for a per-phase breakdown line (ISSUE 5
satellite: a broken trace path fails loudly in bench runs instead of
rotting silently). Stdlib-only, like the rest of ``agent_tpu.obs``.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Dict, Iterable, Optional

from agent_tpu.obs.metrics import parse_exposition


def fetch_metrics_text(
    base_url: str, timeout: float = 10.0
) -> Optional[str]:
    """GET ``<base_url>/v1/metrics`` → exposition text, or None on any
    failure (callers fall back to result-body spans)."""
    url = base_url.rstrip("/") + "/v1/metrics"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            if resp.status != 200:
                return None
            return resp.read().decode("utf-8", errors="replace")
    except Exception:  # noqa: BLE001 — scrape is best-effort by contract
        return None


def op_phase_seconds(
    text: str,
    ops: Iterable[str],
    phases: Iterable[str] = ("execute", "fetch"),
) -> Dict[str, float]:
    """Sum ``task_phase_seconds_sum{op,phase}`` over ``phases`` per op —
    the scraped equivalent of ``utils.spans.op_span_ms`` (which sums
    ``device_ms + fetch_ms``; the execute phase is the device-dispatch
    span). Series carrying an ``agent`` label and the fleet-merged ones
    would double-count if both were summed; only unlabeled (fleet/merged)
    series count."""
    phases = set(phases)
    out = {op: 0.0 for op in ops}
    try:
        samples = parse_exposition(text)
    except ValueError:
        return out
    for labels, value in samples.get("task_phase_seconds_sum", []):
        if "agent" in labels:
            continue
        op = labels.get("op")
        if op in out and labels.get("phase") in phases:
            out[op] += value
    return out


# ---- trace endpoints (ISSUE 5) ----

def fetch_json(
    base_url: str, path: str, timeout: float = 10.0
) -> Optional[Any]:
    """GET ``<base_url><path>`` → parsed JSON, or None on any failure."""
    url = base_url.rstrip("/") + path
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            if resp.status != 200:
                return None
            return json.loads(resp.read().decode("utf-8", errors="replace"))
    except Exception:  # noqa: BLE001 — scrape is best-effort by contract
        return None


def fetch_trace(
    base_url: str, job_id: str, timeout: float = 10.0
) -> Optional[Dict[str, Any]]:
    """``GET /v1/trace/{job_id}`` → the assembled span tree, or None."""
    out = fetch_json(base_url, f"/v1/trace/{job_id}", timeout=timeout)
    return out if isinstance(out, dict) else None


def slowest_trace(
    base_url: str, limit: int = 64, timeout: float = 10.0
) -> Optional[Dict[str, Any]]:
    """The assembled trace of the slowest job in the controller's trace
    window (largest closed root duration) — what the bench/drain scripts
    print a phase-breakdown line for. None when the trace path is down."""
    listing = fetch_json(base_url, f"/v1/traces?limit={int(limit)}",
                         timeout=timeout)
    if not isinstance(listing, dict):
        return None
    candidates = [
        t for t in listing.get("traces", [])
        if isinstance(t, dict)
        and isinstance(t.get("root_duration_ms"), (int, float))
    ]
    if not candidates:
        return None
    worst = max(candidates, key=lambda t: t["root_duration_ms"])
    return fetch_trace(base_url, worst["trace_id"], timeout=timeout)
