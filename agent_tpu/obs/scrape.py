"""Scrape-side helpers: read ``GET /v1/metrics`` back into numbers.

``bench.py`` and ``scripts/drain_at_scale.py`` attribute drain time per op
by scraping the controller's exposition instead of re-deriving spans from
result bodies (``utils/spans.py`` stays as the fallback when scraping is
unavailable — e.g. a controller predating the endpoint). Stdlib-only, like
the rest of ``agent_tpu.obs``.
"""

from __future__ import annotations

import urllib.request
from typing import Dict, Iterable, Optional

from agent_tpu.obs.metrics import parse_exposition


def fetch_metrics_text(
    base_url: str, timeout: float = 10.0
) -> Optional[str]:
    """GET ``<base_url>/v1/metrics`` → exposition text, or None on any
    failure (callers fall back to result-body spans)."""
    url = base_url.rstrip("/") + "/v1/metrics"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            if resp.status != 200:
                return None
            return resp.read().decode("utf-8", errors="replace")
    except Exception:  # noqa: BLE001 — scrape is best-effort by contract
        return None


def op_phase_seconds(
    text: str,
    ops: Iterable[str],
    phases: Iterable[str] = ("execute", "fetch"),
) -> Dict[str, float]:
    """Sum ``task_phase_seconds_sum{op,phase}`` over ``phases`` per op —
    the scraped equivalent of ``utils.spans.op_span_ms`` (which sums
    ``device_ms + fetch_ms``; the execute phase is the device-dispatch
    span). Series carrying an ``agent`` label and the fleet-merged ones
    would double-count if both were summed; only unlabeled (fleet/merged)
    series count."""
    phases = set(phases)
    out = {op: 0.0 for op in ops}
    try:
        samples = parse_exposition(text)
    except ValueError:
        return out
    for labels, value in samples.get("task_phase_seconds_sum", []):
        if "agent" in labels:
            continue
        op = labels.get("op")
        if op in out and labels.get("phase") in phases:
            out[op] += value
    return out
