"""Fleet health: rolling device utilization + the ``/v1/health`` verdict.

Two halves (ISSUE 8):

- **Agent-side utilization accounting.** :class:`RollingWindow` turns the
  per-op device-busy increments into a *rolling duty cycle* (busy seconds
  inside the last N seconds / N), and :func:`resolve_peak_flops` maps a
  runtime's device kind to its peak dense-bf16 FLOP/s so the agent can
  export an analytic-FLOPs MFU gauge per op. Both are estimates by design:
  duty counts dispatch wall time (what the device *thread* spent inside op
  execute), MFU counts matmul-term analytic FLOPs over that time — the same
  accounting bench.py has always used, now live on ``/v1/metrics``.
- **Verdict assembly.** :func:`build_health` rolls SLO judgments, queue
  pressure, starvation, and per-agent liveness/utilization into ONE
  machine-readable dict — the exact signal vector ROADMAP item 4's
  autoscaler will consume, served at ``GET /v1/health``. Pure function of
  its inputs (no controller import) so tests drive it directly.

Verdict semantics: ``page`` iff any SLO objective is paging; ``warn`` when
any objective warns, an agent has gone stale while work is queued, or jobs
are queued with no live agent at all; else ``ok``. Every non-ok verdict
carries machine-readable ``reasons``.
"""

from __future__ import annotations

import collections
import os
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence

# Peak dense-bf16 FLOP/s by jax device_kind (public spec sheets) — shared
# source of truth for the agent's MFU gauge; bench.py keeps its own table
# for report-side normalization. Unknown kinds → MFU is absent, never a
# guess. PEAK_TFLOPS overrides (useful on CPU CI and for new chip steppings).
PEAK_BF16_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v5": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}

# An agent whose last lease poll is older than this is "stale" to the
# verdict (HEALTH_AGENT_STALE_SEC overrides at the controller).
DEFAULT_AGENT_STALE_SEC = 60.0


def resolve_peak_flops(runtime: Any = None) -> Optional[float]:
    """Peak dense-bf16 FLOP/s for MFU normalization: the ``PEAK_TFLOPS``
    env override first (CPU CI, unlisted steppings), else the device-kind
    table; None when unknown (MFU gauges are then simply not exported)."""
    env = os.environ.get("PEAK_TFLOPS")
    if env:
        try:
            return float(env) * 1e12
        except ValueError:
            pass
    if runtime is None:
        return None
    try:
        kind = getattr(runtime.devices[0], "device_kind", "")
    except Exception:  # noqa: BLE001 — telemetry must never raise
        return None
    tf = PEAK_BF16_TFLOPS.get(kind)
    return tf * 1e12 if tf else None


class RollingWindow:
    """Seconds-of-activity inside a sliding wall window — the rolling duty
    cycle primitive. ``add(seconds)`` records one busy span ending now;
    ``fraction()`` = busy seconds inside the window / window span (the span
    is clipped to the tracker's own lifetime so a fresh agent doesn't read
    as idle). O(events in window) memory, events coalesce per second."""

    def __init__(self, window_sec: float = 60.0, clock=None) -> None:
        self.window_sec = max(1e-6, float(window_sec))
        self._clock = clock if clock is not None else time.monotonic
        self._events: "collections.deque" = collections.deque()
        self._born = self._clock()

    def _trim(self, now: float) -> None:
        horizon = now - self.window_sec
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    def add(self, seconds: float, now: Optional[float] = None) -> None:
        if seconds <= 0:
            return
        if now is None:
            now = self._clock()
        # Coalesce into the current 1s slot: a drain completing hundreds of
        # shards per second must not grow the deque per shard.
        slot = int(now)
        if self._events and self._events[-1][0] == slot:
            self._events[-1][1] += float(seconds)
        else:
            self._events.append([slot, float(seconds)])
        self._trim(now)

    def total(self, now: Optional[float] = None) -> float:
        if now is None:
            now = self._clock()
        self._trim(now)
        return sum(v for _t, v in self._events)

    def fraction(self, now: Optional[float] = None) -> float:
        if now is None:
            now = self._clock()
        span = min(self.window_sec, max(now - self._born, 1e-6))
        return min(1.0, self.total(now) / span)


# ---- verdict assembly (the /v1/health body) ----

def _gauge_value(
    snap: Mapping[str, Any], name: str, **labels: str
) -> Optional[float]:
    fam = snap.get(name)
    if not isinstance(fam, Mapping):
        return None
    for s in fam.get("series", []):
        if all(s.get("labels", {}).get(k) == v for k, v in labels.items()):
            return float(s.get("value", 0.0))
    return None


def _series_by_label(
    snap: Mapping[str, Any], name: str, label: str
) -> Dict[str, float]:
    fam = snap.get(name)
    out: Dict[str, float] = {}
    if not isinstance(fam, Mapping):
        return out
    for s in fam.get("series", []):
        key = s.get("labels", {}).get(label)
        if key is not None:
            out[key] = out.get(key, 0.0) + float(s.get("value", 0.0))
    return out


def agent_health(
    entry: Mapping[str, Any], now_wall: Optional[float] = None
) -> Dict[str, Any]:
    """One agent's health row from its ``controller.agent_metrics`` entry:
    liveness plus the utilization series its obs snapshot carries. The
    rolling ``device_duty_cycle`` gauge is preferred; agents predating it
    degrade to the cumulative busy/(busy+idle) ratio."""
    if now_wall is None:
        now_wall = time.time()
    last_seen = float(entry.get("last_seen_wall", 0.0))
    snap = entry.get("obs") if isinstance(entry.get("obs"), Mapping) else {}
    busy_by_op = _series_by_label(snap, "device_busy_seconds_total", "op")
    busy = sum(busy_by_op.values())
    if not busy_by_op:
        # Pre-ISSUE-8 agents exported the counter unlabeled.
        busy = _gauge_value(snap, "device_busy_seconds_total") or 0.0
    idle = _gauge_value(snap, "device_idle_seconds_total") or 0.0
    duty = _gauge_value(snap, "device_duty_cycle")
    if duty is None and busy + idle > 0:
        duty = busy / (busy + idle)
    mfu = _series_by_label(snap, "device_mfu", "op")
    out: Dict[str, Any] = {
        "last_seen_sec_ago": round(max(0.0, now_wall - last_seen), 3),
        # Retiring member (ISSUE 10): the autoscaler must not count it as
        # live capacity, and operators see the drain in flight.
        "draining": bool(entry.get("draining")),
        "duty_cycle": round(duty, 4) if duty is not None else None,
        "device_busy_s": round(busy, 3),
        "device_busy_s_by_op": {
            op: round(v, 3) for op, v in sorted(busy_by_op.items())
        },
        "mfu": {op: round(v, 4) for op, v in sorted(mfu.items())} or None,
        "queue_depth": _gauge_value(snap, "queue_depth", queue="staged"),
    }
    return out


def build_health(
    *,
    slo_enabled: bool,
    slo_objectives: Sequence[Mapping[str, Any]] = (),
    counts: Optional[Mapping[str, int]] = None,
    queue_depth: int = 0,
    queue_by_tier: Optional[Mapping[int, int]] = None,
    starvation_age_sec: Optional[float] = None,
    agents: Optional[Mapping[str, Mapping[str, Any]]] = None,
    agent_stale_sec: float = DEFAULT_AGENT_STALE_SEC,
    now_wall: Optional[float] = None,
    partition: Optional[str] = None,
    anomalies: Sequence[Mapping[str, Any]] = (),
) -> Dict[str, Any]:
    """Assemble the ``GET /v1/health`` body. Pure: every input is data the
    controller already holds (SLO evaluations, job counts, scheduler depth,
    per-agent telemetry entries)."""
    if now_wall is None:
        now_wall = time.time()
    agents = agents or {}
    agent_rows = {
        name: agent_health(entry, now_wall=now_wall)
        for name, entry in sorted(agents.items())
    }
    stale = [
        name for name, row in agent_rows.items()
        if row["last_seen_sec_ago"] > agent_stale_sec
    ]
    for name, row in agent_rows.items():
        row["stale"] = name in stale

    reasons: List[Dict[str, Any]] = []
    verdict = "ok"
    for obj in slo_objectives:
        state = obj.get("state", "ok")
        if state == "ok":
            continue
        reasons.append({
            "kind": "slo_burn",
            "objective": obj.get("objective"),
            "state": state,
            "burn_rate_short": obj.get("burn_rate_short"),
            "burn_rate_long": obj.get("burn_rate_long"),
        })
        if state == "page":
            verdict = "page"
        elif verdict == "ok":
            verdict = "warn"
    # Confirmed anomaly episodes (ISSUE 20) warn like any other burn
    # signal — robust-baseline detection feeds the same verdict machinery.
    for ev in anomalies:
        reasons.append({
            "kind": "anomaly",
            "watch": ev.get("watch"),
            "value": ev.get("value"),
            "baseline_median": ev.get("baseline_median"),
            "z": ev.get("z"),
            "direction": ev.get("direction"),
            "wall": ev.get("wall"),
        })
        if verdict == "ok":
            verdict = "warn"
    live = [n for n in agent_rows if n not in stale]
    if queue_depth > 0 and agent_rows and not live:
        reasons.append({"kind": "no_live_agents", "queued": queue_depth})
        if verdict == "ok":
            verdict = "warn"
    elif stale and queue_depth > 0:
        reasons.append({"kind": "stale_agents", "agents": stale})
        if verdict == "ok":
            verdict = "warn"

    out = {
        "verdict": verdict,
        "reasons": reasons,
        "generated_at": round(now_wall, 3),
        "slo": {
            "enabled": bool(slo_enabled),
            "objectives": list(slo_objectives),
        },
        "queue": {
            "depth": int(queue_depth),
            "by_tier": {
                str(k): int(v)
                for k, v in sorted((queue_by_tier or {}).items())
            },
            "starvation_age_sec": (
                round(starvation_age_sec, 3)
                if starvation_age_sec is not None else None
            ),
        },
        "counts": dict(counts or {}),
        "fleet": {
            "n_agents": len(agent_rows),
            "n_stale": len(stale),
        },
        "agents": agent_rows,
    }
    if partition:
        # Partitioned control plane (ISSUE 18): which shard of the control
        # plane produced this verdict — the router's fan-out merge keys on
        # it, and a single-partition reader sees where it is pointed.
        out["partition"] = partition
    return out
