"""Rolling-baseline anomaly detection over the telemetry stream (ISSUE 20).

Each watched series keeps a bounded baseline of recent sample values and
scores every new value with a robust z-score: ``z = 0.6745·(x − median) /
MAD`` (0.6745 makes the MAD consistent with σ under normality). Median and
MAD shrug off the very outliers we hunt — a mean/stddev baseline gets
dragged toward the anomaly and stops seeing it.

Guards keep a calm seeded drain at exactly zero false positives:

- **warmup gate** — no verdicts until the baseline holds ``warmup``
  samples (the first sweeps of a drain are startup transients, not
  anomalies);
- **MAD floor** — a near-constant series has MAD ≈ 0 and would fire on
  noise; the floor is ``max(mad, mad_floor_frac·|median|, watch floor)``;
- **absolute delta floor** — per-watch ``min_delta``: queue depth moving
  1→3 is not an incident no matter how tight the baseline;
- **confirmation** — ``confirm`` consecutive anomalous samples open an
  episode; one episode emits one event (the incident layer's dedup rides
  this). Anomalous values never join the baseline, so the baseline can't
  normalize an ongoing incident; ``clear`` consecutive calm samples close
  the episode.

Deterministic: verdicts are a pure function of the sample sequence — the
chaos drill replays the same seed and gets the same (single) anomaly.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

from agent_tpu.obs.metrics import histogram_quantile

Sample = Mapping[str, Any]  # {"wall": float, "data": {fam: {key: value}}}

DEFAULT_WINDOW = 60
DEFAULT_WARMUP = 12
DEFAULT_Z = 8.0
DEFAULT_CONFIRM = 2
DEFAULT_CLEAR = 5
MAD_CONSISTENCY = 0.6745


def _fam_sum(sample: Sample, family: str) -> Optional[float]:
    series = (sample.get("data") or {}).get(family)
    if not series:
        return None
    return float(sum(series.values()))


def gauge_sum(family: str) -> Callable[[Optional[Sample], Sample],
                                       Optional[float]]:
    def extract(prev: Optional[Sample], cur: Sample) -> Optional[float]:
        return _fam_sum(cur, family)
    return extract


def gauge_mean(family: str) -> Callable[[Optional[Sample], Sample],
                                        Optional[float]]:
    def extract(prev: Optional[Sample], cur: Sample) -> Optional[float]:
        series = (cur.get("data") or {}).get(family)
        if not series:
            return None
        return float(sum(series.values())) / len(series)
    return extract


def counter_rate(family: str) -> Callable[[Optional[Sample], Sample],
                                          Optional[float]]:
    def extract(prev: Optional[Sample], cur: Sample) -> Optional[float]:
        if prev is None:
            return None
        v0, v1 = _fam_sum(prev, family), _fam_sum(cur, family)
        if v0 is None or v1 is None:
            return None
        dt = float(cur.get("wall", 0.0)) - float(prev.get("wall", 0.0))
        if dt <= 0:
            return None
        return max(0.0, (v1 - v0) / dt)
    return extract


def hist_quantile(family: str, q: float) -> Callable[
    [Optional[Sample], Sample], Optional[float]
]:
    """Quantile of the observations BETWEEN two samples, from the
    flattened ``<family>_bucket`` per-slot counter deltas (each slot is
    monotone; flatten emits the ``le`` label inside the series key)."""
    bucket_fam = f"{family}_bucket"

    def extract(prev: Optional[Sample], cur: Sample) -> Optional[float]:
        if prev is None:
            return None
        cur_b = (cur.get("data") or {}).get(bucket_fam)
        prev_b = (prev.get("data") or {}).get(bucket_fam) or {}
        if not cur_b:
            return None
        increases: Dict[float, float] = {}
        inf_inc = 0.0
        for key, v in cur_b.items():
            inc = max(0.0, float(v) - float(prev_b.get(key, 0.0)))
            try:
                labels = dict(json.loads(key))
            except ValueError:
                continue
            le = labels.get("le")
            if le == "+Inf":
                inf_inc += inc
            else:
                try:
                    edge = float(le)
                except (TypeError, ValueError):
                    continue
                increases[edge] = increases.get(edge, 0.0) + inc
        edges = sorted(increases)
        counts = [increases[e] for e in edges] + [inf_inc]
        if sum(counts) <= 0:
            return None  # no observations this interval — no signal
        return histogram_quantile(edges, counts, q)
    return extract


class Watch:
    """One monitored scalar derived from the sample stream."""

    def __init__(
        self,
        name: str,
        extract: Callable[[Optional[Sample], Sample], Optional[float]],
        direction: str = "high",   # "high" | "low" | "both"
        min_delta: float = 0.0,    # absolute |x - median| floor
        mad_floor: float = 1e-9,   # absolute MAD floor
    ) -> None:
        self.name = name
        self.extract = extract
        self.direction = direction
        self.min_delta = float(min_delta)
        self.mad_floor = float(mad_floor)


def default_watches() -> List[Watch]:
    """The issue's five: TTFT p99, queue depth, lease-error rate,
    KV-free, duty. Floors sized so ordinary drain jitter never clears
    them."""
    return [
        Watch("ttft_p99", hist_quantile("serve_ttft_seconds", 0.99),
              direction="high", min_delta=0.2, mad_floor=0.01),
        Watch("queue_depth", gauge_sum("controller_queue_depth"),
              direction="high", min_delta=10.0, mad_floor=1.0),
        Watch("lease_error_rate", counter_rate("result_post_failures_total"),
              direction="high", min_delta=0.5, mad_floor=0.1),
        Watch("kv_free", gauge_sum("serve_kv_blocks_free"),
              direction="low", min_delta=16.0, mad_floor=2.0),
        Watch("duty", gauge_mean("device_duty_cycle"),
              direction="low", min_delta=0.25, mad_floor=0.02),
    ]


def _median(values: Sequence[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return 0.5 * (ordered[mid - 1] + ordered[mid])


class AnomalyDetector:
    """Feed with consecutive samples via :meth:`observe`; confirmed
    anomalies come back as event dicts, open episodes show in
    :meth:`active` (the /v1/health ``anomaly`` warn reason)."""

    def __init__(
        self,
        watches: Optional[Sequence[Watch]] = None,
        window: int = DEFAULT_WINDOW,
        warmup: int = DEFAULT_WARMUP,
        z_thresh: float = DEFAULT_Z,
        mad_floor_frac: float = 0.05,
        confirm: int = DEFAULT_CONFIRM,
        clear: int = DEFAULT_CLEAR,
    ) -> None:
        self.watches = list(watches) if watches is not None \
            else default_watches()
        self.window = max(4, int(window))
        self.warmup = max(2, int(warmup))
        self.z_thresh = max(1.0, float(z_thresh))
        self.mad_floor_frac = max(0.0, float(mad_floor_frac))
        self.confirm = max(1, int(confirm))
        self.clear = max(1, int(clear))
        self._state: Dict[str, Dict[str, Any]] = {
            w.name: {
                "baseline": [], "streak": 0, "calm": 0,
                "active": None, "episodes": 0,
            }
            for w in self.watches
        }
        self.events_total = 0

    def _score(self, watch: Watch, baseline: List[float],
               x: float) -> Optional[Dict[str, Any]]:
        """Anomaly verdict for one value against one baseline, or None
        when calm."""
        med = _median(baseline)
        mad = _median([abs(v - med) for v in baseline])
        mad_eff = max(
            mad, self.mad_floor_frac * abs(med), watch.mad_floor,
        )
        z = MAD_CONSISTENCY * (x - med) / mad_eff
        delta = x - med
        high = (
            watch.direction in ("high", "both")
            and z >= self.z_thresh and delta >= watch.min_delta
        )
        low = (
            watch.direction in ("low", "both")
            and -z >= self.z_thresh and -delta >= watch.min_delta
        )
        if not high and not low:
            return None
        return {
            "watch": watch.name,
            "value": round(x, 6),
            "baseline_median": round(med, 6),
            "mad": round(mad_eff, 6),
            "z": round(z, 3),
            "direction": "high" if high else "low",
        }

    def observe(
        self, prev: Optional[Sample], sample: Sample
    ) -> List[Dict[str, Any]]:
        """Score one sample; returns newly-CONFIRMED anomaly events
        (one per watch per episode)."""
        events: List[Dict[str, Any]] = []
        wall = float(sample.get("wall", 0.0))
        for watch in self.watches:
            st = self._state[watch.name]
            try:
                x = watch.extract(prev, sample)
            except Exception:  # noqa: BLE001 — a malformed sample must
                # not kill the sweep; this watch just skips the beat.
                x = None
            if x is None:
                continue
            baseline: List[float] = st["baseline"]
            if len(baseline) < self.warmup:
                baseline.append(x)
                continue
            verdict = self._score(watch, baseline, x)
            if verdict is None:
                baseline.append(x)
                if len(baseline) > self.window:
                    del baseline[: len(baseline) - self.window]
                st["streak"] = 0
                if st["active"] is not None:
                    st["calm"] += 1
                    if st["calm"] >= self.clear:
                        st["active"] = None
                        st["calm"] = 0
                continue
            # Anomalous: hold it OUT of the baseline.
            st["calm"] = 0
            st["streak"] += 1
            if st["streak"] >= self.confirm and st["active"] is None:
                verdict["wall"] = round(wall, 3)
                st["active"] = verdict
                st["episodes"] += 1
                self.events_total += 1
                events.append(dict(verdict))
            elif st["active"] is not None:
                st["active"]["value"] = verdict["value"]
                st["active"]["z"] = verdict["z"]
        return events

    def active(self) -> List[Dict[str, Any]]:
        return [
            dict(st["active"])
            for st in self._state.values()
            if st["active"] is not None
        ]

    def stats(self) -> Dict[str, Any]:
        return {
            "watches": {
                name: {
                    "baseline_n": len(st["baseline"]),
                    "episodes": st["episodes"],
                    "active": st["active"] is not None,
                }
                for name, st in self._state.items()
            },
            "events_total": self.events_total,
        }
