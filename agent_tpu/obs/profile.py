"""Continuous profiling & memory telemetry (ISSUE 9).

Three independent pieces, all dependency-free by the obs charter:

- :func:`device_memory_stats` — the ONE reader of jax's per-device
  ``memory_stats()`` (used/limit/peak across *all* local devices, graceful
  ``[]`` on backends that return None — CPU does). ``runtime.describe()``,
  the sizing probe, and the agent's ``device_hbm_bytes{device,kind}`` gauges
  all go through it, so none of them can regress back to probing only
  ``devices[0]`` (the bug this module exists to fix: a ``CHIP_SLICE`` fleet
  member or dp=N mesh agent attributed HBM for one chip out of N).
- :class:`HostProfiler` — a thread-stack sampling profiler built on
  ``sys._current_frames``: a daemon thread samples every live thread's stack
  at a low fixed rate and aggregates collapsed stacks (the
  ``a;b;c count`` flamegraph.pl format, served at ``GET /v1/profile/host``).
  Answers "what was the host doing while the drain was slow" without
  attaching a debugger or redeploying under instrumentation.
- :class:`CaptureCoordinator` — controller-side bookkeeping for on-demand
  ``jax.profiler`` deep captures: ``POST /v1/profile/capture`` requests one,
  the request rides the existing lease ``alerts`` channel to the target
  agent, the agent wraps its next matching op execution in the
  already-present ``jax.profiler.trace`` hook, and the artifact path +
  summary ride the lease metrics channel back.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Mapping, Optional, Sequence

# memory_stats key → the wire/metric `kind` label.
_MEM_KINDS = (
    ("bytes_in_use", "used"),
    ("bytes_limit", "limit"),
    ("peak_bytes_in_use", "peak"),
)


def device_memory_stats(devices: Sequence[Any]) -> List[Dict[str, Any]]:
    """Per-device memory stats across *all* of ``devices``.

    Returns ``[{device, platform?, used?, limit?, peak?}, ...]`` with one
    entry per device that reported a stats mapping; keys whose counter the
    backend omitted are absent (partial dicts are normal — not every XLA
    backend exports the peak). Backends returning ``None`` (CPU) or raising
    contribute nothing, so the empty list is the clean "no HBM telemetry
    here" answer — never an error."""
    out: List[Dict[str, Any]] = []
    for i, dev in enumerate(devices):
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — telemetry must never raise
            continue
        if not isinstance(stats, Mapping):
            continue
        entry: Dict[str, Any] = {"device": str(i)}
        platform = getattr(dev, "platform", None)
        if isinstance(platform, str):
            entry["platform"] = platform
        for raw_key, kind in _MEM_KINDS:
            v = stats.get(raw_key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                entry[kind] = int(v)
        if len(entry) > (2 if "platform" in entry else 1):
            out.append(entry)
    return out


def hbm_totals(devices: Sequence[Any]) -> Optional[Dict[str, Any]]:
    """Summed used/limit/peak over every device that reported stats, plus
    the per-device breakdown — what ``runtime.describe()`` ships. ``None``
    when no device reports (CPU)."""
    per_device = device_memory_stats(devices)
    if not per_device:
        return None
    out: Dict[str, Any] = {"per_device": per_device}
    for _, kind in _MEM_KINDS:
        vals = [e[kind] for e in per_device if kind in e]
        if vals:
            out[kind] = int(sum(vals))
    return out


class HostProfiler:
    """Sampling host profiler: periodic ``sys._current_frames()`` walks
    aggregated into collapsed stacks.

    Frames render as ``file.py:function`` (definition identity, not the
    current line — a hot loop must aggregate into one stack, not one stack
    per bytecode line). Distinct-stack count is bounded (``max_stacks``);
    overflow samples aggregate under a sentinel stack so the memory bound
    holds against pathological stack diversity while the sample count stays
    truthful."""

    OVERFLOW_KEY = ("(overflow)",)

    def __init__(
        self,
        hz: float = 19.0,
        max_stacks: int = 4096,
        max_depth: int = 48,
    ) -> None:
        # Off the round-number grid on purpose: a 20 Hz sampler beats in
        # lockstep with 100ms periodic work and sees only its edges.
        self.hz = min(250.0, max(0.1, float(hz)))
        self.max_stacks = max(16, int(max_stacks))
        self.max_depth = max(4, int(max_depth))
        self._counts: Dict[tuple, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.n_samples = 0
        self.started_wall: Optional[float] = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "HostProfiler":
        if self.running:
            return self
        self._stop.clear()
        self.started_wall = time.time()
        self._thread = threading.Thread(
            target=self._loop, name="host-profiler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        period = 1.0 / self.hz
        while not self._stop.wait(period):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — the profiler must never crash
                pass            # its host; a lost sample is a lost sample

    @staticmethod
    def _frame_name(frame: Any) -> str:
        code = frame.f_code
        # ';' and ' ' are collapsed-format structure; scrub them from paths.
        fname = os.path.basename(code.co_filename).replace(";", ":")
        return f"{fname}:{code.co_name}".replace(" ", "_")

    def sample_once(self) -> None:
        """Walk every live thread's stack once and count the collapsed
        stacks. Callable directly (tests, forced flushes) — the background
        loop is just this on a timer."""
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        stacks: List[tuple] = []
        for tid, frame in frames.items():
            if tid == me:
                continue  # the sampler observing itself is pure noise
            stack: List[str] = []
            f = frame
            while f is not None and len(stack) < self.max_depth:
                stack.append(self._frame_name(f))
                f = f.f_back
            thread = str(names.get(tid, f"tid-{tid}")).replace(";", ":")
            # Root-first (flamegraph collapsed order): thread;outer;...;leaf.
            stacks.append((thread, *reversed(stack)))
        with self._lock:
            for key in stacks:
                if key not in self._counts and \
                        len(self._counts) >= self.max_stacks:
                    key = self.OVERFLOW_KEY
                self._counts[key] = self._counts.get(key, 0) + 1
            self.n_samples += 1

    def collapsed(self) -> str:
        """The flamegraph.pl collapsed-stack text: one ``a;b;c count`` line
        per distinct stack, deterministically ordered."""
        with self._lock:
            items = sorted(self._counts.items())
        return "\n".join(
            f"{';'.join(key)} {count}" for key, count in items
        ) + ("\n" if items else "")

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "samples": self.n_samples,
                "distinct_stacks": len(self._counts),
                "hz": self.hz,
                "started_wall": self.started_wall,
            }


class CaptureCoordinator:
    """On-demand deep-capture bookkeeping (the controller half).

    Lifecycle: ``request()`` (POST /v1/profile/capture) → ``pending_for()``
    hands the request to the target agent's next *granted* lease as an
    ``alerts`` entry (``kind: "profile_capture"`` — old agents ignore
    unknown alert kinds by construction) → the agent wraps one matching op
    execution in ``jax.profiler.trace`` and ships
    ``metrics["profile_captures"]`` back on a later lease →
    ``complete()`` records the artifact path + summary. Bounded; oldest
    records evict first."""

    def __init__(self, max_captures: int = 64) -> None:
        self.max_captures = max(1, int(max_captures))
        self._captures: Dict[str, Dict[str, Any]] = {}
        self._order: List[str] = []
        self._lock = threading.Lock()

    def request(
        self,
        agent: str,
        op: Optional[str] = None,
        duration_ms: Optional[float] = None,
    ) -> Dict[str, Any]:
        if not isinstance(agent, str) or not agent:
            raise ValueError("capture request needs a target agent name")
        if op is not None and (not isinstance(op, str) or not op):
            raise ValueError("op must be a non-empty string when given")
        if duration_ms is not None:
            if isinstance(duration_ms, bool) or not isinstance(
                duration_ms, (int, float)
            ) or duration_ms <= 0:
                raise ValueError("duration_ms must be a positive number")
        capture_id = f"cap-{uuid.uuid4().hex[:12]}"
        record = {
            "capture_id": capture_id,
            "agent": agent,
            "op": op,
            "duration_ms": duration_ms,
            "status": "requested",
            "requested_wall": round(time.time(), 3),
        }
        with self._lock:
            self._captures[capture_id] = record
            self._order.append(capture_id)
            while len(self._order) > self.max_captures:
                self._captures.pop(self._order.pop(0), None)
        return dict(record)

    def pending_for(self, agent: str) -> List[Dict[str, Any]]:
        """Undelivered requests targeting ``agent``, as lease-alert payloads.
        Marks them delivered — the channel is at-most-once by design (a lost
        lease response loses the capture; the operator re-requests, which is
        cheaper than building redelivery for a diagnostic)."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for cid in self._order:
                rec = self._captures.get(cid)
                if rec is None or rec["agent"] != agent \
                        or rec["status"] != "requested":
                    continue
                rec["status"] = "delivered"
                rec["delivered_wall"] = round(time.time(), 3)
                out.append({
                    "kind": "profile_capture",
                    "capture_id": cid,
                    "op": rec["op"],
                    "duration_ms": rec["duration_ms"],
                })
        return out

    def complete(self, payload: Any) -> bool:
        """Record one agent-shipped completion. Unknown/duplicate ids are
        dropped (the piggyback channel may redeliver)."""
        if not isinstance(payload, Mapping):
            return False
        cid = payload.get("capture_id")
        with self._lock:
            rec = self._captures.get(cid)
            if rec is None or rec["status"] in ("done", "error"):
                return False
            status = payload.get("status")
            rec["status"] = status if status in ("done", "error", "op_failed") \
                else "done"
            rec["completed_wall"] = round(time.time(), 3)
            for key in ("artifact", "summary", "error", "actual_duration_ms"):
                if payload.get(key) is not None:
                    rec[key] = payload[key]
        return True

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(self._captures[cid]) for cid in self._order
                    if cid in self._captures]
