"""Swarm-wide observability: metrics registry, flight recorder, scraping.

Dependency-free by design (ISSUE 2): counters/gauges/histograms with labels
rendered to Prometheus text by string formatting, a bounded ring buffer of
structured events for post-hoc diagnosis, and the parse/validate helpers the
scrape side (bench, CI smoke) uses. Agent and controller each own injectable
instances; ``get_registry()``/``get_recorder()`` are the process-global
defaults for standalone callers.
"""

from agent_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    histogram_quantile,
    merge_snapshots,
    parse_exemplars,
    parse_exposition,
    render_snapshots,
    validate_exposition,
)
from agent_tpu.obs.health import (
    RollingWindow,
    build_health,
    resolve_peak_flops,
)
from agent_tpu.obs.recorder import (
    FlightRecorder,
    default_dump_path,
    get_recorder,
    install_sigusr1_dump,
)
from agent_tpu.obs.profile import (
    CaptureCoordinator,
    HostProfiler,
    device_memory_stats,
    hbm_totals,
)
from agent_tpu.obs.slo import (
    DEFAULT_SLO_SPEC,
    Objective,
    SloTracker,
    parse_slo_spec,
)
from agent_tpu.obs.timeseries import TimeSeriesRing, points_to_rates
from agent_tpu.obs.usage import UsageLedger, sanitize_usage, stamp_usage
from agent_tpu.obs.trace import (
    Span,
    SpanBuffer,
    TraceContext,
    TraceStore,
    to_chrome_trace,
    validate_chrome_trace,
)

__all__ = [
    "CaptureCoordinator",
    "DEFAULT_SLO_SPEC",
    "HostProfiler",
    "Objective",
    "TimeSeriesRing",
    "UsageLedger",
    "device_memory_stats",
    "hbm_totals",
    "points_to_rates",
    "sanitize_usage",
    "stamp_usage",
    "RollingWindow",
    "SloTracker",
    "build_health",
    "parse_slo_spec",
    "resolve_peak_flops",
    "Span",
    "SpanBuffer",
    "TraceContext",
    "TraceStore",
    "to_chrome_trace",
    "validate_chrome_trace",
    "DEFAULT_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "FlightRecorder",
    "get_registry",
    "get_recorder",
    "histogram_quantile",
    "merge_snapshots",
    "parse_exemplars",
    "parse_exposition",
    "render_snapshots",
    "validate_exposition",
    "default_dump_path",
    "install_sigusr1_dump",
]
