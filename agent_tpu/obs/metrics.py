"""Dependency-free metrics: counters, gauges, fixed-bucket histograms.

The swarm's observability substrate (ISSUE 2): every layer records into a
``MetricsRegistry`` — thread-safe, label-aware, renderable to the Prometheus
text exposition format with nothing but string formatting (no client
library; the container must not grow a dependency for counting).

Three deliberate shapes:

- **Injectable instances.** The agent and the controller each own a registry
  (they frequently share a process in tests and bench — one global would
  conflate ``tasks_total`` as seen by the agent with the controller's view).
  A process-global default (``get_registry()``) exists for standalone
  callers and scripts.
- **Snapshots are the wire format.** ``registry.snapshot()`` is a plain
  JSON-able dict; agents push it to the controller inside the lease
  ``metrics`` channel, and ``merge_snapshots`` sums per-agent snapshots into
  the fleet aggregate that ``GET /v1/metrics`` exposes next to the
  controller's own series. Counters and histograms sum; gauges sum too
  (fleet queue depth is the sum of per-agent depths).
- **Fixed buckets.** Histograms carry their bucket bounds in the snapshot,
  so merge and quantile estimation (``histogram_quantile``) need no shared
  config. Bounds are seconds-oriented (5 ms .. 5 min) — per-task phase
  latencies, lease waits.

``parse_exposition`` / ``validate_exposition`` close the loop: bench and
``scripts/check_metrics_endpoint.py`` scrape ``/v1/metrics`` and fail on
malformed output instead of trusting the renderer.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

# Seconds-oriented bounds: task phases run 5ms (host stage of a tiny shard)
# to minutes (a cold-compile execute); queue waits can reach minutes on a
# backed-up drain. +Inf is implicit (the overflow slot).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _escape_label(value: Any) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_num(value: float) -> str:
    """Prometheus sample value: integers render bare, floats via repr."""
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_bound(bound: float) -> str:
    """``le`` label text: '0.005', '1', '+Inf'."""
    if bound == float("inf"):
        return "+Inf"
    return "%g" % bound


class _Metric:
    """Base: one named family holding labeled series. Series mutation is
    guarded by the owning registry's lock (no per-metric locks to rank)."""

    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str],
        lock: threading.Lock,
    ) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name!r}")
        self.name = name
        self.help = help
        self.labelnames: Tuple[str, ...] = tuple(labelnames)
        self._lock = lock
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Mapping[str, Any]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up ({amount})")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    def value(self, **labels: Any) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    def dec(self, amount: float = 1.0, **labels: Any) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: Any) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))


class Histogram(_Metric):
    """Fixed-bucket histogram. Each series stores per-bucket (non-cumulative)
    counts with a final +Inf overflow slot, plus sum and count — cumulation
    happens at render time, summation at merge time."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str],
        lock: threading.Lock,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> None:
        super().__init__(name, help, labelnames, lock)
        bounds = tuple(float(b) for b in buckets)
        if not bounds or list(bounds) != sorted(set(bounds)):
            raise ValueError(f"{name}: buckets must be sorted and unique")
        if any(b != b or b == float("inf") for b in bounds):
            raise ValueError(f"{name}: buckets must be finite (+Inf is implicit)")
        self.buckets = bounds

    def observe(
        self,
        value: float,
        exemplar: Optional[Mapping[str, Any]] = None,
        **labels: Any,
    ) -> None:
        """Record one observation. ``exemplar`` (OpenMetrics: a small label
        set like ``{"trace_id": job_id}``) is attached to the landing
        bucket — latest observation wins — and rendered as an exemplar on
        that bucket's exposition line, linking the histogram to the trace
        that produced the sample (ISSUE 5)."""
        v = float(value)
        key = self._key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0,
                    "count": 0,
                }
                self._series[key] = series
            i = len(self.buckets)  # +Inf slot
            for j, bound in enumerate(self.buckets):
                if v <= bound:
                    i = j
                    break
            series["counts"][i] += 1
            series["sum"] += v
            series["count"] += 1
            if exemplar:
                series.setdefault("exemplars", {})[str(i)] = {
                    "labels": {str(k): str(lv) for k, lv in exemplar.items()},
                    "value": v,
                    "ts": time.time(),
                }


class MetricsRegistry:
    """Thread-safe named collection of metrics; get-or-create semantics so
    independent modules can reference the same family."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kwargs: Any) -> Any:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.labelnames != tuple(
                    labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"type/labels"
                    )
                return existing
            metric = cls(name, help, labelnames, self._lock, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able dump of every series — the lease-push wire format and
        the input to ``merge_snapshots`` / ``render_snapshots``."""
        out: Dict[str, Any] = {}
        with self._lock:
            for name, m in self._metrics.items():
                fam: Dict[str, Any] = {
                    "type": m.kind,
                    "help": m.help,
                    "labels": list(m.labelnames),
                    "series": [],
                }
                if isinstance(m, Histogram):
                    fam["buckets"] = list(m.buckets)
                for key, value in m._series.items():
                    labels = dict(zip(m.labelnames, key))
                    if isinstance(m, Histogram):
                        entry = {
                            "labels": labels,
                            "counts": list(value["counts"]),
                            "sum": value["sum"],
                            "count": value["count"],
                        }
                        if value.get("exemplars"):
                            # Only when present: snapshots without exemplars
                            # keep the exact pre-ISSUE-5 shape (merge and
                            # old scrapers unaffected).
                            entry["exemplars"] = {
                                k: dict(v)
                                for k, v in value["exemplars"].items()
                            }
                        fam["series"].append(entry)
                    else:
                        fam["series"].append(
                            {"labels": labels, "value": value}
                        )
                out[name] = fam
        return out

    def render(self) -> str:
        return render_snapshots([(self.snapshot(), {})])


# ---- process-global default (standalone callers; tests inject instances) ----

_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default_registry


# ---- snapshot algebra ----

def _series_key(labels: Mapping[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def merge_snapshots(snapshots: Iterable[Mapping[str, Any]]) -> Dict[str, Any]:
    """Sum same-name/same-labels series across snapshots (the fleet merge:
    one snapshot per agent → fleet totals). Counters, gauges, and histogram
    buckets all add; families whose type or buckets disagree keep the first
    definition and skip conflicting series (a half-upgraded fleet must not
    corrupt the merged view)."""
    out: Dict[str, Any] = {}
    for snap in snapshots:
        if not isinstance(snap, Mapping):
            continue
        for name, fam in snap.items():
            if not isinstance(fam, Mapping) or "series" not in fam:
                continue
            dst = out.get(name)
            if dst is None:
                dst = {
                    "type": fam.get("type", "untyped"),
                    "help": fam.get("help", ""),
                    "labels": list(fam.get("labels", [])),
                    "series": [],
                    "_index": {},
                }
                if "buckets" in fam:
                    dst["buckets"] = list(fam["buckets"])
                out[name] = dst
            fam_buckets = list(fam["buckets"]) if "buckets" in fam else None
            if dst["type"] != fam.get("type") or \
                    dst.get("buckets") != fam_buckets:
                continue
            for s in fam.get("series", []):
                labels = s.get("labels", {})
                key = _series_key(labels)
                have = dst["_index"].get(key)
                if dst["type"] == "histogram":
                    if have is None:
                        have = {
                            "labels": dict(labels),
                            "counts": [0] * len(s.get("counts", [])),
                            "sum": 0.0,
                            "count": 0,
                        }
                        dst["_index"][key] = have
                        dst["series"].append(have)
                    counts = s.get("counts", [])
                    if len(counts) == len(have["counts"]):
                        have["counts"] = [
                            a + b for a, b in zip(have["counts"], counts)
                        ]
                        have["sum"] += float(s.get("sum", 0.0))
                        have["count"] += int(s.get("count", 0))
                        for slot, ex in (s.get("exemplars") or {}).items():
                            if not isinstance(ex, Mapping):
                                continue
                            dst_ex = have.setdefault("exemplars", {})
                            prev = dst_ex.get(slot)
                            # Latest observation wins across the fleet.
                            if prev is None or float(ex.get("ts", 0.0)) >= \
                                    float(prev.get("ts", 0.0)):
                                dst_ex[slot] = dict(ex)
                else:
                    if have is None:
                        have = {"labels": dict(labels), "value": 0.0}
                        dst["_index"][key] = have
                        dst["series"].append(have)
                    have["value"] += float(s.get("value", 0.0))
    for fam in out.values():
        fam.pop("_index", None)
    return out


def render_snapshots(
    parts: Sequence[Tuple[Mapping[str, Any], Mapping[str, str]]]
) -> str:
    """Render snapshots into one Prometheus text exposition.

    ``parts`` is ``[(snapshot, extra_labels), ...]`` — extra labels (e.g.
    ``{"agent": "tpu-vm-3"}``) are stamped onto every series of that
    snapshot, which is how one exposition can carry the controller's own
    series next to per-agent or fleet-merged ones without name collisions.
    One HELP/TYPE header per family regardless of how many parts carry it;
    a family re-appearing with a different type is skipped (exposition
    validity beats completeness).
    """
    families: Dict[str, Dict[str, Any]] = {}
    order: List[str] = []
    for snap, extra in parts:
        if not isinstance(snap, Mapping):
            continue
        for name, fam in snap.items():
            if not isinstance(fam, Mapping) or not _NAME_RE.match(str(name)):
                continue
            entry = families.get(name)
            if entry is None:
                entry = {
                    "type": fam.get("type", "untyped"),
                    "help": fam.get("help", ""),
                    "chunks": [],
                }
                families[name] = entry
                order.append(name)
            elif entry["type"] != fam.get("type"):
                continue
            entry["chunks"].append((fam, dict(extra or {})))

    lines: List[str] = []
    for name in order:
        entry = families[name]
        kind = entry["type"]
        if entry["help"]:
            lines.append(f"# HELP {name} {_escape_help(entry['help'])}")
        lines.append(f"# TYPE {name} {kind}")
        for fam, extra in entry["chunks"]:
            for s in fam.get("series", []):
                labels = {**s.get("labels", {}), **extra}
                if kind == "histogram":
                    bounds = [float(b) for b in fam.get("buckets", [])]
                    counts = list(s.get("counts", []))
                    exemplars = s.get("exemplars") or {}
                    cum = 0
                    for j, (bound, c) in enumerate(zip(bounds, counts)):
                        cum += c
                        lines.append(
                            f"{name}_bucket"
                            f"{_labels_text({**labels, 'le': _fmt_bound(bound)})}"
                            f" {cum}"
                            f"{_exemplar_text(exemplars.get(str(j)))}"
                        )
                    lines.append(
                        f"{name}_bucket"
                        f"{_labels_text({**labels, 'le': '+Inf'})}"
                        f" {int(s.get('count', 0))}"
                        f"{_exemplar_text(exemplars.get(str(len(bounds))))}"
                    )
                    lines.append(
                        f"{name}_sum{_labels_text(labels)}"
                        f" {_fmt_num(s.get('sum', 0.0))}"
                    )
                    lines.append(
                        f"{name}_count{_labels_text(labels)}"
                        f" {int(s.get('count', 0))}"
                    )
                else:
                    lines.append(
                        f"{name}{_labels_text(labels)}"
                        f" {_fmt_num(s.get('value', 0.0))}"
                    )
    return "\n".join(lines) + ("\n" if lines else "")


def _labels_text(labels: Mapping[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _exemplar_text(exemplar: Optional[Mapping[str, Any]]) -> str:
    """OpenMetrics exemplar suffix for one bucket sample:
    `` # {trace_id="job-x"} 0.052 1700000000.5`` — the metrics→traces link
    (ISSUE 5). Empty string when the bucket carries none."""
    if not exemplar or not isinstance(exemplar.get("labels"), Mapping):
        return ""
    labels = _labels_text(exemplar["labels"])
    if not labels:
        return ""
    out = f" # {labels} {_fmt_num(float(exemplar.get('value', 0.0)))}"
    ts = exemplar.get("ts")
    if isinstance(ts, (int, float)) and not isinstance(ts, bool):
        out += f" {round(float(ts), 3)}"
    return out


def histogram_quantile(
    buckets: Sequence[float], counts: Sequence[int], q: float
) -> Optional[float]:
    """Estimate the q-quantile (0..1) from per-bucket counts (+Inf slot
    last), linearly interpolating within the landing bucket — the same
    estimate Prometheus's ``histogram_quantile`` makes. None when empty.
    Values in the +Inf slot clamp to the largest finite bound.

    **Pinned error bound** (ISSUE 8 satellite, property-tested in
    ``tests/test_obs.py::TestQuantileErrorBound``): for observations within
    the finite bucket range, the estimate lands in the same bucket as the
    exact sample quantile, so the absolute error is **at most one bucket
    width** (the width of the bucket containing the true quantile). This
    holds for FLEET-MERGED snapshots too: ``merge_snapshots`` sums
    per-bucket counts losslessly (every agent shares the fixed
    ``DEFAULT_BUCKETS``), so a merged estimate is exactly the estimate the
    pooled samples would have produced — merging adds NO error beyond the
    single-histogram bound. Observations beyond the largest finite bound
    land in +Inf and clamp to that bound, where the error is unbounded by
    construction; size the top bucket above the latencies you must judge."""
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            if i >= len(buckets):  # +Inf slot
                return float(buckets[-1]) if buckets else None
            lower = float(buckets[i - 1]) if i > 0 else 0.0
            upper = float(buckets[i])
            if c <= 0:
                return upper
            frac = (target - (cum - c)) / c
            return lower + (upper - lower) * frac
    return float(buckets[-1]) if buckets else None


# ---- exposition parsing / validation (the scrape side) ----

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"        # metric name
    r"(?:\{(.*)\})?"                        # optional label block
    r"\s+"
    r"([^\s]+)"                             # value
    r"(?:\s+[0-9]+)?$"                      # optional timestamp
)
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"'
)
# OpenMetrics exemplar suffix on a sample line:
#   `` # {trace_id="job-x"} 0.052 1700000000.5`` (timestamp optional).
# Split off BEFORE the sample regex — the greedy label block would
# otherwise swallow the exemplar's braces into the labels.
_EXEMPLAR_SUFFIX_RE = re.compile(
    r"\s#\s\{(.*)\}\s+([^\s]+)(?:\s+([0-9.eE+-]+))?\s*$"
)


def _split_exemplar(
    line: str,
) -> Tuple[str, Optional[Tuple[str, str, Optional[str]]]]:
    m = _EXEMPLAR_SUFFIX_RE.search(line)
    if m is None:
        return line, None
    return line[: m.start()], (m.group(1), m.group(2), m.group(3))


def _unescape_label(value: str) -> str:
    return (
        value.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def parse_exposition(
    text: str,
) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Prometheus text → ``{sample_name: [(labels, value), ...]}``.

    Histogram component samples keep their suffixed names
    (``x_bucket``/``x_sum``/``x_count``). Malformed lines raise ValueError —
    scraping callers that prefer tolerance should run
    ``validate_exposition`` first.
    """
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        line, _exemplar = _split_exemplar(line)
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        name, labelblock, raw = m.group(1), m.group(2), m.group(3)
        labels: Dict[str, str] = {}
        if labelblock:
            consumed = 0
            for pm in _LABEL_PAIR_RE.finditer(labelblock):
                labels[pm.group(1)] = _unescape_label(pm.group(2))
                consumed += 1
            # every comma-separated pair must have parsed
            expect = [p for p in re.split(r",(?=[a-zA-Z_])", labelblock) if p]
            if consumed != len(expect):
                raise ValueError(
                    f"line {lineno}: malformed labels {labelblock!r}"
                )
        try:
            value = float(raw)
        except ValueError as exc:
            raise ValueError(
                f"line {lineno}: non-numeric value {raw!r}"
            ) from exc
        out.setdefault(name, []).append((labels, value))
    return out


def parse_exemplars(
    text: str,
) -> Dict[str, List[Tuple[Dict[str, str], Dict[str, str], float]]]:
    """Exemplars per sample name: ``{sample_name: [(sample_labels,
    exemplar_labels, exemplar_value), ...]}`` — what the trace-pipeline
    smoke uses to assert ``task_phase_seconds`` buckets link to real job
    ids. Lines without exemplars are skipped; malformed ones raise."""
    out: Dict[str, List[Tuple[Dict[str, str], Dict[str, str], float]]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        line, exemplar = _split_exemplar(line)
        if exemplar is None:
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        labels: Dict[str, str] = {}
        if m.group(2):
            for pm in _LABEL_PAIR_RE.finditer(m.group(2)):
                labels[pm.group(1)] = _unescape_label(pm.group(2))
        ex_block, ex_raw, _ex_ts = exemplar
        ex_labels: Dict[str, str] = {}
        for pm in _LABEL_PAIR_RE.finditer(ex_block):
            ex_labels[pm.group(1)] = _unescape_label(pm.group(2))
        try:
            ex_value = float(ex_raw)
        except ValueError as exc:
            raise ValueError(
                f"line {lineno}: non-numeric exemplar value {ex_raw!r}"
            ) from exc
        out.setdefault(m.group(1), []).append((labels, ex_labels, ex_value))
    return out


def validate_exposition(
    text: str, required: Iterable[str] = ()
) -> List[str]:
    """Structural check of one exposition; returns problems (empty = valid).

    Catches: malformed sample/comment lines, samples whose family carries no
    ``# TYPE`` declaration, duplicate TYPE declarations, histogram families
    missing their ``_sum``/``_count``/``+Inf`` samples, and missing
    ``required`` family names. This is the checker
    ``scripts/check_metrics_endpoint.py`` and the tests share.
    """
    problems: List[str] = []
    types: Dict[str, str] = {}
    samples: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if not stripped:
            continue
        if stripped.startswith("#"):
            parts = stripped.split(None, 3)
            if len(parts) >= 2 and parts[1] == "TYPE":
                if len(parts) < 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"
                ):
                    problems.append(f"line {lineno}: malformed TYPE line")
                elif parts[2] in types:
                    problems.append(
                        f"line {lineno}: duplicate TYPE for {parts[2]}"
                    )
                else:
                    types[parts[2]] = parts[3]
            continue
        stripped, exemplar = _split_exemplar(stripped)
        m = _SAMPLE_RE.match(stripped)
        if m is None:
            problems.append(f"line {lineno}: malformed sample {stripped!r}")
            continue
        name, labelblock, raw = m.group(1), m.group(2), m.group(3)
        if exemplar is not None:
            if not name.endswith("_bucket"):
                problems.append(
                    f"line {lineno}: exemplar on non-bucket sample {name}"
                )
            elif not _LABEL_PAIR_RE.search(exemplar[0]):
                problems.append(
                    f"line {lineno}: malformed exemplar labels "
                    f"{exemplar[0]!r}"
                )
            else:
                try:
                    float(exemplar[1])
                except ValueError:
                    problems.append(
                        f"line {lineno}: non-numeric exemplar value "
                        f"{exemplar[1]!r}"
                    )
        try:
            float(raw)
        except ValueError:
            problems.append(f"line {lineno}: non-numeric value {raw!r}")
            continue
        labels: Dict[str, str] = {}
        if labelblock:
            for pm in _LABEL_PAIR_RE.finditer(labelblock):
                labels[pm.group(1)] = _unescape_label(pm.group(2))
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and types.get(base) == "histogram":
                family = base
                break
        if family not in types:
            problems.append(
                f"line {lineno}: sample {name} has no TYPE declaration"
            )
        samples.setdefault(name, []).append((labels, float(raw)))
    for fam, kind in types.items():
        if kind != "histogram":
            continue
        if not any(
            f"{fam}{sfx}" in samples for sfx in ("_bucket", "_sum", "_count")
        ):
            continue  # declared but unobserved family — legal exposition
        if f"{fam}_sum" not in samples or f"{fam}_count" not in samples:
            problems.append(f"histogram {fam} missing _sum/_count samples")
        if not any(
            lbl.get("le") == "+Inf" for lbl, _ in samples.get(f"{fam}_bucket", [])
        ):
            problems.append(f"histogram {fam} missing +Inf bucket")
    for name in required:
        present = name in types or name in samples or any(
            s.startswith(name + "_") for s in samples
        )
        if not present:
            problems.append(f"required series {name} absent")
    return problems
