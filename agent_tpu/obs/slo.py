"""SLO engine: declarative objectives, sliding windows, burn-rate alerts.

The judgment layer over the metric/trace firehose (ISSUE 8): PR 2 gave the
swarm counters and PR 5 gave it causal traces, but nothing *evaluated* them
against an objective. This module turns submit→apply latencies and
success/failure outcomes into:

- **attainment** — the fraction of requests meeting each latency/availability
  target over a sliding window;
- **error-budget burn rate** — Google-SRE style: the rate at which the
  objective's error budget (``1 - target``) is being consumed, measured over
  a short (default 5m) and a long (default 1h) window;
- **alert states** — ``ok | warn | page`` via multi-window thresholds with
  hysteresis (a level is entered when BOTH windows exceed its threshold and
  only exits once the short-window burn falls below ``exit_frac`` of the
  entry threshold, so a burn oscillating around the line cannot flap the
  pager).

Objectives are declarative and env/JSON-configured
(``SLO_SPEC='[{"tier":8,"p99_ms":250,"availability":0.999}]'``), keyed by
any subset of ``{tier, tenant, op}`` — an absent key matches everything.
``tier`` is the scheduler's priority tier (ISSUE 4), so "the interactive
class" is simply ``{"tier": 8}``.

Design notes:

- **Sliding multi-window histogram.** Each objective owns a ring of
  time-bucketed cells (cell width = ``window_short / 5``); a cell carries
  fixed-bucket latency counts (the same ``DEFAULT_BUCKETS`` the metrics
  histograms use), exact over-threshold counts per latency target, and an
  error count. Window reads merge whole cells, so a "5m window" is accurate
  to one cell width — the documented granularity, the price of O(1) memory.
- **Observation is O(objectives).** One ``observe`` per terminal job: match
  each objective, bump a handful of ints. No allocation on the hot path
  beyond the once-per-cell rollover.
- **Injectable clock.** The tracker runs on the controller's monotonic
  clock so tests (and the CI smoke) drive window rollover deterministically.
- **No env reads here.** ``SLO_ENABLED`` gating lives in the controller
  (``config.SloConfig``); a tracker that exists is always on.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from agent_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    histogram_quantile,
)

# Alert severity order (gauge encoding: slo_alert_state value).
STATES = ("ok", "warn", "page")
_RANK = {s: i for i, s in enumerate(STATES)}

# The built-in objectives when SLO_SPEC is unset: judge the interactive
# priority tier (ISSUE 4's tier 8+ = urgent class) on tail latency and
# availability, plus — ISSUE 15 — the serving path's time-to-first-token
# (``metric: "ttft"``, fed by the controller's /v1/infer completion
# fan-out; nothing else observes that metric, so the objective idles on
# batch-only deployments). Deliberately generous (1s p99 latency, 2.5s
# p99 TTFT) — a default must not page a healthy bulk-oriented deployment;
# operators tighten it per deployment.
DEFAULT_SLO_SPEC = (
    '[{"name": "interactive", "tier": 8, "p99_ms": 1000, '
    '"availability": 0.999},'
    ' {"name": "interactive_ttft", "tier": 8, "metric": "ttft", '
    '"p99_ms": 2500, "availability": 0.999}]'
)

# Observation streams an objective may judge: submit→apply latency (the
# default every terminal job feeds) or serving time-to-first-token.
METRICS = ("latency", "ttft")

# Latency percentile keys the spec may carry: "p50_ms" → quantile 0.50.
_PCTL_KEYS = (("p50_ms", 0.50), ("p95_ms", 0.95), ("p99_ms", 0.99))


@dataclass(frozen=True)
class Objective:
    """One declarative objective. Selector fields (``tier``/``tenant``/
    ``op``) are exact-match filters; None matches everything. Targets:
    ``pXX_ms`` ("XX% of matching requests complete within T ms") and
    ``availability`` ("this fraction must succeed")."""

    name: str
    tier: Optional[int] = None
    tenant: Optional[str] = None
    op: Optional[str] = None
    p50_ms: Optional[float] = None
    p95_ms: Optional[float] = None
    p99_ms: Optional[float] = None
    availability: Optional[float] = None
    # Which observation stream this objective judges (ISSUE 15): "latency"
    # (submit→apply, the historical stream) or "ttft" (serving
    # time-to-first-token). An objective only sees observations of its own
    # metric — a TTFT target never judges batch-job latencies.
    metric: str = "latency"

    def matches(
        self, tier: Any, tenant: Any, op: Any, metric: str = "latency"
    ) -> bool:
        if self.metric != metric:
            return False
        if self.tier is not None and tier != self.tier:
            return False
        if self.tenant is not None and tenant != self.tenant:
            return False
        if self.op is not None and op != self.op:
            return False
        return True

    def latency_targets(self) -> List[Tuple[str, float, float]]:
        """``[(key, budget_fraction, threshold_seconds), ...]`` — a p99
        target means at most 1% of requests may exceed the threshold, so
        its error budget is 0.01."""
        out = []
        for key, q in _PCTL_KEYS:
            t_ms = getattr(self, key)
            if t_ms is not None:
                out.append((key, 1.0 - q, float(t_ms) / 1e3))
        return out

    def selector(self) -> Dict[str, Any]:
        return {
            k: v
            for k, v in (
                ("tier", self.tier), ("tenant", self.tenant), ("op", self.op)
            )
            if v is not None
        }


def parse_slo_spec(raw: Optional[str]) -> List[Objective]:
    """``SLO_SPEC`` JSON → objectives. Empty/None → the built-in default.
    Malformed specs raise ValueError at parse time (controller boot) — a
    typo'd objective silently judging nothing is the failure mode this
    refuses."""
    text = (raw or "").strip() or DEFAULT_SLO_SPEC
    try:
        entries = json.loads(text)
    except ValueError as exc:
        raise ValueError(f"SLO_SPEC is not valid JSON: {exc}") from exc
    if not isinstance(entries, list):
        raise ValueError("SLO_SPEC must be a JSON list of objectives")
    out: List[Objective] = []
    seen = set()
    for i, e in enumerate(entries):
        if not isinstance(e, Mapping):
            raise ValueError(f"SLO_SPEC[{i}] must be an object, got {e!r}")
        unknown = set(e) - {
            "name", "tier", "tenant", "op", "metric",
            "p50_ms", "p95_ms", "p99_ms", "availability",
        }
        if unknown:
            raise ValueError(f"SLO_SPEC[{i}]: unknown keys {sorted(unknown)}")
        metric = e.get("metric", "latency")
        if metric not in METRICS:
            raise ValueError(
                f"SLO_SPEC[{i}]: metric must be one of {METRICS}, "
                f"got {metric!r}"
            )
        tier = e.get("tier")
        if tier is not None and (
            isinstance(tier, bool) or not isinstance(tier, int)
        ):
            raise ValueError(f"SLO_SPEC[{i}]: tier must be an int")
        for key in ("p50_ms", "p95_ms", "p99_ms"):
            v = e.get(key)
            if v is not None and (
                isinstance(v, bool)
                or not isinstance(v, (int, float)) or v <= 0
            ):
                raise ValueError(f"SLO_SPEC[{i}]: {key} must be > 0")
        avail = e.get("availability")
        if avail is not None and (
            isinstance(avail, bool)
            or not isinstance(avail, (int, float))
            or not 0.0 < avail < 1.0
        ):
            raise ValueError(
                f"SLO_SPEC[{i}]: availability must be in (0, 1)"
            )
        if avail is None and not any(
            e.get(k) is not None for k, _q in _PCTL_KEYS
        ):
            raise ValueError(
                f"SLO_SPEC[{i}]: needs at least one target "
                "(pXX_ms or availability)"
            )
        name = e.get("name")
        if name is None:
            sel = "_".join(
                f"{k}{e[k]}" for k in ("tier", "tenant", "op")
                if e.get(k) is not None
            )
            name = sel or f"objective{i}"
        name = str(name)
        if name in seen:
            raise ValueError(f"SLO_SPEC[{i}]: duplicate objective name {name!r}")
        seen.add(name)
        out.append(Objective(
            name=name,
            tier=tier,
            tenant=str(e["tenant"]) if e.get("tenant") is not None else None,
            op=str(e["op"]) if e.get("op") is not None else None,
            p50_ms=e.get("p50_ms"),
            p95_ms=e.get("p95_ms"),
            p99_ms=e.get("p99_ms"),
            availability=avail,
            metric=str(metric),
        ))
    return out


class _Cell:
    """One time cell of the sliding window: fixed-bucket latency counts plus
    exact per-target breach counts (bucket edges rarely align with a target
    threshold, so breaches are counted at observe time, not re-derived)."""

    __slots__ = ("bin", "counts", "total", "sum", "errors", "slow")

    def __init__(self, bin_index: int, n_targets: int, n_buckets: int) -> None:
        self.bin = bin_index
        self.counts = [0] * (n_buckets + 1)  # +Inf overflow slot
        self.total = 0
        self.sum = 0.0
        self.errors = 0
        self.slow = [0] * n_targets


class _ObjectiveWindow:
    """Ring of cells for one objective. Cell width = short_window / 5 (the
    SRE convention: a window sees ≥ 5 cells, so a read is accurate to 20%
    of the short window); ring length covers the long window."""

    def __init__(
        self,
        objective: Objective,
        short_sec: float,
        long_sec: float,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> None:
        self.objective = objective
        self.buckets = tuple(float(b) for b in buckets)
        self.cell_sec = max(short_sec / 5.0, 1e-6)
        self.n_cells = int(long_sec / self.cell_sec) + 1
        self.targets = objective.latency_targets()
        self._cells: "collections.deque[_Cell]" = collections.deque(
            maxlen=self.n_cells
        )
        self.state = "ok"
        self.state_since: Optional[float] = None

    def observe(self, latency_s: float, ok: bool, now: float) -> None:
        bin_index = int(now / self.cell_sec)
        cell = self._cells[-1] if self._cells else None
        if cell is None or cell.bin != bin_index:
            cell = _Cell(bin_index, len(self.targets), len(self.buckets))
            self._cells.append(cell)
        v = float(latency_s)
        i = len(self.buckets)
        for j, bound in enumerate(self.buckets):
            if v <= bound:
                i = j
                break
        cell.counts[i] += 1
        cell.total += 1
        cell.sum += v
        if not ok:
            cell.errors += 1
        for t, (_key, _budget, threshold) in enumerate(self.targets):
            if v > threshold:
                cell.slow[t] += 1

    def window(self, seconds: float, now: float) -> Dict[str, Any]:
        """Merged view of the cells inside ``[now - seconds, now]`` (whole
        cells — accuracy is one cell width)."""
        min_bin = int((now - seconds) / self.cell_sec)
        counts = [0] * (len(self.buckets) + 1)
        total = 0
        total_sum = 0.0
        errors = 0
        slow = [0] * len(self.targets)
        for cell in self._cells:
            if cell.bin < min_bin:
                continue
            for i, c in enumerate(cell.counts):
                counts[i] += c
            total += cell.total
            total_sum += cell.sum
            errors += cell.errors
            for t, s in enumerate(cell.slow):
                slow[t] += s
        return {
            "counts": counts, "total": total, "sum": total_sum,
            "errors": errors, "slow": slow,
        }


def _window_stats(
    ow: _ObjectiveWindow, w: Dict[str, Any]
) -> Dict[str, Any]:
    """Burn rate / attainment / quantiles for one merged window view.

    Burn rate per target = (bad fraction) / (error budget); the objective's
    burn is the max across targets — the binding constraint pages first.
    """
    total = w["total"]
    obj = ow.objective
    out: Dict[str, Any] = {
        "requests": total,
        "burn_rate": 0.0,
        "attainment": None,
        "targets": {},
    }
    if total <= 0:
        return out
    burn = 0.0
    attain = 1.0
    for t, (key, budget, threshold) in enumerate(ow.targets):
        bad_frac = w["slow"][t] / total
        target_burn = bad_frac / budget if budget > 0 else 0.0
        burn = max(burn, target_burn)
        attained = 1.0 - bad_frac
        attain = min(attain, attained)
        out["targets"][key] = {
            "threshold_ms": round(threshold * 1e3, 3),
            "attained": round(attained, 6),
            "target": round(1.0 - budget, 6),
            "burn_rate": round(target_burn, 4),
        }
    if obj.availability is not None:
        budget = 1.0 - obj.availability
        bad_frac = w["errors"] / total
        target_burn = bad_frac / budget if budget > 0 else 0.0
        burn = max(burn, target_burn)
        attained = 1.0 - bad_frac
        attain = min(attain, attained)
        out["targets"]["availability"] = {
            "attained": round(attained, 6),
            "target": round(obj.availability, 6),
            "burn_rate": round(target_burn, 4),
        }
    out["burn_rate"] = round(burn, 4)
    out["attainment"] = round(attain, 6)
    for q, label in ((0.5, "p50_ms"), (0.99, "p99_ms")):
        est = histogram_quantile(ow.buckets, w["counts"], q)
        out[label] = round(est * 1e3, 3) if est is not None else None
    return out


class SloTracker:
    """Per-objective sliding windows + the burn-rate alert state machine.

    ``on_alert(result_dict, old_state, new_state)`` fires on every state
    transition (under the tracker lock held briefly; callers must not call
    back into the tracker from it). The controller uses it for recorder
    events and the page-entry flight-recorder auto-dump.
    """

    def __init__(
        self,
        objectives: Sequence[Objective],
        registry: Optional[MetricsRegistry] = None,
        clock: Callable[[], float] = None,
        window_short_sec: float = 300.0,
        window_long_sec: float = 3600.0,
        burn_warn: float = 3.0,
        burn_page: float = 10.0,
        burn_exit_frac: float = 0.5,
        on_alert: Optional[Callable[..., None]] = None,
    ) -> None:
        self.objectives = list(objectives)
        self.window_short_sec = float(window_short_sec)
        self.window_long_sec = max(float(window_long_sec), self.window_short_sec)
        self.burn_warn = float(burn_warn)
        self.burn_page = max(float(burn_page), self.burn_warn)
        self.burn_exit_frac = min(1.0, max(0.0, float(burn_exit_frac)))
        self.on_alert = on_alert
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._windows = [
            _ObjectiveWindow(
                o, self.window_short_sec, self.window_long_sec
            )
            for o in self.objectives
        ]
        self._last_eval: Optional[List[Dict[str, Any]]] = None
        self._last_eval_at = float("-inf")
        self._m_attain = self._m_burn = self._m_budget = None
        self._m_state = self._m_transitions = None
        if registry is not None:
            self._m_attain = registry.gauge(
                "slo_attainment",
                "Fraction of requests meeting the objective's binding "
                "target, per sliding window", ("objective", "window"))
            self._m_burn = registry.gauge(
                "slo_burn_rate",
                "Error-budget burn rate (1.0 = budget consumed exactly at "
                "the window's pace)", ("objective", "window"))
            self._m_budget = registry.gauge(
                "slo_error_budget_remaining",
                "Error budget left over the long window (1 = untouched, "
                "0 = exhausted)", ("objective",))
            self._m_state = registry.gauge(
                "slo_alert_state",
                "Burn-rate alert state (0=ok, 1=warn, 2=page)",
                ("objective",))
            self._m_transitions = registry.counter(
                "slo_alert_transitions_total",
                "Alert state transitions by entered state",
                ("objective", "state"))

    # ---- feed ----

    def observe(
        self,
        latency_s: float,
        ok: bool,
        tier: Any = None,
        tenant: Any = None,
        op: Any = None,
        now: Optional[float] = None,
        metric: str = "latency",
    ) -> None:
        """Record one completed request against every matching objective.
        O(objectives); a handful of integer bumps per match. ``metric``
        routes the observation stream — submit→apply latencies feed the
        default ``latency`` objectives, serving TTFT samples feed
        ``metric: "ttft"`` ones (ISSUE 15), never each other."""
        if now is None:
            now = self._clock()
        with self._lock:
            for ow in self._windows:
                if ow.objective.matches(tier, tenant, op, metric=metric):
                    ow.observe(latency_s, ok, now)

    # ---- judgment ----

    def _next_state(self, cur: str, burn_s: float, burn_l: float) -> str:
        """Multi-window thresholds with hysteresis: enter a level when BOTH
        windows burn above it; hold the current level until the short burn
        falls below ``exit_frac`` of its entry threshold (the short window
        recovers first, so recovery is prompt but not flappy)."""
        if burn_s >= self.burn_page and burn_l >= self.burn_page:
            target = "page"
        elif burn_s >= self.burn_warn and burn_l >= self.burn_warn:
            target = "warn"
        else:
            target = "ok"
        if _RANK[target] >= _RANK[cur]:
            return target
        exit_thr = (
            self.burn_page if cur == "page" else self.burn_warn
        ) * self.burn_exit_frac
        if burn_s >= exit_thr:
            return cur  # hysteresis hold
        return target

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Judge every objective now: window stats, burn rates, alert state
        (advancing the state machine), gauges. Returns one dict per
        objective — the ``slo.objectives`` block of ``GET /v1/health``."""
        if now is None:
            now = self._clock()
        results: List[Dict[str, Any]] = []
        transitions: List[Tuple[Dict[str, Any], str, str]] = []
        with self._lock:
            for ow in self._windows:
                short = _window_stats(
                    ow, ow.window(self.window_short_sec, now)
                )
                long = _window_stats(ow, ow.window(self.window_long_sec, now))
                old = ow.state
                new = self._next_state(
                    old, short["burn_rate"], long["burn_rate"]
                )
                if new != old:
                    ow.state = new
                    ow.state_since = now
                budget_left = max(0.0, 1.0 - long["burn_rate"])
                result = {
                    "objective": ow.objective.name,
                    **ow.objective.selector(),
                    "state": ow.state,
                    "windows": {"short": short, "long": long},
                    "attainment": short["attainment"],
                    "burn_rate_short": short["burn_rate"],
                    "burn_rate_long": long["burn_rate"],
                    "error_budget_remaining": round(budget_left, 6),
                }
                results.append(result)
                if new != old:
                    transitions.append((result, old, new))
                name = ow.objective.name
                if self._m_state is not None:
                    for win, stats in (("short", short), ("long", long)):
                        if stats["attainment"] is not None:
                            self._m_attain.set(
                                stats["attainment"],
                                objective=name, window=win,
                            )
                        self._m_burn.set(
                            stats["burn_rate"], objective=name, window=win
                        )
                    self._m_budget.set(budget_left, objective=name)
                    self._m_state.set(_RANK[ow.state], objective=name)
            self._last_eval = results
            self._last_eval_at = now
        for result, old, new in transitions:
            if self._m_transitions is not None:
                self._m_transitions.inc(
                    objective=result["objective"], state=new
                )
            if self.on_alert is not None:
                self.on_alert(result, old, new)
        return results

    def maybe_evaluate(
        self, now: Optional[float] = None, min_interval_sec: float = 1.0
    ) -> List[Dict[str, Any]]:
        """Rate-limited :meth:`evaluate` for hot paths (the lease handler):
        reuses the last judgment when it is younger than
        ``min_interval_sec``, bounding SLO cost per lease to a dict read."""
        if now is None:
            now = self._clock()
        with self._lock:
            fresh = (
                self._last_eval is not None
                and now - self._last_eval_at < min_interval_sec
            )
            if fresh:
                return self._last_eval
        return self.evaluate(now)

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {w.objective.name: w.state for w in self._windows}

    def active_alerts(self, min_state: str = "warn") -> List[Dict[str, Any]]:
        """Objectives currently at or above ``min_state`` (from the LAST
        evaluation — call ``maybe_evaluate`` first), as the compact shape
        the lease response piggybacks (``{objective, state, tier?, op?,
        tenant?}``) so agents can react (page-entry flight-recorder dump)."""
        rank = _RANK[min_state]
        with self._lock:
            out = []
            for ow in self._windows:
                if _RANK[ow.state] >= rank:
                    out.append({
                        "objective": ow.objective.name,
                        "state": ow.state,
                        **ow.objective.selector(),
                    })
            return out
