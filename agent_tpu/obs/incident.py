"""Automatic incident forensics bundles (ISSUE 20).

When an SLO pages or an anomaly confirms, the operator's first question
is "what was happening" — and the answer used to be scattered across two
flight-recorder dumps, an in-memory request log, and a time-series ring
that may already have rotated past the event. The :class:`IncidentBundler`
snapshots ONE correlated bundle at the moment of the event: the telemetry
window around it, the flight-recorder tail, the reqlog slow tail, the
traces of the K worst requests, controller status and health — bounded,
content-addressed, deduplicated per episode, rate-limited per key.

Bundles are kept in a bounded in-memory ring and (when ``INCIDENT_DIR``
is set) written to ``<dir>/<id>.json`` via tmp+rename, so they survive
the crash they are usually documenting. On open, existing bundle files
are indexed (headers only) — ``GET /v1/incidents`` lists them after a
restart and ``GET /v1/incidents/<id>`` reads the body back from disk.

The id is content-addressed: ``inc-`` + sha256 of the canonical bundle
JSON (sans id), so identical forensics dedupe naturally and a bundle file
can be integrity-checked against its own name.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional

DEFAULT_CAPACITY = 32
DEFAULT_MIN_INTERVAL_SEC = 60.0
DEFAULT_MAX_BUNDLE_BYTES = 512 * 1024
SCHEMA_VERSION = 1


def _canonical(doc: Any) -> str:
    return json.dumps(doc, sort_keys=True, separators=(",", ":"),
                      default=str)


class IncidentBundler:
    def __init__(
        self,
        directory: str = "",
        capacity: int = DEFAULT_CAPACITY,
        min_interval_sec: float = DEFAULT_MIN_INTERVAL_SEC,
        max_bundle_bytes: int = DEFAULT_MAX_BUNDLE_BYTES,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.directory = directory
        self.capacity = max(1, int(capacity))
        self.min_interval_sec = max(0.0, float(min_interval_sec))
        self.max_bundle_bytes = max(4096, int(max_bundle_bytes))
        self._clock = clock
        self._lock = threading.Lock()
        self._bundles: "collections.OrderedDict[str, Dict[str, Any]]" = \
            collections.OrderedDict()
        # Disk-only index after a restart: id -> header (no body in RAM).
        self._disk_index: Dict[str, Dict[str, Any]] = {}
        self._last_by_key: Dict[str, float] = {}
        self.captured = 0
        self.suppressed = 0
        if directory:
            os.makedirs(directory, exist_ok=True)
            self._reindex_disk()

    def _reindex_disk(self) -> None:
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return
        for fname in names:
            if not (fname.startswith("inc-") and fname.endswith(".json")):
                continue
            path = os.path.join(self.directory, fname)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue  # torn write — the tmp+rename path makes this
                # rare; a corrupt bundle is skipped, not fatal.
            if not isinstance(doc, Mapping) or "id" not in doc:
                continue
            self._disk_index[str(doc["id"])] = {
                k: doc.get(k)
                for k in ("id", "wall", "kind", "key", "reason", "schema")
            }

    # ---- capture ----

    def capture(
        self,
        kind: str,
        key: str,
        reason: Mapping[str, Any],
        sections: Mapping[str, Any],
        wall: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """Build + persist one bundle; returns it, or None when the
        (kind, key) episode is rate-limited. Never raises — forensics
        must not take down the path being diagnosed."""
        if wall is None:
            wall = self._clock()
        dedup_key = f"{kind}:{key}"
        with self._lock:
            last = self._last_by_key.get(dedup_key)
            if last is not None and wall - last < self.min_interval_sec:
                self.suppressed += 1
                return None
            self._last_by_key[dedup_key] = wall
        try:
            bundle = self._build(kind, key, reason, sections, wall)
        except Exception:  # noqa: BLE001
            return None
        with self._lock:
            self._bundles[bundle["id"]] = bundle
            while len(self._bundles) > self.capacity:
                self._bundles.popitem(last=False)
            self.captured += 1
        if self.directory:
            self._write(bundle)
        return bundle

    def _build(
        self,
        kind: str,
        key: str,
        reason: Mapping[str, Any],
        sections: Mapping[str, Any],
        wall: float,
    ) -> Dict[str, Any]:
        doc: Dict[str, Any] = {
            "schema": SCHEMA_VERSION,
            "wall": round(float(wall), 3),
            "kind": str(kind),
            "key": str(key),
            "reason": dict(reason),
            "sections": dict(sections),
        }
        # Bound: drop the largest section until the bundle fits. What was
        # dropped is named, so a truncated bundle is visibly truncated.
        dropped: List[str] = []
        while True:
            body = _canonical(doc)
            if len(body) <= self.max_bundle_bytes or not doc["sections"]:
                break
            largest = max(
                doc["sections"],
                key=lambda name: len(_canonical(doc["sections"][name])),
            )
            doc["sections"].pop(largest)
            dropped.append(largest)
            doc["truncated_sections"] = list(dropped)
        digest = hashlib.sha256(_canonical(doc).encode("utf-8")).hexdigest()
        doc["id"] = f"inc-{digest[:12]}"
        return doc

    def _write(self, bundle: Mapping[str, Any]) -> None:
        path = os.path.join(self.directory, f"{bundle['id']}.json")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(bundle, f, sort_keys=True, default=str)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # ---- query ----

    def _header(self, doc: Mapping[str, Any]) -> Dict[str, Any]:
        return {
            "id": doc.get("id"),
            "wall": doc.get("wall"),
            "kind": doc.get("kind"),
            "key": doc.get("key"),
            "reason": doc.get("reason"),
            "truncated_sections": doc.get("truncated_sections"),
        }

    def list(self) -> List[Dict[str, Any]]:
        """Headers, newest first; disk-indexed bundles from before a
        restart included."""
        with self._lock:
            live = [self._header(b) for b in self._bundles.values()]
            live_ids = set(self._bundles)
            disk = [
                dict(h) for bid, h in self._disk_index.items()
                if bid not in live_ids
            ]
        out = live + disk
        out.sort(key=lambda h: (h.get("wall") or 0.0), reverse=True)
        return out

    def get(self, incident_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            bundle = self._bundles.get(incident_id)
            known_on_disk = incident_id in self._disk_index
        if bundle is not None:
            return dict(bundle)
        if not (known_on_disk and self.directory):
            return None
        path = os.path.join(self.directory, f"{incident_id}.json")
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "captured": self.captured,
                "suppressed": self.suppressed,
                "in_memory": len(self._bundles),
                "on_disk_index": len(self._disk_index),
                "dir": self.directory,
            }
