"""Controller-resident metric time series — the trend-retention ring (ISSUE 9).

Every consumer that wanted a *rate* (swarmtop's tasks/s, bench's scrape
deltas) had to scrape ``/v1/metrics`` twice and subtract client-side — which
means every dashboard frame re-derives history the controller already
lived through, and a freshly-attached client has no history at all.
:class:`TimeSeriesRing` fixes that at the source: the controller samples its
own registry (plus the fleet merge) every ``TSDB_INTERVAL`` seconds into a
bounded ring spanning ``TSDB_WINDOW``, and ``GET /v1/timeseries?name=...``
serves the points — so rates and sparklines come from the controller's
clock, not from whenever the client happened to scrape.

Deliberately *not* a database: fixed cadence, bounded window, flattened
samples (counters/gauges keep their value; histograms flatten to their
``_sum``/``_count`` components — enough for rate math, which is all a trend
ring owes anyone). Dependency-free like the rest of ``agent_tpu.obs``.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Tuple

DEFAULT_WINDOW_SEC = 900.0
DEFAULT_INTERVAL_SEC = 10.0


def flatten_snapshot(snap: Mapping[str, Any]) -> Dict[str, Dict[str, float]]:
    """One registry snapshot → ``{family: {label_key: value}}``.

    ``label_key`` is the canonical JSON of the sorted label pairs (the same
    identity ``merge_snapshots`` uses), so a series keeps its key across
    samples. Histograms contribute ``<name>_sum`` and ``<name>_count``
    families plus — since the durable tsdb (ISSUE 20) — a
    ``<name>_bucket`` family with an ``le`` label per slot (``+Inf`` for
    the overflow), so downsampled aggregates keep quantiles computable."""
    out: Dict[str, Dict[str, float]] = {}
    for name, fam in snap.items():
        if not isinstance(fam, Mapping):
            continue
        kind = fam.get("type")
        edges = fam.get("buckets")
        for s in fam.get("series", []):
            labels = s.get("labels", {}) or {}
            key = json.dumps(sorted(labels.items()), separators=(",", ":"))
            if kind == "histogram":
                out.setdefault(f"{name}_sum", {})[key] = float(
                    s.get("sum", 0.0)
                )
                out.setdefault(f"{name}_count", {})[key] = float(
                    s.get("count", 0)
                )
                counts = s.get("counts") or []
                if edges and counts:
                    slots = [str(float(e)) for e in edges] + ["+Inf"]
                    bfam = out.setdefault(f"{name}_bucket", {})
                    for le, c in zip(slots, counts):
                        bkey = json.dumps(
                            sorted(list(labels.items()) + [("le", le)]),
                            separators=(",", ":"),
                        )
                        bfam[bkey] = float(c)
            else:
                out.setdefault(name, {})[key] = float(s.get("value", 0.0))
    return out


def points_to_rates(
    points: List[Tuple[float, float]]
) -> List[Tuple[float, float]]:
    """Consecutive-sample deltas per second, clamped at 0 (a counter reset —
    agent restart — reads as a 0-rate sample, not a negative spike). Each
    rate is stamped at the LATER sample's timestamp; n points → n-1 rates."""
    out: List[Tuple[float, float]] = []
    for (t0, v0), (t1, v1) in zip(points, points[1:]):
        dt = t1 - t0
        if dt <= 0:
            continue
        out.append((t1, max(0.0, (v1 - v0) / dt)))
    return out


class TimeSeriesRing:
    """Bounded ring of periodic flattened registry samples.

    ``maybe_sample(sampler)`` is called from the controller's sweep loop and
    (rate-limited by the same interval check) from the lease hot path, so the
    ring fills with or without a sweeper. ``sampler`` is a zero-arg callable
    returning the snapshot dicts to flatten — evaluated only when a sample is
    actually due, so the hot path pays one clock read per call."""

    def __init__(
        self,
        window_sec: float = DEFAULT_WINDOW_SEC,
        interval_sec: float = DEFAULT_INTERVAL_SEC,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.window_sec = max(1.0, float(window_sec))
        self.interval_sec = min(
            self.window_sec, max(0.05, float(interval_sec))
        )
        self._clock = clock
        maxlen = max(2, int(self.window_sec / self.interval_sec) + 1)
        self._samples: "collections.deque" = collections.deque(maxlen=maxlen)
        self._last = float("-inf")
        self._lock = threading.Lock()
        # Persist hook (ISSUE 20): called OUTSIDE the lock with
        # (wall, mono, data) after every recorded sample — the durable
        # tsdb and the anomaly detector ride every ring sample. Failures
        # are swallowed here (the owner keeps its own error counter);
        # telemetry must never take down the hot path feeding it.
        self.on_sample: Optional[Callable[[float, float, Dict[str, Dict[str, float]]], None]] = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    def maybe_sample(
        self,
        sampler: Callable[[], Iterable[Mapping[str, Any]]],
        now: Optional[float] = None,
        wall: Optional[float] = None,
    ) -> bool:
        """Take a sample iff the interval elapsed. Returns whether one was
        taken. The due-check runs under the lock but the sampler itself does
        not — a second caller racing the window simply records one more
        sample, never corrupts the ring."""
        if now is None:
            now = self._clock()
        with self._lock:
            if now - self._last < self.interval_sec:
                return False
            self._last = now
        self.sample(sampler(), now=now, wall=wall)
        return True

    def sample(
        self,
        snapshots: Iterable[Mapping[str, Any]],
        now: Optional[float] = None,
        wall: Optional[float] = None,
    ) -> None:
        """Unconditionally record one sample (tests and forced flushes)."""
        if now is None:
            now = self._clock()
        if wall is None:
            wall = time.time()
        data: Dict[str, Dict[str, float]] = {}
        for snap in snapshots:
            if not isinstance(snap, Mapping):
                continue
            for name, series in flatten_snapshot(snap).items():
                # Same family from controller + fleet merge: later snapshots
                # win per label key (they never overlap in practice —
                # controller families are controller_*/sched_* prefixed).
                data.setdefault(name, {}).update(series)
        self.append_flat(wall, data, now=now)

    def append_flat(
        self,
        wall: float,
        data: Dict[str, Dict[str, float]],
        now: Optional[float] = None,
    ) -> None:
        """Record one already-flattened sample (the router's collector
        replays scraped partition samples through this)."""
        if now is None:
            now = self._clock()
        with self._lock:
            self._samples.append({"mono": now, "wall": wall, "data": data})
        hook = self.on_sample
        if hook is not None:
            try:
                hook(wall, now, data)
            except Exception:  # noqa: BLE001 — see ctor comment
                pass

    def samples_since(
        self, wall: float, limit: int = 0
    ) -> List[Dict[str, Any]]:
        """Samples strictly NEWER than ``wall``, oldest first — the
        ``/v1/timeseries/export`` delta-scrape cursor contract."""
        with self._lock:
            out = [
                {"wall": s["wall"], "data": s["data"]}
                for s in self._samples
                if s["wall"] > wall
            ]
        if limit and len(out) > limit:
            out = out[-limit:]
        return out

    def last_wall(self) -> Optional[float]:
        with self._lock:
            return self._samples[-1]["wall"] if self._samples else None

    def names(self) -> List[str]:
        seen: Dict[str, None] = {}
        with self._lock:
            for s in self._samples:
                for name in s["data"]:
                    seen.setdefault(name)
        return sorted(seen)

    def series(
        self,
        name: str,
        label_filter: Optional[Mapping[str, str]] = None,
        window_sec: Optional[float] = None,
    ) -> List[Dict[str, Any]]:
        """``[{labels, points: [[wall_ts, value], ...]}, ...]`` for one
        family, newest window first in time order. Unknown names and empty
        windows return ``[]`` — never an error (a ring that hasn't sampled
        yet is a normal state, not a fault)."""
        horizon = None
        if window_sec is not None:
            horizon = self._clock() - max(0.0, float(window_sec))
        grouped: Dict[str, List[Tuple[float, float]]] = {}
        with self._lock:
            samples = list(self._samples)
        for s in samples:
            if horizon is not None and s["mono"] < horizon:
                continue
            for key, value in s["data"].get(name, {}).items():
                grouped.setdefault(key, []).append((s["wall"], value))
        out: List[Dict[str, Any]] = []
        for key in sorted(grouped):
            labels = dict(json.loads(key))
            if label_filter and any(
                labels.get(k) != v for k, v in label_filter.items()
            ):
                continue
            out.append({
                "labels": labels,
                "points": [[round(t, 3), v] for t, v in grouped[key]],
            })
        return out

    def query(
        self,
        name: str,
        label_filter: Optional[Mapping[str, str]] = None,
        rate: bool = False,
        window_sec: Optional[float] = None,
    ) -> Dict[str, Any]:
        """The ``GET /v1/timeseries`` body. ``rate=True`` transforms each
        series' points into per-second deltas (counter rates; a gauge's
        "rate" is its slope, which callers asked for explicitly)."""
        series = self.series(name, label_filter, window_sec=window_sec)
        if rate:
            for s in series:
                s["points"] = [
                    [round(t, 3), round(v, 6)]
                    for t, v in points_to_rates(
                        [(p[0], p[1]) for p in s["points"]]
                    )
                ]
        return {
            "name": name,
            "rate": bool(rate),
            "window_sec": self.window_sec,
            "interval_sec": self.interval_sec,
            "n_samples": len(self),
            "series": series,
        }
