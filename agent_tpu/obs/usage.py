"""Per-tenant / per-job resource accounting — the showback ledger (ISSUE 9).

The fleet could say *how busy* it was (``device_busy_seconds_total``) but
not *who* made it busy — the question a multi-tenant deployment bills on and
the autoscaler's capacity math starts from. The accounting path:

- **Agents** stamp a ``usage`` block into every result body (the dispatch
  loop adds ``device_s``/``chips``/``flops`` in ``note_device_time`` — the
  SAME float that feeds ``device_busy_seconds_total``, so ledger totals
  reconcile with the fleet counter exactly on clean traffic; the
  stage/finalize phases add ``host_s``; ops add ``rows`` via
  ``_model_common.stamp_rows``).
- **The controller** bills each *accepted* result application into this
  ledger keyed ``{tenant, tier, op}`` and per job, deduped by
  ``(job_id, attempt)`` — a spool-redelivered duplicate or epoch-fenced
  stale result is already rejected before billing, and the attempt key makes
  double-billing structurally impossible even if one slipped through.
  Failed attempts that produced a structured result bill too (the fleet
  really did spend that time); error-only failures carry no usage block and
  simply under-count — documented, and irrelevant on clean traffic.
- **Durability**: billed usage rides the journal's ``result`` events (key
  appended only when present, so journals without usage stay byte-identical)
  and replays into a fresh ledger, so ``GET /v1/usage`` survives a
  controller restart like every other piece of job state.

Bounded by design: the aggregate map is small (tenants × tiers × ops); the
per-job map holds at most ``max_jobs`` entries, evicting the smallest
device-seconds consumer first — top-K stays exact until eviction starts,
approximate (biased toward keeping the expensive jobs, which is the point
of a top-K) after.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

# Numeric usage-block fields agents may stamp (anything else is dropped —
# the wire is agent-controlled input).
USAGE_FIELDS = (
    "device_s", "host_s", "flops", "rows", "chips", "wire_bytes",
    "cache_hit_rows", "result_cache_hits",
)

_ZERO = {
    "tasks": 0,
    "device_seconds": 0.0,
    "chip_seconds": 0.0,
    "host_seconds": 0.0,
    "flops": 0.0,
    "rows": 0,
    "wire_bytes": 0,
    # Rows whose prefill was served from the prefix cache (ISSUE 16): the
    # showback line that says how much compute a tenant's repeated prefixes
    # DIDN'T cost the fleet.
    "cache_hit_rows": 0,
    # Whole results served from the content-addressed result cache
    # (ISSUE 19): billed at cache price instead of chip-seconds; the
    # per-tenant result_dedupe_ratio derives from this.
    "result_cache_hits": 0,
}


def sanitize_usage(raw: Any) -> Dict[str, float]:
    """The numeric subset of an agent-stamped usage block: known fields,
    finite non-negative numbers only (the wire is untrusted input — a NaN
    here would poison every aggregate it touches)."""
    out: Dict[str, float] = {}
    if not isinstance(raw, Mapping):
        return out
    for key in USAGE_FIELDS:
        v = raw.get(key)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        v = float(v)
        if v != v or v < 0 or v == float("inf"):
            continue
        out[key] = v
    return out


def _accumulate(bucket: Dict[str, Any], usage: Mapping[str, float],
                wire_bytes: int) -> None:
    bucket["tasks"] += 1
    dev = usage.get("device_s", 0.0)
    bucket["device_seconds"] += dev
    bucket["chip_seconds"] += dev * max(1.0, usage.get("chips", 1.0))
    bucket["host_seconds"] += usage.get("host_s", 0.0)
    bucket["flops"] += usage.get("flops", 0.0)
    bucket["rows"] += int(usage.get("rows", 0))
    bucket["wire_bytes"] += int(wire_bytes) + int(usage.get("wire_bytes", 0))
    bucket["cache_hit_rows"] += int(usage.get("cache_hit_rows", 0))
    bucket["result_cache_hits"] += int(usage.get("result_cache_hits", 0))


def _rounded(bucket: Mapping[str, Any]) -> Dict[str, Any]:
    return {
        "tasks": int(bucket["tasks"]),
        "device_seconds": round(bucket["device_seconds"], 6),
        "chip_seconds": round(bucket["chip_seconds"], 6),
        "host_seconds": round(bucket["host_seconds"], 6),
        "flops": float(bucket["flops"]),
        "rows": int(bucket["rows"]),
        "wire_bytes": int(bucket["wire_bytes"]),
        "cache_hit_rows": int(bucket["cache_hit_rows"]),
        "result_cache_hits": int(bucket["result_cache_hits"]),
    }


class UsageLedger:
    """Thread-safe accounting of accepted result applications."""

    def __init__(
        self,
        registry: Any = None,
        top_k: int = 10,
        max_jobs: int = 4096,
        cost_per_chip_hour: float = 0.0,
        cache_price_per_hit: float = 0.0,
    ) -> None:
        self.top_k = max(1, int(top_k))
        self.max_jobs = max(16, int(max_jobs))
        self.cost_per_chip_hour = max(0.0, float(cost_per_chip_hour))
        # The "cache price": est-cost charged per result served from the
        # content-addressed result cache (ISSUE 19) instead of chip-seconds.
        self.cache_price_per_hit = max(0.0, float(cache_price_per_hit))
        self.started_wall = time.time()
        self._lock = threading.Lock()
        # {(tenant, tier, op): bucket} — the showback aggregate.
        self._by_key: Dict[Tuple[str, int, str], Dict[str, Any]] = {}
        # {job_id: bucket + identity + billed attempt set} — the top-K feed.
        self._jobs: Dict[str, Dict[str, Any]] = {}
        self.billed_tasks = 0
        self.evicted_jobs = 0
        # Prometheus mirrors (when a registry is injected): the series the
        # time-series ring turns into per-tenant rate sparklines.
        self._m_device = self._m_tasks = self._m_rows = None
        if registry is not None:
            self._m_device = registry.counter(
                "usage_device_seconds_total",
                "Billed device-dispatch seconds per tenant and op "
                "(accepted result applications only)", ("tenant", "op"))
            self._m_tasks = registry.counter(
                "usage_tasks_total",
                "Billed result applications per tenant and op",
                ("tenant", "op"))
            self._m_rows = registry.counter(
                "usage_rows_total",
                "Rows processed per tenant and op (ops that stamp rows)",
                ("tenant", "op"))

    def bill(
        self,
        job_id: str,
        tenant: str,
        tier: int,
        op: str,
        attempt: Any,
        usage: Any = None,
        wire_bytes: int = 0,
    ) -> Optional[Dict[str, float]]:
        """Bill one accepted result application. Returns the sanitized usage
        actually billed (what the caller journals), or ``None`` when this
        ``(job_id, attempt)`` was already billed — the structural guard
        that makes "billed exactly once" hold under duplicate delivery."""
        clean = sanitize_usage(usage)
        if not clean and wire_bytes <= 0:
            return None  # nothing measurable to bill
        attempt_key = int(attempt) if isinstance(attempt, int) \
            and not isinstance(attempt, bool) else -1
        wire_bytes = max(0, int(wire_bytes))
        with self._lock:
            entry = self._jobs.get(job_id)
            if entry is not None and attempt_key in entry["attempts"]:
                return None
            if entry is None:
                entry = {
                    "job_id": job_id,
                    "tenant": tenant,
                    "tier": int(tier),
                    "op": op,
                    "attempts": set(),
                    **dict(_ZERO),
                }
                self._jobs[job_id] = entry
                if len(self._jobs) > self.max_jobs:
                    self._evict_locked(keep=job_id)
            entry["attempts"].add(attempt_key)
            _accumulate(entry, clean, wire_bytes)
            key = (tenant, int(tier), op)
            bucket = self._by_key.get(key)
            if bucket is None:
                bucket = dict(_ZERO)
                self._by_key[key] = bucket
            _accumulate(bucket, clean, wire_bytes)
            self.billed_tasks += 1
        if self._m_tasks is not None:
            self._m_tasks.inc(tenant=tenant, op=op)
            if clean.get("device_s"):
                self._m_device.inc(clean["device_s"], tenant=tenant, op=op)
            if clean.get("rows"):
                self._m_rows.inc(int(clean["rows"]), tenant=tenant, op=op)
        billed = dict(clean)
        if wire_bytes:
            billed["wire_bytes"] = billed.get("wire_bytes", 0) + wire_bytes
        return billed

    def _evict_locked(self, keep: str) -> None:
        victim = min(
            (jid for jid in self._jobs if jid != keep),
            key=lambda jid: self._jobs[jid]["device_seconds"],
            default=None,
        )
        if victim is not None:
            del self._jobs[victim]
            self.evicted_jobs += 1

    def export_state(self) -> Dict[str, Any]:
        """JSON-serializable image of the ledger for the controller's
        compacting journal snapshot (ISSUE 14): aggregates, per-job table
        (billed-attempt sets as sorted lists), and the counters. Exact —
        ``import_state`` rebuilds a ledger indistinguishable from one that
        replayed the full journal."""
        with self._lock:
            return {
                "by_key": [
                    [t, tier, op, _rounded(b)]
                    for (t, tier, op), b in self._by_key.items()
                ],
                "jobs": [
                    {
                        **{k: v for k, v in e.items() if k != "attempts"},
                        "attempts": sorted(e["attempts"]),
                    }
                    for e in self._jobs.values()
                ],
                "billed_tasks": self.billed_tasks,
                "evicted_jobs": self.evicted_jobs,
            }

    def import_state(
        self, doc: Mapping[str, Any], mirror: bool = True
    ) -> None:
        """Rehydrate from ``export_state`` output (snapshot replay). With
        ``mirror`` the Prometheus counters re-increment from the
        aggregates so a snapshot-based replay exports the same totals a
        full-journal replay would; a standby RESYNC passes ``mirror=False``
        (its mirrors already counted the events it applied live —
        re-incrementing would double them)."""
        with self._lock:
            self._by_key = {}
            for item in doc.get("by_key") or []:
                try:
                    tenant, tier, op, bucket = item
                except (TypeError, ValueError):
                    continue
                b = dict(_ZERO)
                for f in _ZERO:
                    v = (bucket or {}).get(f)
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        b[f] = type(_ZERO[f])(v)
                self._by_key[(str(tenant), int(tier), str(op))] = b
            self._jobs = {}
            for rec in doc.get("jobs") or []:
                if not isinstance(rec, Mapping) or "job_id" not in rec:
                    continue
                entry = {
                    "job_id": str(rec["job_id"]),
                    "tenant": str(rec.get("tenant", "default")),
                    "tier": int(rec.get("tier", 0)),
                    "op": str(rec.get("op", "?")),
                    "attempts": set(
                        a for a in rec.get("attempts") or []
                        if isinstance(a, int)
                    ),
                    **dict(_ZERO),
                }
                for f in _ZERO:
                    v = rec.get(f)
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        entry[f] = type(_ZERO[f])(v)
                self._jobs[entry["job_id"]] = entry
            self.billed_tasks = int(doc.get("billed_tasks", 0))
            self.evicted_jobs = int(doc.get("evicted_jobs", 0))
            by_key = dict(self._by_key)
        if self._m_tasks is not None and mirror:
            for (tenant, _tier, op), b in by_key.items():
                if b["tasks"]:
                    self._m_tasks.inc(b["tasks"], tenant=tenant, op=op)
                if b["device_seconds"]:
                    self._m_device.inc(
                        b["device_seconds"], tenant=tenant, op=op
                    )
                if b["rows"]:
                    self._m_rows.inc(int(b["rows"]), tenant=tenant, op=op)

    def job_billed_attempts(self) -> Dict[str, int]:
        """``{job_id: distinct billed attempts}`` — what the chaos soak pins
        ("retries/duplicates billed exactly once" = every value here is 1
        on a drain where each job's result applied once)."""
        with self._lock:
            return {jid: len(e["attempts"]) for jid, e in self._jobs.items()}

    def _cost(self, chip_seconds: float) -> Optional[float]:
        if self.cost_per_chip_hour <= 0:
            return None
        return round(chip_seconds / 3600.0 * self.cost_per_chip_hour, 6)

    def _est_cost(self, bucket: Mapping[str, Any]) -> Optional[float]:
        """Chip-second cost plus the cache price for deduped results —
        None when neither price is configured (showback without rates)."""
        chip = self._cost(float(bucket.get("chip_seconds", 0.0)))
        cache = None
        if self.cache_price_per_hit > 0:
            cache = round(
                float(bucket.get("result_cache_hits", 0) or 0)
                * self.cache_price_per_hit, 6
            )
        if chip is None and cache is None:
            return None
        return round((chip or 0.0) + (cache or 0.0), 6)

    def report(
        self,
        top_k: Optional[int] = None,
        pending_by_tenant: Optional[Mapping[str, int]] = None,
    ) -> Dict[str, Any]:
        """The ``GET /v1/usage`` body: grand totals, per-tenant rollups with
        per-op and per-tier splits, and the top-K jobs by device seconds."""
        k = self.top_k if top_k is None else max(1, int(top_k))
        with self._lock:
            by_key = {key: dict(b) for key, b in self._by_key.items()}
            jobs = [
                {kk: vv for kk, vv in e.items() if kk != "attempts"}
                | {"attempts_billed": len(e["attempts"])}
                for e in self._jobs.values()
            ]
            billed = self.billed_tasks
            evicted = self.evicted_jobs
        totals = dict(_ZERO)
        tenants: Dict[str, Dict[str, Any]] = {}
        for (tenant, tier, op), bucket in sorted(by_key.items()):
            for f in _ZERO:
                totals[f] += bucket[f]
            t = tenants.setdefault(tenant, {
                **dict(_ZERO), "by_op": {}, "by_tier": {},
            })
            for f in _ZERO:
                t[f] += bucket[f]
            op_b = t["by_op"].setdefault(op, dict(_ZERO))
            tier_b = t["by_tier"].setdefault(str(tier), dict(_ZERO))
            for f in _ZERO:
                op_b[f] += bucket[f]
                tier_b[f] += bucket[f]
        top = sorted(
            jobs, key=lambda e: e["device_seconds"], reverse=True
        )[:k]
        out: Dict[str, Any] = {
            "enabled": True,
            "since_wall": round(self.started_wall, 3),
            "billed_tasks": billed,
            "evicted_jobs": evicted,
            "cost_per_chip_hour": self.cost_per_chip_hour,
            "cache_price_per_hit": self.cache_price_per_hit,
            "totals": {
                **_rounded(totals),
                "est_cost": self._est_cost(totals),
                "prefix_dedupe_ratio": _dedupe_ratio(totals),
                "result_dedupe_ratio": _result_dedupe_ratio(totals),
            },
            "by_tenant": {
                tenant: {
                    **_rounded(t),
                    "est_cost": self._est_cost(t),
                    # What fraction of this tenant's prefill rows the prefix
                    # cache absorbed (ISSUE 17 satellite): cache_hit_rows
                    # was billed all along but never surfaced as a rate.
                    "prefix_dedupe_ratio": _dedupe_ratio(t),
                    # What fraction of this tenant's billed results the
                    # content-addressed result cache served (ISSUE 19).
                    "result_dedupe_ratio": _result_dedupe_ratio(t),
                    "by_op": {
                        op: _rounded(b) for op, b in sorted(t["by_op"].items())
                    },
                    "by_tier": {
                        tier: _rounded(b)
                        for tier, b in sorted(t["by_tier"].items())
                    },
                }
                for tenant, t in sorted(tenants.items())
            },
            "top_jobs": [
                {
                    "job_id": e["job_id"],
                    "tenant": e["tenant"],
                    "tier": e["tier"],
                    "op": e["op"],
                    "attempts_billed": e["attempts_billed"],
                    **_rounded(e),
                }
                for e in top
            ],
        }
        if pending_by_tenant is not None:
            out["pending_by_tenant"] = {
                t: int(n) for t, n in sorted(pending_by_tenant.items())
            }
        return out


def _dedupe_ratio(bucket: Mapping[str, Any]) -> Optional[float]:
    """cache_hit_rows / (rows + cache_hit_rows) — the share of prefill
    demand the prefix cache deduplicated away. None when no rows billed
    yet (0/0 is "no data", not "no dedupe")."""
    hits = float(bucket.get("cache_hit_rows", 0) or 0)
    rows = float(bucket.get("rows", 0) or 0)
    denom = rows + hits
    if denom <= 0:
        return None
    return round(hits / denom, 4)


def _result_dedupe_ratio(bucket: Mapping[str, Any]) -> Optional[float]:
    """result_cache_hits / tasks — the share of billed result applications
    the content-addressed result cache served instead of the fleet
    computing them. None before anything billed."""
    tasks = float(bucket.get("tasks", 0) or 0)
    if tasks <= 0:
        return None
    hits = float(bucket.get("result_cache_hits", 0) or 0)
    return round(hits / tasks, 4)


def stamp_usage(tags: Optional[Dict[str, Any]], **fields: float) -> None:
    """Accumulate usage fields into ``ctx.tags["usage"]`` — the agent-side
    stamping primitive shared by the dispatch loops (``device_s``/``chips``/
    ``flops``) and the host phases (``host_s``). ``chips`` is a level, not
    an accumulator: last writer wins."""
    if tags is None:
        return
    u = tags.setdefault("usage", {})
    for key, value in fields.items():
        if value is None:
            continue
        if key == "chips":
            u["chips"] = float(value)
        else:
            u[key] = u.get(key, 0.0) + float(value)
