"""Wide-event request log — one structured record per serving request.

Aggregate histograms say *how slow* serving is; the request log (ISSUE 17)
keeps the evidence: one flat record per terminal ``/v1/infer`` request
(tenant, op, bucket, priority, outcome, TTFT/TPOT, the TTFT component
decomposition, tokens, path, prefix hit, KV wait, occupancy, trace ids) in
a bounded ring served at ``GET /v1/debug/requests``.

**Tail-based sampling**: at high request rates keeping every healthy
record is waste — the interesting tail is errors and the slow decile. The
log therefore ALWAYS keeps records whose ``outcome`` is not ``completed``
and records whose TTFT lands in the slowest decile of the recent window,
and keeps the fast/healthy remainder with probability
``SERVE_REQLOG_SAMPLE`` (default 1.0 = everything; 0.0 = tail only). The
sampling decision hashes ``req_id`` — deterministic across replays and
processes, no RNG state to carry.

Dependency-free by the obs charter: stdlib only. Memory is O(capacity)
like the flight recorder, never O(requests).
"""

from __future__ import annotations

import collections
import hashlib
import threading
import time
from typing import Any, Dict, List, Optional

DEFAULT_CAPACITY = 2048
# Recent-TTFT window the slow-decile threshold is computed over. Small
# enough that the per-add sort is noise, large enough to be a stable
# estimate at serving rates.
SLOW_WINDOW = 512
# Below this many observed TTFTs the decile estimate is meaningless —
# keep everything (conservative: the warmup tail is exactly when records
# are scarce and precious).
SLOW_MIN_SAMPLES = 20
SLOW_QUANTILE = 0.90


def _sample_fraction(req_id: str) -> float:
    """Deterministic [0, 1) fraction from the request id — the same
    request samples identically on every replay/process."""
    digest = hashlib.sha1(req_id.encode("utf-8", "replace")).digest()
    return int.from_bytes(digest[:8], "big") / float(1 << 64)


class RequestLog:
    """Bounded, thread-safe ring of wide request records with tail-based
    sampling. ``add`` is on the serving completion path: it must never
    raise and stays O(SLOW_WINDOW log SLOW_WINDOW) worst case."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        sample: float = 1.0,
    ) -> None:
        self.capacity = max(1, int(capacity))
        self.sample = min(1.0, max(0.0, float(sample)))
        self._lock = threading.Lock()
        self._records: "collections.deque" = collections.deque(
            maxlen=self.capacity
        )
        self._ttfts: "collections.deque" = collections.deque(
            maxlen=SLOW_WINDOW
        )
        self.seen = 0
        self.kept = 0
        self.sampled_out = 0
        self.kept_by_reason: Dict[str, int] = {}

    # ---- ingestion ----

    def _slow_threshold_locked(self) -> Optional[float]:
        if len(self._ttfts) < SLOW_MIN_SAMPLES:
            return None
        ordered = sorted(self._ttfts)
        idx = min(len(ordered) - 1, int(len(ordered) * SLOW_QUANTILE))
        return ordered[idx]

    def add(self, record: Dict[str, Any]) -> Optional[str]:
        """Ingest one record; returns the keep reason (``error`` /
        ``slow`` / ``sampled``) or None when sampled out. The record is
        annotated with ``kept`` (the reason) and ``ts`` when absent."""
        with self._lock:
            self.seen += 1
            outcome = str(record.get("outcome") or "")
            ttft = record.get("ttft_ms")
            threshold = self._slow_threshold_locked()
            if isinstance(ttft, (int, float)) and not isinstance(ttft, bool):
                self._ttfts.append(float(ttft))
            if outcome and outcome != "completed":
                reason = "error"
            elif isinstance(ttft, (int, float)) and (
                threshold is None or float(ttft) >= threshold
            ):
                # Slowest decile of the recent window — or the warmup
                # phase before the decile estimate exists.
                reason = "slow"
            elif self.sample >= 1.0 or _sample_fraction(
                str(record.get("req_id") or "")
            ) < self.sample:
                reason = "sampled"
            else:
                self.sampled_out += 1
                return None
            record = dict(record)
            record["kept"] = reason
            record.setdefault("ts", time.time())
            self._records.append(record)
            self.kept += 1
            self.kept_by_reason[reason] = (
                self.kept_by_reason.get(reason, 0) + 1
            )
            return reason

    # ---- query ----

    def snapshot(
        self,
        tenant: Optional[str] = None,
        outcome: Optional[str] = None,
        slow: bool = False,
        limit: int = 256,
    ) -> List[Dict[str, Any]]:
        """Newest-first records matching the filters. ``slow=True``
        restricts to tail-kept records (``kept`` in error/slow) — the
        ``?slow=1`` debug view."""
        with self._lock:
            records = list(self._records)
        out: List[Dict[str, Any]] = []
        for rec in reversed(records):
            if tenant is not None and rec.get("tenant") != tenant:
                continue
            if outcome is not None and rec.get("outcome") != outcome:
                continue
            if slow and rec.get("kept") not in ("error", "slow"):
                continue
            out.append(dict(rec))
            if len(out) >= max(1, int(limit)):
                break
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "sample": self.sample,
                "seen": self.seen,
                "kept": self.kept,
                "sampled_out": self.sampled_out,
                "kept_by_reason": dict(self.kept_by_reason),
                "size": len(self._records),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


def dominant_component(components: Dict[str, Any]) -> Optional[str]:
    """The TTFT component that dominates one request's decomposition —
    the 'why was THIS request slow' one-worder swarmtop and bench print."""
    best: Optional[str] = None
    best_ms = 0.0
    for name, ms in (components or {}).items():
        if isinstance(ms, (int, float)) and not isinstance(ms, bool) \
                and float(ms) >= best_ms:
            best, best_ms = str(name), float(ms)
    return best
