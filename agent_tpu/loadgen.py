"""Open-loop traffic generator — planet-scale arrivals in miniature
(ISSUE 10 tentpole a).

Everything the swarm has drained so far was a *fixed* queue: submit N
shards, drain, stop. "Millions of users" is not that — it is an **open
loop** where arrivals follow their own clock (diurnal swing, bursty
thundering herds, spot-market churn underneath) and never wait for the
system to catch up. This module generates that traffic deterministically:

- :class:`ArrivalPattern` — a non-homogeneous Poisson intensity
  ``rate(t) = base · (1 + amplitude·sin(2πt/period)) · burst_factor(t)``.
  The diurnal sine models the day/night swing; burst windows model the 10×
  herd the autoscaler (``agent_tpu/autoscale.py``) must absorb.
- :class:`TrafficClass` — one kind of work: op + payload template, tenant,
  priority tier, optional ``deadline_sec`` (the interactive class the SLO
  engine judges is just a class with tier 8 + a deadline).
- :class:`LoadGen` — draws the whole arrival **schedule** up front from one
  ``random.Random(seed)`` (thinning over the pattern's peak rate), then
  replays it against a submit callable in real time. Same seed → same
  arrivals, byte for byte; the soak's churn run and its calm reference
  submit the *identical* job set.

Submission is transport-agnostic: :func:`session_submitter` adapts any
``session.post``-shaped object — a ``requests.Session`` against a real
controller URL or a ``chaos.LoopbackSession`` — to the submit-callable
shape ``LoadGen.run`` expects. Open-loop semantics on backpressure: an
admission 429 **drops** the arrival (counted, never retried) — a real user
herd does not politely hold its requests either.
"""

from __future__ import annotations

import bisect
import functools
import math
import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from agent_tpu.config import LoadgenConfig


@functools.lru_cache(maxsize=64)
def _zipf_cdf(n: int, s: float) -> Tuple[float, ...]:
    weights = [1.0 / (i + 1) ** s for i in range(n)]
    total = sum(weights)
    acc = 0.0
    cdf = []
    for w in weights:
        acc += w
        cdf.append(acc / total)
    return tuple(cdf)


def zipf_rank(rng: random.Random, n: int, s: float) -> int:
    """Draw a 0-based rank from a truncated zipfian over ``n`` items:
    P(rank=k) ∝ 1/(k+1)^s. ``s=0`` is uniform; larger ``s`` concentrates
    mass on low ranks — the head-heavy repeat distribution real request
    streams show, and exactly what makes a content-addressed result cache
    earn its keep (ISSUE 19). Deterministic given the caller's seeded rng."""
    if n <= 1:
        return 0
    cdf = _zipf_cdf(int(n), float(s))
    return min(n - 1, bisect.bisect_left(cdf, rng.random()))


class Rejected(Exception):
    """Submit refused by admission control (HTTP 429) — the open loop
    counts the drop and moves on. Behind the partitioned control plane's
    router the 429 body names the rejecting partition (ISSUE 18);
    ``partition`` carries it so drops count per partition, not as one
    smeared fleet total."""

    def __init__(self, msg: str, partition: Optional[str] = None) -> None:
        super().__init__(msg)
        self.partition = partition


@dataclass(frozen=True)
class TrafficClass:
    """One class of offered work. ``payload`` is the static template;
    ``payload_fn(rng, seq)`` (when given) builds a per-arrival payload from
    the generator's seeded rng and the arrival sequence number, so payload
    variety stays deterministic too.

    ``route`` picks the submission surface (ISSUE 15): ``"jobs"`` (the
    batch queue, ``POST /v1/jobs`` — the historical shape) or ``"infer"``
    (the serving front door, ``POST /v1/infer``). An infer class's ``op``
    is the REQUEST op (``classify``/``summarize``) and its payload carries
    ``{"text": ..., "params": {...}}`` — one traffic driver for
    elastic_soak's job churn and the serving bench's interactive load.

    ``payload_zipf_s`` (ISSUE 19) switches the class to a zipfian payload
    MIX: each arrival draws a variant rank from ``zipf_rank(rng,
    payload_pool, payload_zipf_s)`` and the payload is a deterministic
    function of that rank alone — so popular variants recur byte-identical
    (the repeats a result cache dedupes) while the tail stays cold. With a
    ``payload_fn`` the rank replaces ``seq`` and the rng is freshly seeded
    from the rank, making the built payload a pure function of the rank;
    without one the template gains a ``"variant": rank`` field."""

    name: str
    op: str
    weight: float = 1.0
    tenant: Optional[str] = None
    priority: Optional[int] = None
    deadline_sec: Optional[float] = None
    payload: Dict[str, Any] = field(default_factory=dict)
    payload_fn: Optional[Callable[[random.Random, int], Dict[str, Any]]] = None
    route: str = "jobs"   # "jobs" | "infer"
    payload_zipf_s: Optional[float] = None  # zipf exponent; None = off
    payload_pool: int = 64                  # distinct variants when zipfian

    def __post_init__(self) -> None:
        if self.route not in ("jobs", "infer"):
            raise ValueError(
                f"route must be 'jobs' or 'infer', got {self.route!r}"
            )
        if self.payload_zipf_s is not None and self.payload_zipf_s < 0:
            raise ValueError("payload_zipf_s must be >= 0")
        if self.payload_pool < 1:
            raise ValueError("payload_pool must be >= 1")

    def build_payload(self, rng: random.Random, seq: int) -> Dict[str, Any]:
        if self.payload_zipf_s is not None:
            rank = zipf_rank(rng, self.payload_pool, self.payload_zipf_s)
            if self.payload_fn is not None:
                # Fresh rank-seeded rng: the variant's payload is identical
                # every time the rank recurs, whatever the arrival history.
                return self.payload_fn(random.Random(rank), rank)
            out = dict(self.payload)
            out["variant"] = rank
            return out
        if self.payload_fn is not None:
            return self.payload_fn(rng, seq)
        return dict(self.payload)


@dataclass(frozen=True)
class Arrival:
    """One scheduled submission: offset seconds from run start, the class,
    the pre-built payload, and the run-wide sequence number."""

    t: float
    cls: TrafficClass
    payload: Dict[str, Any]
    seq: int


class ArrivalPattern:
    """Deterministic intensity function over run time."""

    def __init__(
        self,
        base_rate: float,
        diurnal_amplitude: float = 0.0,
        diurnal_period_sec: float = 86400.0,
        bursts: Sequence[Tuple[float, float, float]] = (),
    ) -> None:
        self.base_rate = max(0.0, float(base_rate))
        self.diurnal_amplitude = min(1.0, max(0.0, float(diurnal_amplitude)))
        self.diurnal_period_sec = max(1e-9, float(diurnal_period_sec))
        # (start_sec, end_sec, factor) windows; overlapping windows multiply.
        self.bursts = [
            (float(s), float(e), max(0.0, float(f))) for s, e, f in bursts
        ]

    @classmethod
    def from_config(cls, cfg: LoadgenConfig) -> "ArrivalPattern":
        bursts = []
        if cfg.burst_len_sec > 0 and cfg.burst_factor != 1.0:
            bursts.append((
                cfg.burst_at_sec,
                cfg.burst_at_sec + cfg.burst_len_sec,
                cfg.burst_factor,
            ))
        return cls(
            cfg.base_rate,
            diurnal_amplitude=cfg.diurnal_amplitude,
            diurnal_period_sec=cfg.diurnal_period_sec,
            bursts=bursts,
        )

    def burst_factor(self, t: float) -> float:
        f = 1.0
        for start, end, factor in self.bursts:
            if start <= t < end:
                f *= factor
        return f

    def rate(self, t: float) -> float:
        """Jobs/sec at offset ``t`` (never negative)."""
        diurnal = 1.0 + self.diurnal_amplitude * math.sin(
            2.0 * math.pi * t / self.diurnal_period_sec
        )
        return max(0.0, self.base_rate * diurnal * self.burst_factor(t))

    def peak_rate(self) -> float:
        """An upper bound on ``rate`` — the thinning envelope."""
        burst_max = max(
            [1.0] + [f for _s, _e, f in self.bursts if f > 1.0]
        )
        return self.base_rate * (1.0 + self.diurnal_amplitude) * burst_max


@dataclass
class LoadGenStats:
    """What one replayed schedule did: per-class submit counts, open-loop
    drops, and the (job_id, class, submit-wall-offset, seq) ledger the soak
    joins against controller-side completion times."""

    submitted: Dict[str, int] = field(default_factory=dict)
    rejected: Dict[str, int] = field(default_factory=dict)
    # Which partition said no (ISSUE 18): keyed by the partition name the
    # router stamped into the 429 body; unstamped rejects (a bare
    # controller) count under "".
    rejected_by_partition: Dict[str, int] = field(default_factory=dict)
    errors: Dict[str, int] = field(default_factory=dict)
    jobs: List[Dict[str, Any]] = field(default_factory=list)

    def total_submitted(self) -> int:
        return sum(self.submitted.values())

    def total_rejected(self) -> int:
        return sum(self.rejected.values())

    def job_ids(self, cls_name: Optional[str] = None) -> List[str]:
        return [
            j["job_id"] for j in self.jobs
            if cls_name is None or j["class"] == cls_name
        ]


class LoadGen:
    """Seeded open-loop generator over a class mix + arrival pattern."""

    def __init__(
        self,
        classes: Sequence[TrafficClass],
        pattern: ArrivalPattern,
        seed: int = 0,
    ) -> None:
        if not classes:
            raise ValueError("at least one TrafficClass is required")
        if any(c.weight < 0 for c in classes):
            raise ValueError("class weights must be >= 0")
        if not any(c.weight > 0 for c in classes):
            raise ValueError("at least one class weight must be > 0")
        self.classes = list(classes)
        self.pattern = pattern
        self.seed = int(seed)

    def schedule(self, duration_sec: float) -> List[Arrival]:
        """The full arrival list for ``duration_sec``, drawn from one seeded
        rng: thinning over the pattern's peak rate (a draw is accepted with
        probability ``rate(t)/peak``), then a weighted class pick and the
        class's payload build. Pure function of (seed, classes, pattern,
        duration) — the determinism the soak's calm-vs-churn comparison
        rests on."""
        rng = random.Random(self.seed)
        peak = self.pattern.peak_rate()
        arrivals: List[Arrival] = []
        if peak <= 0 or duration_sec <= 0:
            return arrivals
        weights = [c.weight for c in self.classes]
        t = 0.0
        seq = 0
        while True:
            t += rng.expovariate(peak)
            if t >= duration_sec:
                break
            if rng.random() >= self.pattern.rate(t) / peak:
                continue  # thinned: the instantaneous rate is below peak
            cls = rng.choices(self.classes, weights=weights, k=1)[0]
            arrivals.append(Arrival(t, cls, cls.build_payload(rng, seq), seq))
            seq += 1
        return arrivals

    def run(
        self,
        submit: Callable[[Arrival], str],
        duration_sec: float,
        *,
        now: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        stats: Optional[LoadGenStats] = None,
    ) -> LoadGenStats:
        """Replay the schedule in real time against ``submit(arrival) ->
        job_id``. Open loop: the clock, not the system, paces submissions —
        a slow controller gets the full burst anyway, late (the generator
        never skips an arrival, it just falls behind the ideal offsets).
        ``Rejected`` (admission 429) drops the arrival; any other submit
        exception is counted and dropped too (the generator must outlive a
        controller blip)."""
        stats = stats if stats is not None else LoadGenStats()
        t0 = now()
        for arrival in self.schedule(duration_sec):
            delay = arrival.t - (now() - t0)
            if delay > 0:
                sleep(delay)
            name = arrival.cls.name
            try:
                job_id = submit(arrival)
            except Rejected as exc:
                stats.rejected[name] = stats.rejected.get(name, 0) + 1
                part = exc.partition or ""
                stats.rejected_by_partition[part] = (
                    stats.rejected_by_partition.get(part, 0) + 1
                )
                continue
            except Exception:  # noqa: BLE001 — open loop outlives blips
                stats.errors[name] = stats.errors.get(name, 0) + 1
                continue
            stats.submitted[name] = stats.submitted.get(name, 0) + 1
            stats.jobs.append({
                "job_id": job_id,
                "class": name,
                "seq": arrival.seq,
                "scheduled_t": arrival.t,
                "submitted_t": now() - t0,
            })
        return stats


def session_submitter(
    session: Any, base_url: str = "http://loopback"
) -> Callable[[Arrival], str]:
    """Adapt any ``session.post``-shaped transport (``requests.Session``,
    ``chaos.LoopbackSession``) into the submit callable ``LoadGen.run``
    expects. ``route="jobs"`` classes POST to ``{base_url}/v1/jobs``
    (tenant/priority/deadline riding the body, job_id back);
    ``route="infer"`` classes POST to the serving front door
    ``{base_url}/v1/infer`` non-blocking (``wait: false``, req_id back) —
    open loop both ways. 429 → :class:`Rejected` (open-loop drop,
    carrying the rejecting partition when the body is router-stamped);
    any other non-200 raises. ``base_url`` may be a single controller OR
    the partition router (ISSUE 18) — the paths are identical, which is
    the router's whole contract."""
    base = base_url.rstrip("/")
    jobs_url = f"{base}/v1/jobs"
    infer_url = f"{base}/v1/infer"

    def submit(arrival: Arrival) -> str:
        cls = arrival.cls
        if cls.route == "infer":
            body: Dict[str, Any] = {
                "op": cls.op,
                "text": arrival.payload.get("text"),
                "wait": False,
            }
            if isinstance(arrival.payload.get("params"), dict):
                body["params"] = arrival.payload["params"]
            id_key, url = "req_id", infer_url
        else:
            body = {"op": cls.op, "payload": arrival.payload}
            if cls.deadline_sec is not None:
                body["deadline_sec"] = cls.deadline_sec
            id_key, url = "job_id", jobs_url
        if cls.tenant is not None:
            body["tenant"] = cls.tenant
        if cls.priority is not None:
            body["priority"] = cls.priority
        resp = session.post(url, json=body, timeout=10.0)
        status = getattr(resp, "status_code", 0)
        if status == 429:
            try:
                rej = resp.json()
            except ValueError:
                rej = None
            partition = (
                rej.get("partition") if isinstance(rej, dict) else None
            )
            raise Rejected(
                f"admission rejected {cls.name!r}", partition=partition
            )
        if status != 200:
            raise RuntimeError(
                f"submit {cls.name!r} failed: HTTP {status}"
            )
        out_id = resp.json().get(id_key)
        if not isinstance(out_id, str) or not out_id:
            raise RuntimeError(f"submit {cls.name!r}: malformed response")
        return out_id

    return submit
