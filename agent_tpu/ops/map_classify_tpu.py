"""Classification on the TPU mesh — successor of the reference's Edge-TPU op.

Capability parity with reference ``ops/map_classify_tpu.py:31-90`` +
``CONTRACT.md:1-27`` (full contract: ``map_classify_tpu.CONTRACT.md`` here):

- Payload: required input (``input`` flat numeric list — now token ids — or the
  batched upgrades ``text``/``texts``), optional ``model_path``, ``topk``
  (default 5), ``allow_fallback`` (default True).
- Result: ``{op, model_path, topk: [{index, score}], elapsed_ms}`` (ref
  ``:76-82``); degraded shape ``{fallback: "cpu", reason, topk: []}`` on
  failure with ``allow_fallback`` (ref ``:22-28, 84-90``).
- Input-size validation errors raise (→ structured ``failed`` result at the
  agent) unless fallback is allowed, matching ref ``:58-69``.

The TPU-native inversion: instead of one ``interpreter.invoke()`` per row, rows
batch into bucketed static shapes (``pad_batch``), the batch dim shards over
the mesh ``dp`` axis, and a jit-compiled executable is cached per
(model, batch-bucket, length-bucket) — reference handle-singleton semantics
(``ops/_tpu_runtime.py:34-63``) generalized to a compiled-op cache.

The op is **phase-split** for the pipelined drain (BASELINE.json "host-side
double buffering"): :func:`stage` (pure host — payload validation, CSV shard
read, fused tokenize+pad), :func:`execute` (device — params, compiled
dispatch; with ``allow_fallback`` also the result fetch), :func:`finalize`
(host — result shaping; in the no-fallback drain mode it also pays the
deferred device→host fetch, which is a thread-safe READ of device arrays).
``run`` composes all three, so monolithic callers see the classic contract;
the agent's pipeline runs stage/finalize on worker threads and keeps every
device *dispatch* in ``execute`` on the owning thread (single-owner
invariant, SURVEY.md §5.2 — ownership governs dispatch/mesh mutation, not
reads of results).

Degraded mode is *better* than the reference's: the reference's fallback never
computes (empty topk, ``CONTRACT.md:26`` "fallback handled elsewhere"); ours
retries the identical JAX program on the CPU backend and only returns the empty
shape if that fails too — same program, different backend (SURVEY.md §7).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input

DEFAULT_TOPK = 5
DEFAULT_MODEL_ID = "classify-default"

# Lazy module state: config + CPU fallback runtime, built on first use so the
# op module imports cleanly on hosts without a working jax backend.
_cpu_runtime = None


def _get_cfg(payload: Dict[str, Any]):
    from agent_tpu.models.encoder import EncoderConfig
    from agent_tpu.ops._model_common import config_from_payload

    return config_from_payload(payload, EncoderConfig)


def _resolve_family(model_id: str) -> str:
    """``model_path`` pointing at a local HF checkpoint directory serves the
    pretrained-BERT family; anything else is the in-house encoder (model id
    or ``.npz`` artifact). The pretrained serving story of the reference
    (``ops/_tpu_runtime.py:23-31``), TPU-native."""
    from agent_tpu.models import bert

    return "bert" if bert.is_hf_dir(model_id) else "encoder"


# The only model_config fields a payload may override for a checkpoint
# model: serving controls. Structural fields (num_layers, hidden_size, ...)
# are the checkpoint's — an override there would desync the staged config
# from the actual weights.
_BERT_SERVING_OVERRIDES = ("dtype", "num_labels", "quant")


def _get_bert_cfg(model_id: str, payload: Dict[str, Any]):
    """BertConfig from the checkpoint's config.json; payload ``model_config``
    may override only the serving controls (``_BERT_SERVING_OVERRIDES``:
    ``dtype``, ``num_labels``, ``quant``)."""
    import os as _os

    from agent_tpu.models.bert import BertConfig

    overrides = payload.get("model_config")
    allowed = {}
    if isinstance(overrides, dict):
        allowed = {
            k: v for k, v in overrides.items()
            if k in _BERT_SERVING_OVERRIDES
        }
    return BertConfig.from_hf_json(
        _os.path.join(model_id, "config.json"), **allowed
    )


def _resolve_model_id(payload: Dict[str, Any]) -> str:
    from agent_tpu.ops._model_common import resolve_model_id

    return resolve_model_id(payload, "TPU_MODEL_PATH", DEFAULT_MODEL_ID)


def _build_params(model_id: str, cfg, family: str = "encoder"):
    import os

    if family == "bert":
        from agent_tpu.models import bert

        # Same overrides as the staged cfg so the head matches num_labels.
        _, params = bert.load_hf_dir(
            model_id, dtype=cfg.dtype, num_labels=cfg.num_labels
        )
    else:
        from agent_tpu.models import encoder

        if model_id.endswith(".npz") and os.path.exists(model_id):
            params = encoder.load_npz(model_id, cfg)
        else:
            params = encoder.init_params(cfg, model_id=model_id)
    from agent_tpu.ops._model_common import maybe_quantize_params

    return maybe_quantize_params(params, family, cfg)


def _collect_sequences(payload: Dict[str, Any], cfg) -> Tuple[List, str, bool]:
    """Payload → (items, kind, was_single_input); kind is ``"ids"`` (items =
    token-id lists) or ``"texts"`` (raw strings — tokenization fuses with
    padding on the hot path, ``byte_encode_pad``).

    Accepts, in precedence order: ``input`` (flat token ids, reference
    contract), ``text``/``texts``, or CSV shard addressing (``source_uri`` +
    ``start_row``/``shard_size`` + optional ``text_field``) — the last makes
    a classify task *itself* shard-addressable, so the controller's
    ``submit_csv_job(map_op="map_classify_tpu")`` drains a dataset without a
    separate read stage (BASELINE.json 10M-row drain shape).
    """
    if "input" in payload:
        raw = payload["input"]
        if not isinstance(raw, list) or not raw:
            raise ValueError("input must be a non-empty flat list of ints")
        ids = []
        for v in raw:
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError("input values must be numeric")
            iv = int(v)
            if not 0 <= iv < cfg.vocab_size:
                # Validate-and-reject like the reference's size/shape checks
                # (ref ops/map_classify_tpu.py:58-69) — silently wrapping
                # out-of-range ids would hide caller bugs.
                raise ValueError(
                    f"input id {iv} out of range [0, {cfg.vocab_size})"
                )
            ids.append(iv)
        return [ids[: cfg.max_len]], "ids", True
    texts = payload.get("texts")
    single = False
    if texts is None and "text" in payload:
        texts = [payload["text"]]
        single = True  # single iff the row came from 'text'; 'texts' wins
    if texts is None and "source_uri" in payload:
        from agent_tpu.data.csv_index import read_shard_texts

        # Shared drain-mode contract (also map_summarize's): ValueError →
        # soft bad_input; RuntimeError/OSError propagate so the shard FAILS
        # and the controller retries instead of silently dropping its rows.
        texts = read_shard_texts(payload)
    if texts is not None:
        if not isinstance(texts, list) or not texts or not all(
            isinstance(t, str) for t in texts
        ):
            raise ValueError("texts must be a non-empty list of strings")
        return texts, "texts", single
    raise ValueError(
        "payload requires 'input' (token ids), 'text'/'texts', or "
        "'source_uri' CSV shard addressing"
    )


MAX_BATCH = 8192


def _stage_chunks(dp: int, items: List, kind: str, cfg,
                  family: str = "encoder", model_id: str = "") -> List[Tuple]:
    """Pure host: tokenize+pad ``items`` into device-ready
    ``[(ids[B, L] wire-dtype, lengths[B] int32, n_real_rows), ...]``.

    Text rows go through the shared fused tokenize+pad hot path
    (``_model_common.stage_text_chunks`` — wire format documented there) for
    the byte-vocab encoder family, or the checkpoint's wordpiece vocab for
    the BERT family; pre-tokenized ``input`` rows (v0 contract) pad here.
    """
    from agent_tpu.models.tokenizer import pad_batch
    from agent_tpu.ops._model_common import (
        batch_buckets,
        iter_chunks,
        length_buckets_for,
        stage_text_chunks,
    )

    if kind == "texts":
        encode_pad = None
        if family == "bert":
            from agent_tpu.models import bert

            tok = bert.hf_wordpiece(model_id)

            def encode_pad(chunk, lb, bb):
                return bert.encode_pad_batch(tok, chunk, cfg.max_len, bb, lb)

        return stage_text_chunks(
            dp, items, max_len=cfg.max_len, vocab_size=cfg.vocab_size,
            max_batch=MAX_BATCH, encode_pad=encode_pad,
            split_for_dispatch=True,
        )
    # Length buckets must not exceed the position table (max_len).
    buckets = length_buckets_for(cfg.max_len)
    bbuckets = batch_buckets(dp, MAX_BATCH)
    wire_dtype = np.uint16 if cfg.vocab_size <= (1 << 16) else np.int32
    chunks: List[Tuple] = []
    from agent_tpu.ops._model_common import split_padded_chunk

    for chunk in iter_chunks(items, bbuckets[-1]):
        ids, _ = pad_batch(chunk, buckets=buckets, batch_buckets=bbuckets)
        B, L = ids.shape
        lengths = np.zeros(B, dtype=np.int32)
        lengths[: len(chunk)] = [min(len(s), L) for s in chunk]
        chunks.extend(
            split_padded_chunk(ids.astype(wire_dtype), lengths, len(chunk), dp)
        )
    return chunks


def _execute_chunks(
    runtime, chunks: List[Tuple], model_id: str, cfg, k: int,
    family: str = "encoder", fetch: bool = True,
):
    """Device phase: classify staged chunks.

    ``fetch=True`` → (topk values [N, k] numpy, indices numpy), synced here.
    ``fetch=False`` → the pending device arrays, unfetched — one
    ``(vals_dev, idx_dev, n)`` entry, or ``("cat", vals_dev, idx_dev,
    layout)`` when several dispatch chunks were gathered on device: the pipelined drain's finalize (poster thread) syncs
    them instead, so the device thread can dispatch the NEXT shard while
    this one's device→host round trip is in flight (reading a jax.Array is
    thread-safe; only dispatch is owner-bound).

    Top-k runs on device, fused into the forward executable: the host fetches
    k probabilities per row, not [B, n_classes] logits — at bench shapes that
    is a ~100× smaller device→host transfer. Chunks dispatch asynchronously
    and are fetched after the loop, so host staging of chunk i+1 overlaps
    device compute of chunk i even without the pipeline.
    """
    import jax
    import jax.numpy as jnp

    from agent_tpu.models import encoder, tokenizer
    from agent_tpu.ops._model_common import cfg_key
    from agent_tpu.parallel.shardings import bert_param_specs, encoder_param_specs

    if family == "bert":
        from agent_tpu.models import bert as model_mod

        specs = bert_param_specs(cfg)
    else:
        model_mod = encoder
        specs = encoder_param_specs(cfg)
    from agent_tpu.ops._model_common import maybe_quantize_specs

    specs = maybe_quantize_specs(specs, family, cfg)

    # On a tp>1 mesh the weights land sharded (Megatron-style specs) and XLA
    # inserts the tp collectives in the forward — the serving path for models
    # that exceed one chip's HBM, not just the train path.
    params = runtime.get_params(
        f"{model_id}#{family}#{hash(cfg_key(cfg)) & 0xFFFFFFFF:08x}",
        lambda: _build_params(model_id, cfg, family),
        specs=specs,
    )
    attn_fn = runtime.attention_fn()  # ring over sp when the mesh has one

    # Pipeline-parallel routing (SURVEY §2.8 "strategies usable by the
    # workload"): a pp axis on the serving mesh, or model_config {"pp": N},
    # sends the encoder's block stack through the GPipe shard_map schedule.
    # With a derived mesh (same devices, dp×pp layout) XLA reshards the
    # dp-placed inputs at the jit boundary; workers that serve pp-heavy
    # models full-time should put the pp axis in MESH_SHAPE instead.
    pp_mesh = None
    if family == "encoder":
        if runtime.axis_size("pp") > 1:
            pp_mesh = runtime.mesh
        elif getattr(cfg, "pp", 1) > 1:
            from agent_tpu.runtime.mesh import build_mesh

            pp = cfg.pp
            n_dev = runtime.n_devices
            if n_dev % pp != 0:
                raise ValueError(
                    f"pp={pp} does not divide the {n_dev}-device mesh"
                )
            pp_mesh = build_mesh(
                runtime.devices, {"dp": n_dev // pp, "pp": pp}
            )
    if pp_mesh is not None:
        from agent_tpu.parallel.pipeline import encoder_forward_pp

        # Inside the pp shard_map the per-stage attention must be a plain
        # per-shard function (a nested mesh wrapper would shard_map twice):
        # the bare flash kernel on TPU, dense elsewhere.
        if runtime.platform == "tpu" and runtime.config.pallas_attn:
            from agent_tpu.kernels.flash_attention import (
                flash_attention as pp_attn,
            )
        else:
            from agent_tpu.models.layers import (
                dot_product_attention as pp_attn,
            )

    pending: List[Tuple[Any, Any, int]] = []
    for ids, lengths, n in chunks:
        B, L = ids.shape

        def build(L=L):
            def run_fwd(p, i, nlen):
                mask = (jnp.arange(L)[None, :] < nlen[:, None]).astype(jnp.int32)
                ids = i.astype(jnp.int32)
                if i.dtype == jnp.uint8:
                    # Raw-byte wire (stage_text_chunks): unshifted bytes on
                    # the wire, ids rebuilt on device. Trace-time branch —
                    # jit specializes per input dtype, so the uint16/int32
                    # wires trace without it.
                    ids = (ids + tokenizer.N_SPECIAL) * mask
                if pp_mesh is not None:
                    logits = encoder_forward_pp(
                        p, ids, mask, cfg, pp_mesh,
                        attn_fn=pp_attn,
                    )
                elif family == "encoder":
                    logits = model_mod.forward(
                        p, ids, mask, cfg, attn_fn=attn_fn,
                        mesh=runtime.mesh,  # ep expert sharding for MoE cfgs
                    )
                else:
                    logits = model_mod.forward(
                        p, ids, mask, cfg, attn_fn=attn_fn
                    )
                vals, idx = encoder.topk_probs(logits, k)
                # One fused [B, k, 2] f32 result: a device→host read costs a
                # full round trip regardless of size (tunneled hosts measure
                # ~60 ms each), so vals+idx must fetch as ONE array. idx
                # rides as its exact int32 bitpattern, no magnitude limit.
                return jnp.stack(
                    [vals, jax.lax.bitcast_convert_type(idx, jnp.float32)],
                    axis=-1,
                )

            return jax.jit(run_fwd)

        # k is fused into the executable, so a task stream alternating topk
        # values recompiles per (shape, k). Measured trade-off: splitting
        # top-k into its own jit avoids that but costs an extra dispatch
        # round-trip every call (-15% bench throughput); jobs use one topk,
        # so the fused form wins.
        fn = runtime.compiled(
            ("map_classify_tpu", model_id, family, B, L, k, cfg_key(cfg)),
            build,
        )
        packed = fn(
            params, runtime.put_batch(ids), runtime.put_batch(lengths)
        )
        pending.append((packed, n))
    if len(pending) > 1:
        # Gather the chunk results on DEVICE here, on the dispatching
        # (owner) thread: each host read of a device array is a full tunnel
        # round trip, so fetching 16 chunks separately would pay 16 round
        # trips where one suffices — and in pipelined no-fallback mode the
        # fetch happens on the poster thread, which must only ever READ
        # device arrays (single-owner dispatch invariant, agent/pipeline.py).
        packed_d = _concat_pending()([p for p, _ in pending])
        pending = [("cat", packed_d, [(p.shape[0], n) for p, n in pending])]
    if not fetch:
        return pending
    return _fetch_pending(pending)


_concat_fn = None


def _concat_pending():
    """Module-cached jitted device concat (jit reuses its own executable
    cache per chunk-shape signature). Called from the dispatching thread
    ONLY — see the single-owner note in :func:`_execute_chunks`."""
    global _concat_fn
    if _concat_fn is None:
        import jax
        import jax.numpy as jnp

        _concat_fn = jax.jit(lambda ps: jnp.concatenate(ps, axis=0))
    return _concat_fn


def _fetch_pending(pending) -> Tuple[np.ndarray, np.ndarray]:
    """Sync pending device results → (vals [N, k], idx [N, k]) numpy,
    trimming padding rows — ONE ``np.asarray`` (= one device→host round
    trip) per shard: chunks return a packed [B, k, 2] array (scores, idx
    bitcast to f32) and multi-chunk shards were already gathered into one
    ``("cat", packed, layout)`` entry on the device thread at dispatch time.
    Pure READS of device arrays, so the pipelined poster thread may call
    it."""
    first = pending[0]
    if isinstance(first[0], str):  # ("cat", packed, layout)
        _, packed_d, layout = first
        arr = np.asarray(packed_d)
        out, off = [], 0
        for B, n in layout:
            out.append(arr[off:off + n])
            off += B
        arr = np.concatenate(out)
    else:  # (packed, n)
        packed_d, n = first
        arr = np.asarray(packed_d)[:n]
    vals = np.ascontiguousarray(arr[..., 0])
    idx = np.ascontiguousarray(arr[..., 1]).view(np.int32)
    return vals, idx


def _get_cpu_runtime():
    global _cpu_runtime
    if _cpu_runtime is None:
        import jax

        from agent_tpu.config import DeviceConfig
        from agent_tpu.runtime.runtime import TpuRuntime

        # One device, dp=1: the degraded path must accept chunks staged for
        # ANY primary mesh (every batch bucket divides 1), and production
        # hosts expose a single cpu device anyway.
        _cpu_runtime = TpuRuntime(
            config=DeviceConfig(tpu_disabled=True),
            devices=jax.devices("cpu")[:1],
        )
    return _cpu_runtime


def stage(payload: Any, ctx: Optional[object] = None):
    """Host-only phase. Returns ``("done", result)`` for immediate soft
    results (bad input) or ``("staged", state)`` for :func:`execute`.

    Thread-safe: touches no device state (the mesh shape read off an existing
    runtime is host metadata). Shard-read and tokenize errors follow the
    drain contract — ValueError → soft result, I/O / integrity errors raise.
    """
    t0 = time.perf_counter()
    if not isinstance(payload, dict):
        return "done", bad_input("payload must be a dict")

    topk = payload.get("topk", DEFAULT_TOPK)
    if isinstance(topk, bool) or not isinstance(topk, int) or topk <= 0:
        return "done", bad_input("topk must be a positive int")
    result_format = payload.get("result_format", "rows")
    if result_format not in ("rows", "columnar"):
        return "done", bad_input("result_format must be 'rows' or 'columnar'")

    model_id = _resolve_model_id(payload)
    family = _resolve_family(model_id)
    from agent_tpu.ops._model_common import resolve_runtime

    rt = resolve_runtime(ctx)  # one resolution serves guards and staging
    try:
        # Checkpoint-integrity problems (unreadable config.json, missing
        # vocab) raise past this handler on purpose: they fail the shard for
        # retry rather than soft-dropping it as caller error.
        cfg = (
            _get_bert_cfg(model_id, payload) if family == "bert"
            else _get_cfg(payload)
        )
        from agent_tpu.ops._model_common import apply_quant_env

        cfg = apply_quant_env(payload, cfg)
        if family == "encoder":
            # Strategy-combination guards (caller error → soft bad_input):
            # pp stages the stacked block pytree and MoE/int8 reshape its
            # leaves — the unsupported pairings must reject, not mis-serve.
            # The EFFECTIVE pp is the mesh's pp axis when the serving mesh
            # has one (execute routes through the pipeline for it with no
            # payload involvement), else model_config's pp — guarding only
            # cfg.pp would let the mesh-axis route bypass every check.
            mesh_pp = rt.axis_size("pp") if rt is not None else 1
            eff_pp = mesh_pp if mesh_pp > 1 else getattr(cfg, "pp", 1)
            # (int8 composes with BOTH pp and MoE since round 5: quantized
            # leaves are ordinary pytrees for the GPipe stack/scan, and MoE
            # expert FFNs take per-expert int8 — quant.qmoe_expert. The
            # former soft-rejections are now equality-tested serving modes,
            # tests/test_pp_moe_serving.py.)
            if eff_pp > 1:
                if cfg.n_layers % eff_pp != 0:
                    raise ValueError(
                        f"n_layers {cfg.n_layers} not divisible by pp={eff_pp}"
                    )
                if cfg.moe_experts > 0:
                    raise ValueError(
                        "pp and moe_experts cannot combine in one config"
                    )
                if mesh_pp <= 1 and rt is not None \
                        and rt.n_devices % eff_pp != 0:
                    raise ValueError(
                        f"pp={eff_pp} does not divide the "
                        f"{rt.n_devices}-device mesh"
                    )
        items, kind, single = _collect_sequences(payload, cfg)
        from agent_tpu.ops._model_common import (
            validate_output_uri,
            validate_start_row,
        )

        output_dir = validate_output_uri(payload)
        start_row = validate_start_row(payload)
    except ValueError as exc:
        return "done", bad_input(str(exc))

    # Batch buckets must divide the mesh that will execute them. The pp
    # schedule additionally needs batches divisible by n_micro × pipeline-dp
    # (= pp·dp on a pp mesh; = all devices for a derived mesh), so pp
    # configs stage with that larger divisor.
    dp_stage = rt.axis_size("dp") if rt is not None else 1
    if family == "encoder" and rt is not None:
        if rt.axis_size("pp") > 1:
            dp_stage = rt.axis_size("pp") * rt.axis_size("dp")
        elif getattr(cfg, "pp", 1) > 1:
            dp_stage = rt.n_devices
    chunks = _stage_chunks(
        dp_stage, items, kind, cfg, family=family, model_id=model_id
    )

    state = {
        "t0": t0,
        "chunks": chunks,
        "n_rows": len(items),
        "cfg": cfg,
        "k": min(topk, cfg.n_classes),  # clamp so lax.top_k stays legal
        "model_id": model_id,
        "family": family,
        "result_format": result_format,
        "allow_fallback": bool(payload.get("allow_fallback", True)),
        "single": single,
        "output_dir": output_dir,
        "start_row": start_row,
        "t_staged": time.perf_counter(),
    }
    return "staged", state


def _stamp_flops(state: Dict[str, Any], ctx: Optional[object]) -> None:
    """Analytic-FLOPs attribution (ISSUE 8): estimate the dispatched matmul
    FLOPs from the staged chunk shapes and the model config, stamped into
    ``ctx.tags["device_attr"]`` so the agent can export ``device_mfu{op}``.
    Dimension names differ per family (encoder: d_model/d_ff/n_layers,
    BERT: hidden_size/intermediate_size/num_layers); a config missing them
    simply doesn't stamp — MFU is then absent, never wrong."""
    cfg = state.get("cfg")
    d = getattr(cfg, "d_model", None) or getattr(cfg, "hidden_size", None)
    f = getattr(cfg, "d_ff", None) or getattr(cfg, "intermediate_size", None)
    n_layers = (
        getattr(cfg, "n_layers", None) or getattr(cfg, "num_layers", None)
    )
    if not (d and f and n_layers):
        return
    from agent_tpu.ops._model_common import (
        encoder_fwd_flops,
        stamp_device_flops,
    )

    total = 0.0
    biggest = (0, "?")
    for chunk in state.get("chunks") or []:
        try:
            B, L = chunk[0].shape
        except Exception:  # noqa: BLE001 — estimation must never fail a shard
            continue
        total += encoder_fwd_flops(
            B, L, d, f, n_layers, getattr(cfg, "n_classes", 0) or 0
        )
        if B * L > biggest[0]:
            biggest = (B * L, f"B{B}xL{L}")
    if total > 0:
        stamp_device_flops(ctx, total, biggest[1])


def execute(state: Dict[str, Any], ctx: Optional[object] = None) -> Dict[str, Any]:
    """Device phase (owning thread only): run staged chunks on the mesh,
    falling back to the CPU backend per the degraded-mode contract."""
    # Stamped here, not at stage end: in pipelined mode the item may sit in
    # the bounded queue between phases, and that wait must not count as
    # device time (it shows up as queue_ms instead).
    state["t_exec0"] = time.perf_counter()
    _stamp_flops(state, ctx)
    model_id, cfg, k = state["model_id"], state["cfg"], state["k"]
    fallback_reason = None
    try:
        if ctx is not None and getattr(ctx, "require_runtime", None):
            runtime = ctx.require_runtime()
        else:
            from agent_tpu.runtime.runtime import get_runtime

            runtime = get_runtime()
        if not state["allow_fallback"]:
            # Drain mode (no CPU retry promised): leave the device arrays
            # unfetched so finalize — the pipeline's poster thread — pays
            # the device→host round trip while THIS thread dispatches the
            # next shard. A device failure then surfaces at fetch time and
            # fails the shard, exactly the no-fallback contract.
            state.update(
                pending_dev=_execute_chunks(
                    runtime, state["chunks"], model_id, cfg, k,
                    family=state["family"], fetch=False,
                ),
                device=runtime.platform,
                fallback_reason=None,
                t_device=time.perf_counter(),
            )
            return state
        vals, idx = _execute_chunks(
            runtime, state["chunks"], model_id, cfg, k,
            family=state["family"],
        )
        device = runtime.platform
    except Exception as exc:  # noqa: BLE001 — any device failure → fallback path
        if not state["allow_fallback"]:
            raise
        try:
            runtime = _get_cpu_runtime()
            vals, idx = _execute_chunks(
                runtime, state["chunks"], model_id, cfg, k,
                family=state["family"],
            )
            device = runtime.platform
            fallback_reason = f"{type(exc).__name__}: {exc}"
        except Exception as cpu_exc:  # noqa: BLE001 — truly degraded
            if not state["single"]:
                # Batch/drain shards must FAIL (→ controller retry), not
                # report a degraded empty result that silently drops every
                # row of the shard; the reference's degraded contract is a
                # single-row interactive shape (ref :22-28).
                raise
            state["degraded_reason"] = (
                f"{type(exc).__name__}: {exc}; cpu retry: {cpu_exc}"
            )
            state["t_device"] = time.perf_counter()
            return state
    state.update(
        vals=vals, idx=idx, device=device, fallback_reason=fallback_reason,
        t_device=time.perf_counter(),
    )
    return state


def finalize(state: Dict[str, Any], ctx: Optional[object] = None) -> Dict[str, Any]:
    """Host serialization phase: numpy top-k → the JSON-shaped result. Safe
    off the device thread (reads fetched arrays only)."""
    t0, model_id = state["t0"], state["model_id"]
    result_format = state["result_format"]

    if "degraded_reason" in state:
        # Reference degraded shape (ref ops/map_classify_tpu.py:22-28),
        # carrying whichever empty result keys the requested format promises.
        out = {
            "ok": True,
            "op": "map_classify_tpu",
            "model_path": model_id,
            "fallback": "cpu",
            "reason": state["degraded_reason"][:500],
            "elapsed_ms": (time.perf_counter() - t0) * 1000.0,
        }
        if result_format == "columnar":
            out["indices"] = []
            out["scores"] = []
        else:
            out["topk"] = []
        return out

    if "pending_dev" in state:
        # Deferred fetch (no-fallback mode): sync the device results here,
        # off the device thread. elapsed_ms keeps covering the true span;
        # the wait is stamped as timings.fetch_ms (device_ms is dispatch
        # only in this mode).
        t_f = time.perf_counter()
        vals, idx = _fetch_pending(state["pending_dev"])
        state["fetch_ms"] = (time.perf_counter() - t_f) * 1000.0
    else:
        vals, idx = state["vals"], state["idx"]

    if ctx is not None and hasattr(ctx, "tags"):
        # Per-stage trace (SURVEY.md §5.1): staging = payload → token rows
        # (incl. shard read); queue = wait between phases (pipelined mode);
        # device = params + transfer + compute (+ fetch, except in the
        # deferred-fetch no-fallback mode, where the fetch lands in
        # fetch_ms on the finalize span so the device thread stays free to
        # dispatch).
        ctx.tags.setdefault("timings", {}).update(
            stage_ms=round((state["t_staged"] - t0) * 1000.0, 3),
            queue_ms=round((state["t_exec0"] - state["t_staged"]) * 1000.0, 3),
            device_ms=round((state["t_device"] - state["t_exec0"]) * 1000.0, 3),
            **(
                {"fetch_ms": round(state["fetch_ms"], 3)}
                if "fetch_ms" in state else {}
            ),
        )
    from agent_tpu.ops._model_common import stamp_rows

    stamp_rows(ctx, state["n_rows"])
    out: Dict[str, Any] = {
        "ok": True,
        "op": "map_classify_tpu",
        "model_path": model_id,
        "device": state["device"],
        "n_rows": state["n_rows"],
        "elapsed_ms": (time.perf_counter() - t0) * 1000.0,
    }
    if state["fallback_reason"] is not None:
        out["fallback"] = "cpu"
        out["reason"] = state["fallback_reason"]

    if state["output_dir"] is not None:
        # Result-sink mode: full per-row top-k goes to disk; the wire carries
        # a receipt. At drain scale the controller must not hold row payloads.
        from agent_tpu.ops._model_common import write_output_shard

        idx_l = np.asarray(idx).tolist()
        val_l = np.round(np.asarray(vals), 6).tolist()
        path, n = write_output_shard(
            state["output_dir"], "map_classify_tpu", state["start_row"],
            ({"indices": i, "scores": s} for i, s in zip(idx_l, val_l)),
        )
        out["output_path"] = path
        out["rows_written"] = n
        return out

    if result_format == "columnar":
        if ctx is not None and hasattr(ctx, "tags") \
                and ctx.tags.get("wire") == "b1":
            # Binary shard wire (ISSUE 6): ship the [N, k] columns as raw
            # arrays (indices width-shrunk, scores as the rounded f32 bit
            # patterns) instead of tolist()-ing them into JSON — the
            # controller decodes to the exact lists the JSON path would
            # have produced (same np.round(…, 6) then-widen semantics), so
            # binary and JSON drains are bit-identical.
            from agent_tpu.data import wire

            return wire.attach_result_columns(out, {
                "indices": np.ascontiguousarray(idx),
                "scores": np.round(np.asarray(vals), 6),
            })
        # Drain-friendly wire shape: [N, k] index/score arrays instead of
        # 5·N score dicts — ~3× smaller JSON and ~4× faster to serialize,
        # which is real money when results travel per-shard over HTTP.
        out["indices"] = np.asarray(idx).tolist()
        out["scores"] = np.round(np.asarray(vals), 6).tolist()
        return out

    from agent_tpu.models.encoder import topk_rows

    per_row = topk_rows(vals, idx)
    out["topk"] = per_row[0]
    if not state["single"]:
        out["results"] = [{"topk": t} for t in per_row]
    return out


@register_op("map_classify_tpu")
def run(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    """Classic monolithic entry: stage → execute → finalize inline."""
    phase, value = stage(payload, ctx)
    if phase == "done":
        return value
    return finalize(execute(value, ctx), ctx)


# Phase hooks for the pipelined drain (agent_tpu.agent.pipeline): the agent
# discovers them via these attributes, so ops without phases run monolithic.
run.stage = stage
run.execute = execute
run.finalize = finalize
