"""Op registry and dispatch.

This is the *intended* design of the reference registry (reference
``ops/__init__.py:35-108`` + ``ops_loader.py``) with its four shipped wiring gaps
fixed (SURVEY.md §1):

1. The registry is the **only** dispatch table — the agent loop uses it (the
   reference agent ignored its registry and kept a private 2-entry dict,
   reference ``app.py:135-138``).
2. Every entry in ``OP_TO_MODULE`` maps to a module that exists (the reference
   mapped four phantom modules, reference ``ops/__init__.py:21-25``).
3. Registered names equal map keys (the reference registered ``read_csv_shard``
   under map key ``csv_shard``, making the op unreachable both ways,
   reference ``ops/__init__.py:20`` vs ``ops/csv_shard.py:29``).
4. The ERP triggers are proper registered ops (the reference shipped them as
   bare unwired ``run()`` functions, reference ``ops/trigger_sap.py:9``).

Semantics preserved from the reference:
- ``register_op(name)`` decorator populates the registry at module import
  (ref ``ops/__init__.py:35-39``).
- Lazy import: modules load on first ``get_op``; import failures are recorded in
  ``OPS_LOAD_ERRORS`` and surfaced in rich error messages, never at package
  import (ref ``ops/__init__.py:74-84``), so the agent boots on hosts missing
  heavy deps — the moral equivalent of booting without pycoral
  (ref ``ops/_tpu_runtime.py:45-46``).
- TASKS gating with ``*``/``all``/``none`` sentinels (ref ``ops/__init__.py:42-71``).

Op call contract: ``fn(payload: dict, ctx: OpContext | None = None) -> dict``.
The optional ``ctx`` carries the device runtime (mesh, compiled-op cache); pure
host ops ignore it — same shape as the reference's optional ``ctx`` on the TPU op
(ref ``ops/map_classify_tpu.py:32``).
"""

from __future__ import annotations

import importlib
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

OpFn = Callable[..., Dict[str, Any]]

# name -> handler. Populated by @register_op side effects at module import.
OPS_REGISTRY: Dict[str, OpFn] = {}
# [(module_name, repr(error))] — import failures, recorded not raised.
OPS_LOAD_ERRORS: List[Tuple[str, str]] = []

# Static lazy-import map: op name -> submodule of agent_tpu.ops.
# Invariant (tested): every module exists and registers exactly its key.
OP_TO_MODULE: Dict[str, str] = {
    "echo": "echo",
    "map_tokenize": "map_tokenize",
    "map_classify_tpu": "map_classify_tpu",
    "map_summarize": "map_summarize",
    # MPMD pipeline stages (ISSUE 7 stretch): summarize's encoder and
    # decoder as separate ops, chained across agents via dep-gating.
    "summarize_encode": "summarize_mpmd",
    "summarize_decode": "summarize_mpmd",
    # Request-serving ops (ISSUE 15): the agent half of POST /v1/infer —
    # batched interactive classify + the continuous-batching decode engine.
    "serve_classify": "serve_infer",
    "serve_summarize": "serve_infer",
    # Disaggregated serving pools (ISSUE 16): prefill and decode as
    # separate ops so the fleets can split (SERVE_DISAGG=1), chained via
    # dep-gating like the MPMD stages.
    "serve_prefill": "serve_infer",
    "serve_decode": "serve_infer",
    "read_csv_shard": "csv_shard",       # name == registered name (gap 3 fixed)
    "risk_accumulate": "risk_accumulate",
    "trigger_sap": "trigger_sap",        # now a real registered op (gap 4 fixed)
    "trigger_oracle": "trigger_oracle",
    "train_classifier": "train_classifier",  # train → .npz artifact → serve
}

# Deterministic ops whose results may be served from the content-addressed
# result cache (ISSUE 19): same payload + model version => bit-identical
# result dict. Excluded on purpose: ``read_csv_shard`` (reads mutable files
# behind a URI), the ERP triggers (external side effects), ``train_classifier``
# (writes an artifact), and the decode-side serving ops (their payloads embed
# per-request ids). The serving front door caches ``serve_classify`` /
# ``serve_summarize`` at request granularity itself, keyed on
# (op, text, params) before bucketing.
CACHEABLE_OPS = frozenset(
    {
        "echo",
        "map_tokenize",
        "map_classify_tpu",
        "map_summarize",
        "summarize_encode",
        "summarize_decode",
        "risk_accumulate",
    }
)


def is_cacheable(name: str) -> bool:
    """True when ``name`` is registered as deterministic/cache-safe."""
    return name in CACHEABLE_OPS


_imported: Dict[str, bool] = {}
_lock = threading.Lock()
_plugins_loaded = False


def load_plugins(paths: Optional[str] = None) -> List[str]:
    """Load extra op modules from ``OPS_PLUGIN_PATH`` (``:``-separated files).

    The reference's extension point was an optional ``tpu_ops.py`` imported
    from beside the app (reference ``app.py:118-123``) that could provide
    ``map_classify_tpu``. Generalized: each path is executed as a module and
    its ``@register_op`` decorations land in the shared registry (and in
    ``OP_TO_MODULE`` so TASKS gating and ``list_ops`` see them). Missing files
    and import errors are recorded in ``OPS_LOAD_ERRORS``, never raised — the
    agent must boot without its plugins, like the reference without
    ``tpu_ops.py`` (ref ``app.py:126-132``).

    Returns the op names newly registered by plugins.
    """
    global _plugins_loaded
    raw = paths if paths is not None else os.environ.get("OPS_PLUGIN_PATH", "")
    if paths is None:
        with _lock:
            if _plugins_loaded:
                return []
            _plugins_loaded = True
    new_names: List[str] = []
    for path in [p for p in (raw or "").split(":") if p.strip()]:
        before = set(OPS_REGISTRY)
        try:
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                f"agent_tpu_plugin_{abs(hash(path)) & 0xFFFF:04x}", path
            )
            if spec is None or spec.loader is None:
                raise ImportError(f"cannot load plugin {path!r}")
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception as exc:  # noqa: BLE001 — recorded, not raised
            OPS_LOAD_ERRORS.append((f"plugin:{path}", repr(exc)))
            # Roll back partial registrations: an op registered by a plugin
            # that then failed to import would otherwise sit in OPS_REGISTRY
            # with no OP_TO_MODULE entry — registered but unreachable.
            for name in set(OPS_REGISTRY) - before:
                if name not in OP_TO_MODULE:
                    del OPS_REGISTRY[name]
            continue
        for name in set(OPS_REGISTRY) - before:
            if name in OP_TO_MODULE:
                # A builtin registered as a side effect of the plugin's own
                # imports (e.g. `from agent_tpu.ops.echo import run`) — not
                # the plugin's op; leave its builtin attribution alone.
                continue
            OP_TO_MODULE[name] = f"plugin:{path}"
            _imported[f"plugin:{path}"] = True
            new_names.append(name)
    return new_names


def register_op(name: str) -> Callable[[OpFn], OpFn]:
    """Decorator: register ``fn`` under ``name`` (ref ops/__init__.py:35-39)."""

    def deco(fn: OpFn) -> OpFn:
        OPS_REGISTRY[name] = fn
        return fn

    return deco


def _parse_tasks_env(raw: Optional[str] = None) -> Optional[List[str]]:
    """TASKS env → enabled-op filter. None means "no filter" (all enabled).

    Sentinels per reference ``ops/__init__.py:42-57``: ``*`` or ``all`` → all ops;
    ``none`` → empty set; unset → all.
    """
    if raw is None:
        raw = os.environ.get("TASKS", "")
    toks = [t.strip() for t in raw.split(",") if t.strip()]
    if not toks:
        return None
    low = [t.lower() for t in toks]
    if "*" in toks or "all" in low:
        return None
    if low == ["none"]:
        return []
    return toks


def _is_enabled(name: str, tasks: Optional[List[str]] = None) -> bool:
    enabled = _parse_tasks_env() if tasks is None else (_parse_tasks_env(",".join(tasks)) if tasks else [])
    return enabled is None or name in enabled


def list_ops() -> List[str]:
    """All known op names, filtered by the TASKS gate (ref ops/__init__.py:60-65)."""
    enabled = _parse_tasks_env()
    names = sorted(OP_TO_MODULE)
    if enabled is None:
        return names
    return [n for n in names if n in enabled]


def _import_op_module(module: str) -> None:
    """Import ``agent_tpu.ops.<module>`` once; record failures (ref :74-84)."""
    with _lock:
        if _imported.get(module):
            return
        try:
            importlib.import_module(f"agent_tpu.ops.{module}")
            _imported[module] = True
        except Exception as exc:  # noqa: BLE001 — deliberately broad, recorded
            OPS_LOAD_ERRORS.append((module, repr(exc)))
            _imported[module] = False


def get_op(name: str) -> OpFn:
    """Resolve an op name to its handler, or raise with a rich diagnostic.

    Resolution order mirrors reference ``ops/__init__.py:87-108``:
    enabled-check → module map → lazy import → registry lookup.
    """
    if not _is_enabled(name):
        raise KeyError(
            f"op {name!r} is not enabled by TASKS={os.environ.get('TASKS', '')!r}; "
            f"enabled ops: {list_ops()}"
        )
    module = OP_TO_MODULE.get(name)
    if module is None:
        raise KeyError(
            f"unknown op {name!r}; known ops: {sorted(OP_TO_MODULE)}"
        )
    _import_op_module(module)
    fn = OPS_REGISTRY.get(name)
    if fn is None:
        errs = "; ".join(f"{m}: {e}" for m, e in OPS_LOAD_ERRORS[:10])
        raise KeyError(
            f"op {name!r} did not register (module {module!r}). "
            f"registered: {sorted(OPS_REGISTRY)}. import errors: {errs or 'none'}"
        )
    return fn


def load_ops(tasks: List[str]) -> Dict[str, OpFn]:
    """Resolve a list of op names at startup; raise early on any unknown/disabled
    name (successor of reference ``ops_loader.py:8-19`` — now actually used by
    the agent)."""
    load_plugins()  # OPS_PLUGIN_PATH extras join the registry first (once)
    handlers: Dict[str, OpFn] = {}
    for name in tasks:
        handlers[name] = get_op(name)
    return handlers
