"""SAP S/4HANA Quality Notification webhook op.

Capability parity with reference ``ops/trigger_sap.py:9-33`` (an ERP trigger
posting an OData Quality Notification built from ``{event_type, material,
text}``, credentials from SAP_HOST/SAP_USER/SAP_PASS) — but properly wired: the
reference shipped this as a bare ``run()`` with no registration (SURVEY.md §1
gap 4). Network egress is optional: with no SAP_HOST configured, or with
``dry_run: true``, the op returns the request it *would* send, which is also
how tests exercise it hermetically.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input

ODATA_PATH = "/sap/opu/odata/sap/API_QUALITYNOTIFICATION_SRV/A_QualityNotification"


@register_op("trigger_sap")
def run(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    if not isinstance(payload, dict):
        return bad_input("payload must be a dict")
    event_type = payload.get("event_type", "quality_alert")
    material = payload.get("material")
    text = payload.get("text", "")
    if not isinstance(material, str) or not material:
        return bad_input("material is required and must be a non-empty string")

    host = os.environ.get("SAP_HOST")
    body = {
        "NotificationType": "Q1" if event_type == "quality_alert" else "Q2",
        "Material": material,
        "NotificationText": str(text)[:40],  # S/4 short-text limit
    }
    request = {"method": "POST", "url": f"{host or '<SAP_HOST unset>'}{ODATA_PATH}", "json": body}

    if not host or payload.get("dry_run", False):
        return {"ok": True, "dry_run": True, "request": request}

    import requests  # lazy: agent boots without it configured

    try:
        resp = requests.post(
            f"{host}{ODATA_PATH}",
            json=body,
            auth=(os.environ.get("SAP_USER", ""), os.environ.get("SAP_PASS", "")),
            timeout=10,
        )
        return {"ok": resp.status_code < 300, "status": resp.status_code, "request": request}
    except requests.RequestException as exc:
        return {"ok": False, "error": f"sap request failed: {exc}", "request": request}
