"""Tokenize op: batched real tokenization, plus the reference's chunking mode.

Capability parity with reference ``ops/map_tokenize.py:12-61``:

- ``payload["text"]`` / ``payload["data"]`` single-string mode (ref ``:51``) and
  ``payload["items"]`` list mode with flattened chunks + per-item counts
  (ref ``:29-48``).
- ``mode: "chars"`` reproduces the reference behavior exactly: fixed-size
  character windows, default ``chunk_size=1024`` (ref ``:24``).
- Validation errors come back as ``{"ok": False, "error": ...}`` (ref ``:25-32``).

The upgrade (BASELINE.json: "map_tokenize ... HF tokenizer", made egress-free):
``mode: "tokens"`` (the default) runs a real tokenizer — byte-level by
default, ``tokenizer: "wordpiece"`` with a local vocab.txt, or
``tokenizer: "bpe"`` with a local GPT-2/BART vocab directory
(``vocab_path`` = dir holding vocab.json + merges.txt, e.g. an HF checkpoint
dir; ids match ``transformers``' tokenizer exactly, see ``models/bpe.py``) —
chunking the *token* stream into windows of ``chunk_size`` ids (default
1024). The whole items list is tokenized as one batch on the host —
tokenization is host work by design; the device pipeline consumes its padded
output (see ``agent_tpu.models.tokenizer.pad_batch``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input

DEFAULT_CHUNK_SIZE = 1024


def _chunks(seq, size: int) -> List:
    return [seq[i : i + size] for i in range(0, len(seq), size)] or [seq[:0]]


@register_op("map_tokenize")
def run(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    if not isinstance(payload, dict):
        return bad_input("payload must be a dict")

    chunk_size = payload.get("chunk_size", DEFAULT_CHUNK_SIZE)
    if not isinstance(chunk_size, int) or chunk_size <= 0:
        return bad_input("chunk_size must be a positive int")
    mode = payload.get("mode", "tokens")
    if mode not in ("tokens", "chars"):
        return bad_input(f"unknown mode {mode!r} (expected 'tokens' or 'chars')")

    # Collect input texts: items list, or single text/data (ref :29-51).
    if "items" in payload:
        items = payload["items"]
        if not isinstance(items, list) or not all(isinstance(t, str) for t in items):
            return bad_input("items must be a list of strings")
        single = False
    else:
        text = payload.get("text", payload.get("data"))
        if not isinstance(text, str):
            return bad_input("payload requires 'text'/'data' string or 'items' list")
        items = [text]
        single = True

    if mode == "chars":
        per_item = [_chunks(t, chunk_size) for t in items]
        flat = [c for cs in per_item for c in cs]
        out: Dict[str, Any] = {
            "ok": True,
            "mode": "chars",
            "chunk_size": chunk_size,
            "chunks": flat,
            "counts": [len(cs) for cs in per_item],
            "n_items": len(items),
            "n_chunks": len(flat),
            # Reference wire-contract aliases (reference
            # ``ops/map_tokenize.py:42-48,56-61``) so reference-era consumers
            # keep working: tokens == chunks, count == n_chunks,
            # total_chars; items mode also gets items_count.
            "tokens": flat,
            "count": len(flat),
            "total_chars": sum(len(t) for t in items),
        }
        if single:
            out["n_chars"] = len(items[0])
        else:
            out["items_count"] = len(items)
        return out

    from agent_tpu.models.tokenizer import get_tokenizer  # lazy: keep import light

    try:
        tok = get_tokenizer(
            payload.get("tokenizer", "byte"), payload.get("vocab_path")
        )
    except (ValueError, OSError) as exc:
        return bad_input(str(exc))

    try:
        encoded = [tok.encode(t) for t in items]
    except KeyError as exc:
        # An inconsistent vocab/merges pair (merge product or base symbol
        # missing from vocab.json) is caller input, not a crash: soft error
        # per the op contract.
        return bad_input(f"vocab is missing token {exc} (inconsistent "
                         "vocab.json/merges.txt?)")
    per_item = [_chunks(ids, chunk_size) for ids in encoded]
    flat = [c for cs in per_item for c in cs]
    out = {
        "ok": True,
        "mode": "tokens",
        "tokenizer": payload.get("tokenizer", "byte"),
        "vocab_size": tok.vocab_size,
        "chunk_size": chunk_size,
        "chunks": flat,
        "counts": [len(cs) for cs in per_item],
        "token_counts": [len(ids) for ids in encoded],
        "n_items": len(items),
        "n_chunks": len(flat),
        "n_tokens": sum(len(ids) for ids in encoded),
    }
    return out
