"""Diagnostic echo op — controller↔agent plumbing test.

Capability parity with reference ``ops/echo.py:7-24``: returns the payload
verbatim under ``echo`` with ``ok: True``, tolerating ``None`` and non-dict
payloads rather than raising (ref ``:17-22``). Kept host-only on purpose: it
must work before any device runtime exists, since it is the first op a fresh
deployment runs (ref ``ops/echo.py:9-14``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from agent_tpu.ops import register_op


@register_op("echo")
def run(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    if payload is None:
        payload = {}
    return {"ok": True, "echo": payload}
