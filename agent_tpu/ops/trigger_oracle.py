"""Oracle SCM Cloud inventory-transaction webhook op.

Capability parity with reference ``ops/trigger_oracle.py:9-35`` (posts an
inventory transaction built from ``{event, item, qty}``, credentials from
ORACLE_HOST/ORA_USER/ORA_PASS), properly registered (SURVEY.md §1 gap 4 fixed).
Hermetic by default: no ORACLE_HOST, or ``dry_run: true``, returns the request
that would be sent.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input

REST_PATH = "/fscmRestApi/resources/11.13.18.05/inventoryStagedTransactions"


@register_op("trigger_oracle")
def run(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    if not isinstance(payload, dict):
        return bad_input("payload must be a dict")
    event = payload.get("event", "inventory_adjustment")
    item = payload.get("item")
    qty = payload.get("qty", 0)
    if not isinstance(item, str) or not item:
        return bad_input("item is required and must be a non-empty string")
    if isinstance(qty, bool) or not isinstance(qty, (int, float)):
        return bad_input("qty must be numeric")

    host = os.environ.get("ORACLE_HOST")
    body = {
        "TransactionType": event,
        "ItemNumber": item,
        "TransactionQuantity": qty,
    }
    request = {"method": "POST", "url": f"{host or '<ORACLE_HOST unset>'}{REST_PATH}", "json": body}

    if not host or payload.get("dry_run", False):
        return {"ok": True, "dry_run": True, "request": request}

    import requests

    try:
        resp = requests.post(
            f"{host}{REST_PATH}",
            json=body,
            auth=(os.environ.get("ORA_USER", ""), os.environ.get("ORA_PASS", "")),
            timeout=10,
        )
        return {"ok": resp.status_code < 300, "status": resp.status_code, "request": request}
    except requests.RequestException as exc:
        return {"ok": False, "error": f"oracle request failed: {exc}", "request": request}
