"""Summarization on the mesh — successor of the reference's torch-BART op.

Capability parity with reference ``ops/map_summarize.py:35-68``:

- Payload: required ``text`` (plus the batched upgrade ``texts``), optional
  ``max_length`` (default 130, ref ``:46``), ``model_path``.
- Result: ``{ok, summary, device, model}`` (ref ``:61-67``), plus timing.
- Input truncated at 1024 tokens (ref ``:49``).
- Lazy once-per-process model init (ref ``:17-33``) — via the runtime's HBM
  params store instead of a module-global + lock.

The decode itself is ``models.seq2seq.greedy_generate``: one compiled program,
``lax.scan`` over static steps, KV cache in HBM — replacing the reference's
host-side ``model.generate`` beam loop (ref ``:52-59``). SUMMARIZE_FORCE_CPU is
still honored as a kill-switch (ref ``:10``) but defaults off: BASELINE.json's
north star is zero CPU-side model execution.

Like ``map_classify_tpu``, the op is **phase-split** for the pipelined drain:
:func:`stage` (host — validation, shard read, fused tokenize+pad),
:func:`execute` (device — params, compiled decode *dispatch*; the token
arrays come back unfetched), :func:`finalize` (host — the deferred
device→host token fetch, a thread-safe read, then detokenize, sink write,
result shape). The summarize leg of an at-scale drain therefore overlaps
next-shard tokenization, the previous shard's fetch, and result posting
with device decode; ``run`` composes the phases for monolithic callers.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input

DEFAULT_MODEL_ID = "summarize-default"
DEFAULT_MAX_LENGTH = 130

# One-shot guard for the default-inversion notice in stage(): the framework
# default (device execution) is the INVERSE of the reference's CPU-on default,
# and that must be visible in operational logs of processes that actually run
# summarize (only those — hence here, not in config.py).
_force_cpu_default_logged = False


def _resolve_model_id(payload: Dict[str, Any]) -> str:
    from agent_tpu.ops._model_common import resolve_model_id

    return resolve_model_id(payload, "BART_MODEL", DEFAULT_MODEL_ID)


def _get_cfg(payload: Dict[str, Any]):
    from agent_tpu.models.seq2seq import Seq2SeqConfig
    from agent_tpu.ops._model_common import config_from_payload

    return config_from_payload(payload, Seq2SeqConfig)


def _resolve_family(model_id: str) -> str:
    """``model_path`` pointing at a local HF checkpoint directory serves the
    pretrained family: BART (the reference's actual summarize model, ref
    ``ops/map_summarize.py:29-32``) or T5 (the family BASELINE.json names);
    else the in-house seq2seq.

    Any OTHER checkpoint directory (an HF dir of a different model_type)
    fails the shard loudly: silently serving seeded random weights for what
    was unambiguously a checkpoint would return ok=true nonsense."""
    from agent_tpu.models import bart, bert, t5

    if bart.is_hf_bart_dir(model_id):
        return "bart"
    if t5.is_hf_t5_dir(model_id):
        return "t5"
    if bert.is_hf_dir(model_id):  # generic "HF checkpoint dir" detector
        raise RuntimeError(
            f"model_path {model_id!r} is a checkpoint directory but not a "
            "BART/T5 one (map_summarize serves model_type=bart|t5; "
            "classify serves BERT)"
        )
    return "seq2seq"


# model_config fields a payload may override for a checkpoint model:
# serving controls only (structural fields are the checkpoint's). "quant"
# accepts "int8" (W8A8) and "w8a16" (weight-only — the decode-targeted mode:
# summarize is weight-HBM-bound per step, so a T5/BART checkpoint serves
# with int8-resident weights dequantized in-register at dtype).
_CKPT_SERVING_OVERRIDES = ("dtype", "quant")


def _get_ckpt_cfg(model_id: str, payload: Dict[str, Any], family: str):
    import os as _os

    if family == "t5":
        from agent_tpu.models.t5 import T5Config as config_cls
    else:
        from agent_tpu.models.bart import BartConfig as config_cls

    overrides = payload.get("model_config")
    allowed = {}
    if isinstance(overrides, dict):
        allowed = {
            k: v for k, v in overrides.items()
            if k in _CKPT_SERVING_OVERRIDES
        }
    return config_cls.from_hf_json(
        _os.path.join(model_id, "config.json"), **allowed
    )


def _build_params(model_id: str, cfg, family: str = "seq2seq"):
    if family == "bart":
        from agent_tpu.models import bart

        _, params = bart.load_hf_dir(model_id, dtype=cfg.dtype)
    elif family == "t5":
        from agent_tpu.models import t5

        _, params = t5.load_hf_dir(model_id, dtype=cfg.dtype)
    else:
        from agent_tpu.models import seq2seq

        if model_id.endswith(".npz") and os.path.exists(model_id):
            params = seq2seq.load_npz(model_id, cfg)
        else:
            params = seq2seq.init_params(cfg, model_id=model_id)
    from agent_tpu.ops._model_common import maybe_quantize_params

    return maybe_quantize_params(params, family, cfg)


# Decode-row budget per compiled program: the per-step decode matmuls are
# [rows, d_model]-thin, so bigger programs fill the MXU better right up to
# this cap (measured on v5e at B=8192/greedy: 9,132 rows/s as ONE program
# vs 8,485 as 8 chained B=1024 programs). Beam search multiplies rows in
# flight by num_beams (beams flatten into the batch dim, and the KV caches
# size with B*K), so staging divides the budget by num_beams.
MAX_DECODE_ROWS = 8192


def _stage_chunks(dp: int, texts: List[str], cfg, num_beams: int = 1,
                  family: str = "seq2seq", model_id: str = "") -> List:
    """Shared staging scaffolding (``_model_common.stage_text_chunks``):
    fused byte tokenize+pad with BOS/EOS for the in-house seq2seq, the
    checkpoint's byte-level BPE (``<s> … </s>``) for the BART family."""
    from agent_tpu.ops._model_common import stage_text_chunks

    encode_pad = None
    if family == "bart":
        from agent_tpu.models import bart

        tok = bart.hf_bpe(model_id)

        def encode_pad(chunk, lb, bb):
            return bart.encode_pad_batch(tok, chunk, cfg, bb, lb)

    elif family == "t5":
        from agent_tpu.models import t5

        sp = t5.hf_spm(model_id)  # gated: actionable error sans sentencepiece

        def encode_pad(chunk, lb, bb):
            return t5.encode_pad_batch(sp, chunk, cfg, bb, lb)

    return stage_text_chunks(
        dp, texts, max_len=cfg.max_src_len, vocab_size=cfg.vocab_size,
        max_batch=max(1, MAX_DECODE_ROWS // num_beams),
        add_bos=True, add_eos=True,
        encode_pad=encode_pad,
    )


def _decode_chunks(runtime, chunks: List, model_id: str, cfg,
                   max_new: int, num_beams: int,
                   length_penalty: float = 1.0,
                   early_stopping: bool = False,
                   min_length: int = 0,
                   family: str = "seq2seq") -> List[Tuple[Any, int]]:
    """Device phase: decode staged chunks → pending ``[(toks_dev, n), ...]``
    device arrays (deferred fetch — see the return comment below; same
    pattern as classify's no-fallback mode).
    """
    import jax

    from agent_tpu.models import seq2seq
    from agent_tpu.ops._model_common import cfg_key
    from agent_tpu.parallel.shardings import (
        bart_param_specs,
        seq2seq_param_specs,
        t5_param_specs,
    )

    specs = (
        bart_param_specs(cfg) if family == "bart"
        else t5_param_specs(cfg) if family == "t5"
        else seq2seq_param_specs(cfg)
    )
    from agent_tpu.ops._model_common import maybe_quantize_specs

    specs = maybe_quantize_specs(specs, family, cfg)
    # tp>1 mesh → weights land sharded, same serving-path TP as classify.
    params = runtime.get_params(
        f"{model_id}#{family}#{hash(cfg_key(cfg)) & 0xFFFFFFFF:08x}",
        lambda: _build_params(model_id, cfg, family),
        specs=specs,
    )
    attn_fn = runtime.attention_fn()  # ring over sp for the encoder pass
    pending = []
    for ids, lengths, n in chunks:
        B, Ls = ids.shape

        # Lengths-on-wire like classify: ship uint16 ids + one length per
        # row, rebuild ids dtype and the [B, L] mask inside the compiled
        # program — ~4× less host→device traffic per chunk.
        def build(Ls=Ls):
            import jax.numpy as jnp

            if family == "bart":
                from agent_tpu.models import bart

                gen = lambda p, i, m: bart.generate(  # noqa: E731
                    p, i, m, cfg, max_new, num_beams=num_beams,
                    length_penalty=length_penalty,
                    early_stopping=early_stopping, min_length=min_length,
                    attn_fn=attn_fn,
                )
            elif family == "t5":
                from agent_tpu.models import t5

                # No generic attn_fn: T5's bias-carrying attention has its
                # own fused path — the runtime's mesh-aware kernel wrapper
                # (make_flash_attention_t5: batch over dp, heads over tp;
                # bias computed per tile in VMEM) goes to t5.encode, which
                # falls back to dense for short/unsupported shapes. Ring-
                # over-sp composition remains a known limitation.
                t5_kernel = runtime.t5_attention_kernel()
                gen = lambda p, i, m: t5.generate(  # noqa: E731
                    p, i, m, cfg, max_new, num_beams=num_beams,
                    length_penalty=length_penalty,
                    early_stopping=early_stopping, min_length=min_length,
                    kernel=t5_kernel,
                )
            else:
                gen = (
                    (lambda p, i, m: seq2seq.greedy_generate(
                        p, i, m, cfg, max_new, min_length=min_length,
                        attn_fn=attn_fn))
                    if num_beams <= 1
                    else (lambda p, i, m: seq2seq.beam_generate(
                        p, i, m, cfg, max_new, num_beams=num_beams,
                        length_penalty=length_penalty,
                        early_stopping=early_stopping,
                        min_length=min_length, attn_fn=attn_fn))
                )

            def run_gen(p, i, n):
                mask = (jnp.arange(Ls)[None, :] < n[:, None]).astype(jnp.int32)
                return gen(p, i.astype(jnp.int32), mask)

            return jax.jit(run_gen)

        fn = runtime.compiled(
            ("map_summarize", model_id, family, B, Ls, max_new, num_beams,
             length_penalty, early_stopping, min_length, cfg_key(cfg)),
            build,
        )
        toks, _ = fn(
            params, runtime.put_batch(ids), runtime.put_batch(lengths)
        )
        pending.append((toks, n))
    # Unfetched: finalize (the pipeline's poster thread) syncs, so the
    # device thread can dispatch the next shard during this one's
    # device→host round trip (reading a jax.Array is thread-safe).
    return pending


def stage(payload: Any, ctx: Optional[object] = None):
    """Host-only phase: validation, shard read, tokenize+pad. Returns
    ``("done", result)`` for soft errors or ``("staged", state)``."""
    t0 = time.perf_counter()
    if not isinstance(payload, dict):
        return "done", bad_input("payload must be a dict")

    texts = payload.get("texts")
    single = texts is None and "source_uri" not in payload
    empty_rows: List[int] = []  # drain-mode blank cells → empty summaries
    if texts is None and "source_uri" in payload:
        # CSV shard addressing — the summarize half of the BASELINE.json
        # classify+summarize drain. Shared contract with classify
        # (``read_shard_texts``): ValueError → soft bad_input; shard
        # integrity / I/O problems raise so the shard FAILS and retries.
        from agent_tpu.data.csv_index import read_shard_texts

        try:
            texts = read_shard_texts(payload)
        except ValueError as exc:
            return "done", bad_input(str(exc))
        # Messy data is normal in drains: blank cells get an empty summary
        # (overwritten after generation) instead of failing the shard or
        # emitting model output for no input — the payload 'texts' path
        # keeps its strict non-empty contract.
        empty_rows = [i for i, t in enumerate(texts) if not t]
        if empty_rows:
            texts = [t or " " for t in texts]
    elif single:
        text = payload.get("text")
        if not isinstance(text, str) or not text:
            return "done", bad_input("payload requires a non-empty 'text' string")
        texts = [text]
    elif not isinstance(texts, list) or not texts or not all(
        isinstance(t, str) and t for t in texts
    ):
        return "done", bad_input("texts must be a non-empty list of non-empty strings")

    max_new = payload.get("max_length", DEFAULT_MAX_LENGTH)
    if isinstance(max_new, bool) or not isinstance(max_new, int) or max_new <= 0:
        return "done", bad_input("max_length must be a positive int")

    # Beam search opt-in (the reference always decoded with num_beams=4,
    # reference ops/map_summarize.py:57; greedy default keeps the fast path).
    num_beams = payload.get("num_beams", 1)
    if isinstance(num_beams, bool) or not isinstance(num_beams, int) or \
            not 1 <= num_beams <= 16:
        return "done", bad_input("num_beams must be an int in [1, 16]")
    # Beam score normalization exponent (HF semantics: selection scores
    # divide by length**length_penalty). bart-large-cnn — the reference's
    # actual model — generates with 2.0; our default stays HF's generic 1.0.
    length_penalty = payload.get("length_penalty", 1.0)
    if isinstance(length_penalty, bool) or \
            not isinstance(length_penalty, (int, float)) or \
            not -4.0 <= float(length_penalty) <= 4.0:
        return "done", bad_input(
            "length_penalty must be a number in [-4, 4]"
        )
    length_penalty = float(length_penalty)
    early_stopping = payload.get("early_stopping", False)
    if not isinstance(early_stopping, bool):
        return "done", bad_input("early_stopping must be a bool")
    # HF counting: min_length bounds the FULL decoder sequence (start +
    # generated); bart-large-cnn generated with 56.
    min_length = payload.get("min_length", 0)
    if isinstance(min_length, bool) or not isinstance(min_length, int) or \
            min_length < 0:
        return "done", bad_input("min_length must be a non-negative int")

    from agent_tpu.ops._model_common import (
        validate_output_uri,
        validate_start_row,
    )

    try:
        output_dir = validate_output_uri(payload)
        start_row = validate_start_row(payload)
    except ValueError as exc:
        return "done", bad_input(str(exc))

    model_id = _resolve_model_id(payload)
    family = _resolve_family(model_id)
    # Checkpoint-integrity problems (unreadable config.json) raise past the
    # soft-error handlers on purpose: retryable shard failure, not bad input.
    cfg = (
        _get_ckpt_cfg(model_id, payload, family)
        if family in ("bart", "t5") else _get_cfg(payload)
    )
    try:
        from agent_tpu.ops._model_common import apply_quant_env

        cfg = apply_quant_env(payload, cfg)
    except ValueError as exc:
        return "done", bad_input(str(exc))
    max_new = min(max_new, cfg.max_tgt_len)

    from agent_tpu.config import OpsConfig

    # The typed config is authoritative (its default is the single source;
    # standalone calls read the env through OpsConfig.from_env).
    ops_cfg = (
        ctx.config.ops
        if ctx is not None and getattr(ctx, "config", None) is not None
        else OpsConfig.from_env()
    )
    global _force_cpu_default_logged
    if not ops_cfg.summarize_force_cpu and not _force_cpu_default_logged \
            and "SUMMARIZE_FORCE_CPU" not in os.environ:
        # Only on the untouched-default path: an operator who set the var
        # (either way) made a choice and needs no notice.
        _force_cpu_default_logged = True
        from agent_tpu.utils.logging import log as _log

        _log(
            "summarize runs on the device backend by default "
            "(the reference defaulted to CPU; SUMMARIZE_FORCE_CPU=1 forces CPU)"
        )

    # Batch buckets must divide the executing mesh. Force-CPU always
    # executes on the 1-device CPU runtime → dp=1.
    from agent_tpu.ops._model_common import resolve_dp

    dp = 1 if ops_cfg.summarize_force_cpu else resolve_dp(ctx)

    state = {
        "t0": t0,
        "chunks": _stage_chunks(
            dp, texts, cfg, num_beams=num_beams, family=family,
            model_id=model_id,
        ),
        "empty_rows": empty_rows,
        "single": single,
        "max_new": max_new,
        "num_beams": num_beams,
        "length_penalty": length_penalty,
        "early_stopping": early_stopping,
        "min_length": min_length,
        "model_id": model_id,
        "family": family,
        "cfg": cfg,
        "force_cpu": ops_cfg.summarize_force_cpu,
        "output_dir": output_dir,
        "start_row": start_row,
        "t_staged": time.perf_counter(),
    }
    return "staged", state


def _stamp_flops(state: Dict[str, Any], ctx: Optional[object]) -> None:
    """Analytic-FLOPs attribution (ISSUE 8): encode + incremental decode
    estimate from the staged chunk shapes, stamped into
    ``ctx.tags["device_attr"]`` for the agent's ``device_mfu{op}`` gauge.
    Configs missing the dimensions (exotic checkpoints) don't stamp."""
    cfg = state.get("cfg")
    d = getattr(cfg, "d_model", None)
    f = getattr(cfg, "d_ff", None)
    n_enc = getattr(cfg, "n_enc_layers", None)
    n_dec = getattr(cfg, "n_dec_layers", None)
    if not (d and f and n_enc and n_dec):
        return
    from agent_tpu.ops._model_common import (
        seq2seq_fwd_flops,
        stamp_device_flops,
    )

    total = 0.0
    biggest = (0, "?")
    for chunk in state.get("chunks") or []:
        try:
            B, L = chunk[0].shape
        except Exception:  # noqa: BLE001 — estimation must never fail a shard
            continue
        total += seq2seq_fwd_flops(
            B, L, state["max_new"], d, f, n_enc, n_dec,
            vocab_size=getattr(cfg, "vocab_size", 0) or 0,
            num_beams=state["num_beams"],
        )
        if B * L > biggest[0]:
            biggest = (B * L, f"B{B}xL{L}xT{state['max_new']}")
    if total > 0:
        stamp_device_flops(ctx, total, biggest[1])


def execute(state: Dict[str, Any], ctx: Optional[object] = None) -> Dict[str, Any]:
    """Device phase (owning thread only): compiled decode of staged chunks."""
    state["t_exec0"] = time.perf_counter()
    _stamp_flops(state, ctx)
    if state["force_cpu"]:
        from agent_tpu.ops.map_classify_tpu import _get_cpu_runtime

        runtime = _get_cpu_runtime()
    elif ctx is not None and getattr(ctx, "require_runtime", None):
        runtime = ctx.require_runtime()
    else:
        from agent_tpu.runtime.runtime import get_runtime

        runtime = get_runtime()

    state["token_chunks"] = _decode_chunks(
        runtime, state["chunks"], state["model_id"], state["cfg"],
        state["max_new"], state["num_beams"],
        length_penalty=state["length_penalty"],
        early_stopping=state["early_stopping"],
        min_length=state["min_length"], family=state["family"],
    )
    state["device"] = runtime.platform
    state["t_device"] = time.perf_counter()
    return state


def finalize(state: Dict[str, Any], ctx: Optional[object] = None) -> Dict[str, Any]:
    """Host phase: detokenize fetched token rows, write the sink, shape the
    result. Safe off the device thread (reads numpy arrays only)."""
    # Deferred fetch: sync the device token arrays here, off the device
    # thread (the pipeline's poster thread pays the round trip).
    t_f = time.perf_counter()
    token_chunks = [
        np.asarray(toks)[:n] for toks, n in state["token_chunks"]
    ]
    fetch_ms = (time.perf_counter() - t_f) * 1000.0
    summaries: List[str] = []
    if state["family"] == "t5":
        from agent_tpu.models import t5

        cfg = state["cfg"]
        sp = t5.hf_spm(state["model_id"])
        n_pieces = sp.GetPieceSize()
        # Same id set transformers' skip_special_tokens drops — incl. unk.
        skip = {cfg.pad_id, cfg.eos_id, sp.unk_id()}
        for toks in token_chunks:
            summaries.extend(
                sp.DecodeIds(
                    [int(t) for t in row
                     if int(t) not in skip and int(t) < n_pieces]
                ).strip()
                for row in toks
            )
    elif state["family"] == "bart":
        from agent_tpu.models import bart

        cfg = state["cfg"]
        tok = bart.hf_bpe(state["model_id"])
        # Same id set transformers' skip_special_tokens drops — including
        # <unk> — so the served text matches the reference decode.
        skip = {cfg.pad_id, cfg.bos_id, cfg.eos_id, cfg.decoder_start_id}
        unk = tok.vocab.get("<unk>")
        if unk is not None:
            skip.add(unk)
        for toks in token_chunks:
            summaries.extend(
                tok.decode([t for t in row if int(t) not in skip]).strip()
                for row in toks
            )
    else:
        from agent_tpu.models.tokenizer import ByteTokenizer

        tok = ByteTokenizer()
        for toks in token_chunks:
            summaries.extend(
                tok.decode([t for t in row if t > 0]) for row in toks
            )
    for i in state["empty_rows"]:
        summaries[i] = ""  # no input → no summary, not model noise

    if ctx is not None and hasattr(ctx, "tags"):
        # Same timings schema as classify: stage = payload → token rows;
        # queue = wait between phases (pipelined mode); device = params +
        # transfer + decode + fetch. Detokenize lands in the result's total.
        ctx.tags.setdefault("timings", {}).update(
            stage_ms=round((state["t_staged"] - state["t0"]) * 1000.0, 3),
            queue_ms=round(
                (state["t_exec0"] - state["t_staged"]) * 1000.0, 3
            ),
            # device_ms is the dispatch span; the decode's device→host sync
            # lands in fetch_ms (deferred to this, the poster thread).
            device_ms=round(
                (state["t_device"] - state["t_exec0"]) * 1000.0, 3
            ),
            fetch_ms=round(fetch_ms, 3),
        )

    from agent_tpu.ops._model_common import stamp_rows

    stamp_rows(ctx, len(summaries))
    out: Dict[str, Any] = {
        "ok": True,
        # Explicit op attribution (ISSUE 2 satellite): the reference shape
        # carried no "op" key, forcing utils/spans.result_op to guess from
        # "summaries" — the heuristic survives only for old bodies.
        "op": "map_summarize",
        "device": state["device"],
        "model": state["model_id"],
        "num_beams": state["num_beams"],
        "elapsed_ms": (time.perf_counter() - state["t0"]) * 1000.0,
    }
    if state["output_dir"] is not None:
        # Result-sink mode (see classify): summaries go to disk, the wire
        # carries a receipt — a 10M-row summarize drain posts ~KBs/shard,
        # not the row payloads.
        from agent_tpu.ops._model_common import write_output_shard

        path, n = write_output_shard(
            state["output_dir"], "map_summarize", state["start_row"],
            ({"summary": s} for s in summaries),
        )
        out["output_path"] = path
        out["rows_written"] = n
        return out
    out["summary"] = summaries[0]
    if not state["single"]:
        if ctx is not None and hasattr(ctx, "tags") \
                and ctx.tags.get("wire") == "b1":
            # Binary shard wire (ISSUE 6): the summaries column is the bulk
            # of a drain result body — ship it length-prefixed + deflated
            # (repetitive summaries compress hard) instead of as escaped
            # JSON strings. The controller decodes back to the identical
            # ``summaries`` list.
            from agent_tpu.data import wire

            return wire.attach_result_columns(out, {"summaries": summaries})
        out["summaries"] = summaries
    return out


@register_op("map_summarize")
def run(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    """Classic monolithic entry: stage → execute → finalize inline."""
    phase, value = stage(payload, ctx)
    if phase == "done":
        return value
    return finalize(execute(value, ctx), ctx)


# Phase hooks for the pipelined drain (agent_tpu.agent.pipeline): the agent
# discovers them via these attributes, so ops without phases run monolithic.
run.stage = stage
run.execute = execute
run.finalize = finalize
