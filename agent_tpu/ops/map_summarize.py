"""Summarization on the mesh — successor of the reference's torch-BART op.

Capability parity with reference ``ops/map_summarize.py:35-68``:

- Payload: required ``text`` (plus the batched upgrade ``texts``), optional
  ``max_length`` (default 130, ref ``:46``), ``model_path``.
- Result: ``{ok, summary, device, model}`` (ref ``:61-67``), plus timing.
- Input truncated at 1024 tokens (ref ``:49``).
- Lazy once-per-process model init (ref ``:17-33``) — via the runtime's HBM
  params store instead of a module-global + lock.

The decode itself is ``models.seq2seq.greedy_generate``: one compiled program,
``lax.scan`` over static steps, KV cache in HBM — replacing the reference's
host-side ``model.generate`` beam loop (ref ``:52-59``). SUMMARIZE_FORCE_CPU is
still honored as a kill-switch (ref ``:10``) but defaults off: BASELINE.json's
north star is zero CPU-side model execution.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input

DEFAULT_MODEL_ID = "summarize-default"
DEFAULT_MAX_LENGTH = 130

# One-shot guard for the default-inversion notice in run(): the framework
# default (device execution) is the INVERSE of the reference's CPU-on default,
# and that must be visible in operational logs of processes that actually run
# summarize (only those — hence here, not in config.py).
_force_cpu_default_logged = False


def _resolve_model_id(payload: Dict[str, Any]) -> str:
    from agent_tpu.ops._model_common import resolve_model_id

    return resolve_model_id(payload, "BART_MODEL", DEFAULT_MODEL_ID)


def _get_cfg(payload: Dict[str, Any]):
    from agent_tpu.models.seq2seq import Seq2SeqConfig
    from agent_tpu.ops._model_common import config_from_payload

    return config_from_payload(payload, Seq2SeqConfig)


def _build_params(model_id: str, cfg):
    from agent_tpu.models import seq2seq

    if model_id.endswith(".npz") and os.path.exists(model_id):
        return seq2seq.load_npz(model_id, cfg)
    return seq2seq.init_params(cfg, model_id=model_id)


MAX_BATCH = 1024


def _generate(runtime, texts: List[str], model_id: str, cfg,
              max_new: int, num_beams: int = 1) -> Tuple[List[str], str]:
    import jax

    from agent_tpu.models import seq2seq
    from agent_tpu.models.tokenizer import (
        DEFAULT_BUCKETS,
        ByteTokenizer,
        byte_encode_pad,
    )
    from agent_tpu.ops._model_common import batch_buckets, cfg_key, iter_chunks

    tok = ByteTokenizer()
    dp = runtime.axis_size("dp")
    # Length buckets must not exceed the position table (max_src_len).
    buckets = [b for b in DEFAULT_BUCKETS if b <= cfg.max_src_len] or [cfg.max_src_len]
    bbuckets = batch_buckets(dp, MAX_BATCH)

    from agent_tpu.parallel.shardings import seq2seq_param_specs

    # tp>1 mesh → weights land sharded, same serving-path TP as classify.
    params = runtime.get_params(
        f"{model_id}#seq2seq#{hash(cfg_key(cfg)) & 0xFFFFFFFF:08x}",
        lambda: _build_params(model_id, cfg),
        specs=seq2seq_param_specs(cfg),
    )
    summaries: List[str] = []
    attn_fn = runtime.attention_fn()  # ring over sp for the encoder pass
    for chunk in iter_chunks(texts, bbuckets[-1]):
        # Fused tokenize+pad (one numpy pass per row, classify's hot path).
        ids, lengths = byte_encode_pad(
            chunk, buckets=buckets, batch_buckets=bbuckets,
            max_len_cap=cfg.max_src_len, add_bos=True, add_eos=True,
        )
        B, Ls = ids.shape

        # Lengths-on-wire like classify: ship uint16 ids + one length per
        # row, rebuild ids dtype and the [B, L] mask inside the compiled
        # program — ~4× less host→device traffic per chunk.
        def build(Ls=Ls):
            import jax.numpy as jnp

            gen = (
                (lambda p, i, m: seq2seq.greedy_generate(
                    p, i, m, cfg, max_new, attn_fn=attn_fn))
                if num_beams <= 1
                else (lambda p, i, m: seq2seq.beam_generate(
                    p, i, m, cfg, max_new, num_beams=num_beams,
                    attn_fn=attn_fn))
            )

            def run_gen(p, i, n):
                mask = (jnp.arange(Ls)[None, :] < n[:, None]).astype(jnp.int32)
                return gen(p, i.astype(jnp.int32), mask)

            return jax.jit(run_gen)

        fn = runtime.compiled(
            ("map_summarize", model_id, B, Ls, max_new, num_beams, cfg_key(cfg)),
            build,
        )
        wire_dtype = np.uint16 if cfg.vocab_size <= (1 << 16) else np.int32
        toks, _ = fn(
            params,
            runtime.put_batch(ids.astype(wire_dtype)),
            runtime.put_batch(lengths),
        )
        toks = np.asarray(toks)[: len(chunk)]
        summaries.extend(tok.decode([t for t in row if t > 0]) for row in toks)
    return summaries, runtime.platform


@register_op("map_summarize")
def run(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    t0 = time.perf_counter()
    if not isinstance(payload, dict):
        return bad_input("payload must be a dict")

    texts = payload.get("texts")
    single = texts is None and "source_uri" not in payload
    empty_rows: List[int] = []  # drain-mode blank cells → empty summaries
    if texts is None and "source_uri" in payload:
        # CSV shard addressing — the summarize half of the BASELINE.json
        # classify+summarize drain. Shared contract with classify
        # (``read_shard_texts``): ValueError → soft bad_input; shard
        # integrity / I/O problems raise so the shard FAILS and retries.
        from agent_tpu.data.csv_index import read_shard_texts

        try:
            texts = read_shard_texts(payload)
        except ValueError as exc:
            return bad_input(str(exc))
        # Messy data is normal in drains: blank cells get an empty summary
        # (overwritten after generation) instead of failing the shard or
        # emitting model output for no input — the payload 'texts' path
        # keeps its strict non-empty contract.
        empty_rows = [i for i, t in enumerate(texts) if not t]
        if empty_rows:
            texts = [t or " " for t in texts]
    elif single:
        text = payload.get("text")
        if not isinstance(text, str) or not text:
            return bad_input("payload requires a non-empty 'text' string")
        texts = [text]
    elif not isinstance(texts, list) or not texts or not all(
        isinstance(t, str) and t for t in texts
    ):
        return bad_input("texts must be a non-empty list of non-empty strings")

    max_new = payload.get("max_length", DEFAULT_MAX_LENGTH)
    if isinstance(max_new, bool) or not isinstance(max_new, int) or max_new <= 0:
        return bad_input("max_length must be a positive int")

    # Beam search opt-in (the reference always decoded with num_beams=4,
    # reference ops/map_summarize.py:57; greedy default keeps the fast path).
    num_beams = payload.get("num_beams", 1)
    if isinstance(num_beams, bool) or not isinstance(num_beams, int) or \
            not 1 <= num_beams <= 16:
        return bad_input("num_beams must be an int in [1, 16]")

    model_id = _resolve_model_id(payload)
    cfg = _get_cfg(payload)
    max_new = min(max_new, cfg.max_tgt_len)

    from agent_tpu.ops._model_common import (
        validate_output_uri,
        validate_start_row,
    )

    try:
        output_dir = validate_output_uri(payload)
        start_row = validate_start_row(payload)
    except ValueError as exc:
        return bad_input(str(exc))

    from agent_tpu.config import OpsConfig

    # stage = payload → texts (incl. shard read); runtime acquisition and
    # beyond is device time — same attribution as map_classify_tpu so the
    # shared timings schema means one thing across ops.
    t_staged = time.perf_counter()

    # The typed config is authoritative (its default is the single source;
    # standalone calls read the env through OpsConfig.from_env).
    ops_cfg = (
        ctx.config.ops
        if ctx is not None and getattr(ctx, "config", None) is not None
        else OpsConfig.from_env()
    )
    global _force_cpu_default_logged
    if not ops_cfg.summarize_force_cpu and not _force_cpu_default_logged \
            and "SUMMARIZE_FORCE_CPU" not in os.environ:
        # Only on the untouched-default path: an operator who set the var
        # (either way) made a choice and needs no notice.
        _force_cpu_default_logged = True
        from agent_tpu.utils.logging import log as _log

        _log(
            "summarize runs on the device backend by default "
            "(the reference defaulted to CPU; SUMMARIZE_FORCE_CPU=1 forces CPU)"
        )
    if ops_cfg.summarize_force_cpu:
        from agent_tpu.ops.map_classify_tpu import _get_cpu_runtime

        runtime = _get_cpu_runtime()
    elif ctx is not None and getattr(ctx, "require_runtime", None):
        runtime = ctx.require_runtime()
    else:
        from agent_tpu.runtime.runtime import get_runtime

        runtime = get_runtime()

    summaries, device = _generate(
        runtime, texts, model_id, cfg, max_new, num_beams=num_beams
    )
    for i in empty_rows:
        summaries[i] = ""  # no input → no summary, not model noise
    if ctx is not None and hasattr(ctx, "tags"):
        ctx.tags.setdefault("timings", {}).update(
            stage_ms=round((t_staged - t0) * 1000.0, 3),
            device_ms=round((time.perf_counter() - t_staged) * 1000.0, 3),
        )

    out: Dict[str, Any] = {
        "ok": True,
        "device": device,
        "model": model_id,
        "num_beams": num_beams,
        "elapsed_ms": (time.perf_counter() - t0) * 1000.0,
    }
    if output_dir is not None:
        # Result-sink mode (see classify): summaries go to disk, the wire
        # carries a receipt — a 10M-row summarize drain posts ~KBs/shard,
        # not the row payloads.
        from agent_tpu.ops._model_common import write_output_shard

        path, n = write_output_shard(
            output_dir, "map_summarize", start_row,
            ({"summary": s} for s in summaries),
        )
        out["output_path"] = path
        out["rows_written"] = n
        return out
    out["summary"] = summaries[0]
    if not single:
        out["summaries"] = summaries
    return out
