"""CSV shard op — the swarm's data-distribution primitive.

Capability parity with reference ``ops/csv_shard.py:29-103``:

- Registered as ``read_csv_shard`` (and now reachable — the reference's map key
  / registered-name mismatch is fixed, SURVEY.md §1 gap 3).
- Accepts the payload directly **or** wrapped in a task dict under ``payload``
  (ref ``:51``).
- Payload: ``source_uri`` (required), ``start_row`` (default 0), ``shard_size``
  (default 100, ref ``:62``), ``mode`` in ``rows`` | ``count`` (ref ``:71-73``).
- Extensive validation with soft ``{"ok": False, "error"}`` failures
  (ref ``:55-76``).

The execution engine is new: byte-range reads over a cached quote-aware row
index (``agent_tpu.data.csv_index``) instead of the reference's per-shard
DictReader skip-scan — O(shard bytes) per shard instead of O(start_row) rows.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from agent_tpu.data.csv_index import (
    DEFAULT_SHARD_SIZE,  # noqa: F401 — re-export; the wire default lives once
    CsvIndex,
    resolve_shard_payload,
)
from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input


@register_op("read_csv_shard")
def run(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    if isinstance(payload, dict) and isinstance(payload.get("payload"), dict):
        payload = payload["payload"]  # task-wrapped form (ref :51)
    if not isinstance(payload, dict):
        return bad_input("payload must be a dict")

    try:
        # Shared shard-addressing contract (also used by map_classify_tpu's
        # drain mode) — one place defines URI/validation semantics.
        path, start_row, shard_size = resolve_shard_payload(payload)
    except ValueError as exc:
        return bad_input(str(exc))
    source_uri = payload["source_uri"]

    mode = payload.get("mode", "rows")
    if mode not in ("rows", "count"):
        return bad_input(f"mode must be 'rows' or 'count', got {mode!r}")

    try:
        index = CsvIndex.for_file(path)
    except OSError as exc:
        return bad_input(f"cannot open {source_uri!r}: {exc}")

    # Reference wire-contract fields (reference ``ops/csv_shard.py:55,86-103``)
    # ride alongside ours: dataset_id echo, end_row, row_count.
    from agent_tpu.ops._model_common import stamp_rows

    dataset_id = payload.get("dataset_id", "unknown_dataset")
    total = index.n_data_rows
    if mode == "count":
        in_range = max(0, min(shard_size, total - start_row))
        stamp_rows(ctx, in_range)
        return {
            "ok": True,
            "mode": "count",
            "dataset_id": dataset_id,
            "source_uri": source_uri,
            "start_row": start_row,
            "end_row": start_row + in_range,
            "shard_size": shard_size,
            "count": in_range,
            "row_count": in_range,
            "total_rows": total,
        }

    rows = index.read_dict_rows(start_row, shard_size)
    stamp_rows(ctx, len(rows))
    return {
        "ok": True,
        "mode": "rows",
        "dataset_id": dataset_id,
        "source_uri": source_uri,
        "start_row": start_row,
        "end_row": start_row + len(rows),
        "shard_size": shard_size,
        "rows": rows,
        "count": len(rows),
        "row_count": len(rows),
        "total_rows": total,
    }
