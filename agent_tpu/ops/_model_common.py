"""Shared plumbing for the model-backed ops (classify, summarize).

Factored out so the two ops cannot drift: model-id resolution (payload →
env → default, the precedence of reference ``ops/_tpu_runtime.py:23-31``),
config-from-payload parsing, **config-aware cache keys** (a payload that
overrides ``model_config`` must never reuse weights or executables built for
a different config), batch-size buckets, and chunking for batches that exceed
the top bucket.
"""

from __future__ import annotations

import os
from dataclasses import fields
from typing import Any, Dict, Iterator, List, Sequence, Tuple


# ---- analytic FLOPs (ISSUE 8: the MFU numerator) ----
#
# Matmul terms only (2·M·N·K per matmul; elementwise/softmax are noise at
# model scale) — the same accounting bench.py's encoder_flops_per_row has
# always used, now stamped per executed shard so the agent can export a
# live device_mfu{op} gauge. These are ESTIMATES by design: the point is a
# stable utilization trend per shape bucket, not a profiler.

def encoder_fwd_flops(
    batch: int, seq_len: int, d_model: int, d_ff: int, n_layers: int,
    n_classes: int = 0,
) -> float:
    """Forward FLOPs of ``batch`` rows through an encoder stack at padded
    length ``seq_len``: QKVO projections + score/value matmuls + FFN per
    layer, plus the classifier head."""
    d, f, L = float(d_model), float(d_ff), float(seq_len)
    attn_proj = 8.0 * L * d * d          # 4 projections × 2·L·d·d
    attn_sdpa = 4.0 * L * L * d          # QKᵀ and P·V × 2·L²·d
    ffn = 4.0 * L * d * f                # 2 matmuls × 2·L·d·f
    per_row = n_layers * (attn_proj + attn_sdpa + ffn) + 2.0 * d * n_classes
    return batch * per_row


def seq2seq_fwd_flops(
    batch: int, src_len: int, new_tokens: int, d_model: int, d_ff: int,
    n_enc_layers: int, n_dec_layers: int, vocab_size: int = 0,
    num_beams: int = 1,
) -> float:
    """Forward FLOPs of an encode + incremental greedy/beam decode:
    the encoder stack over ``src_len``, then per generated token a
    single-position decoder step (self-attn + cross-attn projections, FFN,
    cross-attention reads over the cached ``src_len`` keys, vocab
    projection). Beams multiply the decode rows in flight."""
    d, f = float(d_model), float(d_ff)
    enc = encoder_fwd_flops(batch, src_len, d_model, d_ff, n_enc_layers)
    rows = float(batch * max(1, num_beams))
    per_tok_layer = (
        8.0 * d * d          # self-attn QKVO projections (one position)
        + 8.0 * d * d        # cross-attn QKVO projections
        + 4.0 * src_len * d  # cross-attn scores + values over the cache
        + 4.0 * d * f        # FFN
    )
    dec = rows * new_tokens * (
        n_dec_layers * per_tok_layer + 2.0 * d * vocab_size
    )
    return enc + dec


def stamp_device_flops(ctx, flops: float, shape: str) -> None:
    """Accumulate an op's analytic-FLOPs estimate (and its dominant shape
    bucket) into ``ctx.tags["device_attr"]`` — the channel the agent's
    dispatch loop reads to feed ``device_flops_total{op,shape}`` and the
    ``device_mfu{op}`` gauge. No-op without a ctx (pure-op callers)."""
    if ctx is None or not hasattr(ctx, "tags") or flops <= 0:
        return
    attr = ctx.tags.setdefault("device_attr", {})
    attr["flops"] = attr.get("flops", 0.0) + float(flops)
    attr["shape"] = str(shape)


def stamp_rows(ctx, rows: Any) -> None:
    """Accumulate the rows this task processed into the result's usage
    block (ISSUE 9) — the numerator of the showback report's rows column
    and swarmtop's rows/s sparkline. No-op without a ctx or a positive
    count (pure-op callers, empty shards)."""
    if ctx is None or not hasattr(ctx, "tags"):
        return
    if isinstance(rows, bool) or not isinstance(rows, int) or rows <= 0:
        return
    from agent_tpu.obs.usage import stamp_usage

    stamp_usage(ctx.tags, rows=rows)


def resolve_model_id(payload: Dict[str, Any], env_var: str, default: str) -> str:
    """payload ``model_path`` → env var → default (ref ``_tpu_runtime.py:23-31``)."""
    mp = payload.get("model_path")
    if isinstance(mp, str) and mp:
        return mp
    return os.environ.get(env_var) or default


def config_from_payload(payload: Dict[str, Any], config_cls):
    """Build ``config_cls`` applying any recognized ``model_config`` overrides."""
    overrides = payload.get("model_config")
    if isinstance(overrides, dict):
        allowed = {
            k: v for k, v in overrides.items()
            if k in config_cls.__dataclass_fields__
        }
        return config_cls(**allowed)
    return config_cls()


def apply_quant_env(payload: Dict[str, Any], cfg):
    """Quant-mode resolution shared by the model ops: payload
    ``model_config.quant`` wins; else ``TPU_QUANT`` env; else the config
    default.

    Error contract: a bad *payload* value raises ValueError (→ soft
    bad_input, caller error); a bad *env* value raises RuntimeError — a
    worker deployment misconfig must fail the shard for retry/visibility,
    not soft-drop every task as caller error (same rule as the checkpoint
    integrity errors, ``models/bert.py`` from_hf_json).
    """
    from dataclasses import replace

    from agent_tpu.models.quant import validate_quant

    overrides = payload.get("model_config")
    if isinstance(overrides, dict) and "quant" in overrides:
        # Apply the payload value here, self-contained — not via the family
        # override whitelists (a whitelist that forgot "quant" would
        # otherwise silently serve unquantized while this "validated" the
        # default).
        return replace(cfg, quant=validate_quant(overrides["quant"]))
    env = os.environ.get("TPU_QUANT", "").strip().lower()
    if env:
        try:
            return replace(cfg, quant=validate_quant(env))
        except ValueError as exc:
            raise RuntimeError(f"bad TPU_QUANT env: {exc}") from exc
    return cfg


def maybe_quantize_params(params, family: str, cfg):
    """The shared quantized-mode build-time transform gate (guard +
    dispatch), so the two model ops cannot drift. Covers both execution
    modes — ``int8`` (W8A8, the encoder mode) and ``w8a16`` (weight-only,
    the decode mode). Host-side quantization BEFORE HBM placement: the int8
    tables — 4× smaller than f32 — are what transfer and stay resident
    (``models.quant``)."""
    mode = getattr(cfg, "quant", "none")
    from agent_tpu.models.quant import QUANTIZED_MODES

    if mode in QUANTIZED_MODES:
        from agent_tpu.models.quant import quantize_for_family

        return quantize_for_family(family, params, mode)
    return params


def maybe_quantize_specs(specs, family: str, cfg):
    """Spec-tree twin of :func:`maybe_quantize_params`: the quantized tree
    has ``{"w_q", "w_scale"}`` (int8) or ``{"w8", "w_scale"}`` (w8a16)
    leaves, so tp placement specs transform the same paths."""
    mode = getattr(cfg, "quant", "none")
    from agent_tpu.models.quant import QUANTIZED_MODES

    if mode in QUANTIZED_MODES:
        from agent_tpu.models.quant import quantize_specs_for_family

        return quantize_specs_for_family(family, specs, mode)
    return specs


def cfg_key(cfg) -> Tuple:
    """Hashable fingerprint of a frozen config dataclass — goes into both the
    params-store key and the executable-cache key so distinct configs never
    alias (two payloads with different ``model_config`` must get distinct
    weights and distinct compiled programs)."""
    return tuple((f.name, getattr(cfg, f.name)) for f in fields(cfg))


def batch_buckets(dp: int, cap: int) -> List[int]:
    """Batch-size buckets dp, 2·dp, … ≤ cap, so the batch dim always divides
    the mesh ``dp`` axis and the executable cache stays small."""
    out, b = [], max(1, dp)
    while b <= cap:
        out.append(b)
        b *= 2
    return out or [max(1, dp)]


# Device-dispatch chunk budget (rows × padded length) for DENSE-attention
# shapes. The dense path materializes [B, H, L, L] score temps; past ~131k
# tokens per program the score traffic degrades the matmul schedule —
# measured on v5e at BERT-base/seq 512: 256-row chunks run the same 1,024
# rows 11% faster in bf16 and 40% faster in int8 than one 1,024-row program
# (chunks dispatch back-to-back, so the split costs no extra host↔device
# round trips). Flash-path lengths (``kernels.flash_attention.selects_flash``)
# stream their scores through VMEM and keep the large-batch grid.
DENSE_CHUNK_TOKENS = 131_072


def chunk_token_budget() -> int:
    env = os.environ.get("TPU_CHUNK_TOKENS", "").strip()
    return int(env) if env else DENSE_CHUNK_TOKENS


def split_padded_chunk(ids, lengths, n: int, dp: int) -> List[Tuple]:
    """Split one padded ``(ids [B, L], lengths [B], n_real)`` staging chunk
    into device-dispatch slices of at most :func:`chunk_token_budget` tokens.

    The slice size is the largest batch bucket (power-of-two multiple of
    ``dp``) within budget, so every slice's batch dim still divides the mesh
    and the executable cache sees ONE shape for all full slices. ``B`` is
    itself a bucket, so the slice size always divides it exactly. Slices
    holding only padding rows are dropped.
    """
    from agent_tpu.kernels.flash_attention import selects_flash

    B, L = ids.shape
    budget = chunk_token_budget()
    if selects_flash(L) or B * L <= budget:
        return [(ids, lengths, n)]
    rows = max(1, budget // L)
    cap = max(1, dp)
    while cap * 2 <= rows:
        cap *= 2
    if cap >= B:
        return [(ids, lengths, n)]
    out: List[Tuple] = []
    for s in range(0, B, cap):
        n_i = min(n - s, cap)
        if n_i <= 0:
            break
        out.append((ids[s:s + cap], lengths[s:s + cap], n_i))
    return out


def iter_chunks(seqs: Sequence, max_chunk: int) -> Iterator[Sequence]:
    """Slice an oversize batch into ≤ max_chunk pieces — rows beyond the top
    batch bucket run as extra device calls instead of overflowing ``pad_batch``
    (which would allocate fewer rows than sequences and crash)."""
    for i in range(0, len(seqs), max_chunk):
        yield seqs[i : i + max_chunk]


def resolve_runtime(ctx):
    """The runtime this op will execute on, or ``None`` when no backend is
    available. A host-side metadata read — never initializes device state
    beyond what the runtime singleton already did."""
    try:
        if ctx is not None and getattr(ctx, "require_runtime", None):
            return ctx.require_runtime()
        from agent_tpu.runtime.runtime import get_runtime

        return get_runtime()
    except Exception:  # noqa: BLE001 — no backend
        return None


def resolve_dp(ctx) -> int:
    """The mesh ``dp`` extent the op's batches must divide — a host-side
    metadata read. The pipeline always injects a built runtime; standalone
    calls resolve the singleton here, on the owning thread. No backend at
    all ⇒ 1, matching the degraded CPU path's shapes."""
    rt = resolve_runtime(ctx)
    return rt.axis_size("dp") if rt is not None else 1


def length_buckets_for(max_len: int) -> List[int]:
    """Length buckets capped at ``max_len`` (never exceeding the model's
    position table), with ``max_len`` itself as the top bucket when the
    standard powers of two don't reach it — so a full-length row is always
    representable instead of silently truncating to the largest power."""
    from agent_tpu.models.tokenizer import DEFAULT_BUCKETS

    buckets = [b for b in DEFAULT_BUCKETS if b < max_len]
    buckets.append(max_len)
    return buckets


def stage_text_chunks(
    dp: int,
    texts: Sequence[str],
    *,
    max_len: int,
    vocab_size: int,
    max_batch: int,
    add_bos: bool = False,
    add_eos: bool = False,
    encode_pad=None,
    split_for_dispatch: bool = False,
) -> List[Tuple]:
    """Pure host: tokenize+pad ``texts`` into device-ready
    ``[(ids[B, L] wire-dtype, lengths[B] int32, n_real_rows), ...]`` chunks —
    the shared staging scaffolding of both model ops and both vocab families.

    ``encode_pad(chunk, length_buckets, batch_buckets) -> (ids, lengths)``
    supplies the tokenizer (e.g. a checkpoint's wordpiece vocab); the default
    is the fused byte path (``byte_encode_pad``).

    Host→device traffic is the per-task tax (a tunneled chip moves ~10 MB/s,
    so wire bytes ARE serving latency): ship the narrowest exact encoding +
    one length per row and let the compiled program rebuild int32 ids and the
    [B, L] mask on device. Wire dtypes, narrowest first:

    - uint8 **unshifted bytes** — byte-vocab path with no BOS/EOS: exact
      reconstruction is ``(raw + N_SPECIAL) * mask`` (see
      ``tokenizer.byte_encode_pad(raw_uint8=True)``); uint8 on this wire
      ALWAYS means shifted-raw — real id arrays never stage as uint8.
    - uint16 ids — any vocab < 2^16 (wordpiece/BPE/byte-with-specials).
    - int32 ids — vocabs past 2^16 (none in-repo today).

    Length buckets come from :func:`length_buckets_for`; batch buckets are
    multiples of ``dp`` so the batch dim always divides the mesh.
    """
    import numpy as np

    from agent_tpu.models.tokenizer import N_SPECIAL, byte_encode_pad

    buckets = length_buckets_for(max_len)
    bbuckets = batch_buckets(dp, max_batch)
    wire_dtype = np.uint16 if vocab_size <= (1 << 16) else np.int32
    custom_encode = encode_pad is not None
    if encode_pad is None:
        # Raw-byte wire needs the byte ids 4..259 resident in the embedding
        # table; the byte tokenizer requires that of its models anyway.
        raw_u8 = (not add_bos and not add_eos
                  and vocab_size >= N_SPECIAL + 256)

        def encode_pad(chunk, lb, bb):
            return byte_encode_pad(
                chunk, buckets=lb, batch_buckets=bb,
                max_len_cap=max_len, add_bos=add_bos, add_eos=add_eos,
                raw_uint8=raw_u8,
            )
    chunks: List[Tuple] = []
    # Oversize batches run as extra device calls on the top bucket shape.
    for chunk in iter_chunks(texts, bbuckets[-1]):
        ids, lengths = encode_pad(chunk, buckets, bbuckets)
        if ids.dtype == np.uint8:
            # uint8 on this wire is an in-band sentinel meaning shifted-raw
            # bytes; only the internal byte path above may emit it. A custom
            # tokenizer returning uint8 real ids would be silently corrupted
            # by the device-side (+N_SPECIAL)*mask rebuild — reject it here.
            if custom_encode:
                raise TypeError(
                    "encode_pad returned uint8 ids: the uint8 wire is "
                    "reserved for the internal raw-byte path; return "
                    "int32/uint16 ids from custom tokenizers"
                )
        else:
            ids = ids.astype(wire_dtype)
        staged = (ids, lengths, len(chunk))
        if split_for_dispatch:
            # Dense-path dispatch budget (split_padded_chunk docstring):
            # slices dispatch back-to-back, fetched once, so the split is
            # free on the wire but keeps score temps at the measured
            # per-program sweet spot.
            chunks.extend(split_padded_chunk(*staged, dp))
        else:
            chunks.append(staged)
    return chunks


def validate_start_row(payload: Dict[str, Any]) -> int:
    """``start_row`` as a non-negative int (0 when absent); ValueError — the
    soft-error path — on anything else. Sink-mode shard files are named by
    it, so a bad value must fail validation, not generate garbage names."""
    raw = payload.get("start_row", 0)
    if raw is None:
        return 0
    if isinstance(raw, bool) or not isinstance(raw, int) or raw < 0:
        raise ValueError("start_row must be a non-negative int")
    return raw


def validate_output_uri(payload: Dict[str, Any]):
    """Optional result sink: ``output_uri`` names a local directory the op
    writes full per-row results to, posting only a small receipt back to the
    controller. The at-scale drain pattern (BASELINE.json 10M-row job): row
    payloads (10M summaries ≈ GBs) stream to disk next to the data instead of
    accumulating in controller memory and the result journal.

    Returns the validated directory (created if missing) or None; raises
    ValueError (→ soft bad_input) when unusable.
    """
    uri = payload.get("output_uri")
    if uri is None:
        return None
    if not isinstance(uri, str) or not uri:
        raise ValueError("output_uri must be a non-empty directory path")
    try:
        os.makedirs(uri, exist_ok=True)
    except OSError as exc:
        raise ValueError(f"output_uri not creatable: {exc}") from exc
    if not os.path.isdir(uri) or not os.access(uri, os.W_OK):
        raise ValueError(f"output_uri not a writable directory: {uri}")
    return uri


def write_output_shard(
    output_dir: str, op: str, start_row: int, rows: Iterator[Dict[str, Any]]
) -> Tuple[str, int]:
    """Write one shard's rows as JSONL → (path, n_rows). Line ``k`` holds
    absolute dataset row ``start_row + k``.

    Atomic (tmp + ``os.replace``) so a controller retry of the same shard
    (idempotent shard addressing, SURVEY.md §5.4) can never leave a torn
    file — the retry simply rewrites the identical content.
    """
    import json

    path = os.path.join(output_dir, f"{op}_rows_{start_row:012d}.jsonl")
    tmp = f"{path}.tmp.{os.getpid()}"
    n = 0
    with open(tmp, "w") as f:
        for row in rows:
            f.write(json.dumps(row, separators=(",", ":")))
            f.write("\n")
            n += 1
    os.replace(tmp, path)
    return path, n
