"""Shared plumbing for the model-backed ops (classify, summarize).

Factored out so the two ops cannot drift: model-id resolution (payload →
env → default, the precedence of reference ``ops/_tpu_runtime.py:23-31``),
config-from-payload parsing, **config-aware cache keys** (a payload that
overrides ``model_config`` must never reuse weights or executables built for
a different config), batch-size buckets, and chunking for batches that exceed
the top bucket.
"""

from __future__ import annotations

import os
from dataclasses import fields
from typing import Any, Dict, Iterator, List, Sequence, Tuple


def resolve_model_id(payload: Dict[str, Any], env_var: str, default: str) -> str:
    """payload ``model_path`` → env var → default (ref ``_tpu_runtime.py:23-31``)."""
    mp = payload.get("model_path")
    if isinstance(mp, str) and mp:
        return mp
    return os.environ.get(env_var) or default


def config_from_payload(payload: Dict[str, Any], config_cls):
    """Build ``config_cls`` applying any recognized ``model_config`` overrides."""
    overrides = payload.get("model_config")
    if isinstance(overrides, dict):
        allowed = {
            k: v for k, v in overrides.items()
            if k in config_cls.__dataclass_fields__
        }
        return config_cls(**allowed)
    return config_cls()


def cfg_key(cfg) -> Tuple:
    """Hashable fingerprint of a frozen config dataclass — goes into both the
    params-store key and the executable-cache key so distinct configs never
    alias (two payloads with different ``model_config`` must get distinct
    weights and distinct compiled programs)."""
    return tuple((f.name, getattr(cfg, f.name)) for f in fields(cfg))


def batch_buckets(dp: int, cap: int) -> List[int]:
    """Batch-size buckets dp, 2·dp, … ≤ cap, so the batch dim always divides
    the mesh ``dp`` axis and the executable cache stays small."""
    out, b = [], max(1, dp)
    while b <= cap:
        out.append(b)
        b *= 2
    return out or [max(1, dp)]


def iter_chunks(seqs: Sequence, max_chunk: int) -> Iterator[Sequence]:
    """Slice an oversize batch into ≤ max_chunk pieces — rows beyond the top
    batch bucket run as extra device calls instead of overflowing ``pad_batch``
    (which would allocate fewer rows than sequences and crash)."""
    for i in range(0, len(seqs), max_chunk):
        yield seqs[i : i + max_chunk]
