"""Reduction op: count/sum/mean/min/max over numeric values.

Capability parity with reference ``ops/risk_accumulate.py:18-77``: payload is a
numeric ``values`` list or an ``items`` list-of-dicts with a ``field`` selector
(default ``"risk"``, ref ``:44``); result carries ``{count, sum, mean, min, max,
compute_time_ms}`` with the zero-input shape of ref ``:56-63``. This op is the
swarm's reduce stage: the controller combines per-shard partials.

The TPU-native upgrade (BASELINE.json north star: "risk_accumulate runs as an
on-device lax.psum reduction"): when a device runtime ``ctx`` is present and the
payload is large enough to be worth shipping to HBM, the reduction runs as a
single jitted ``shard_map`` program whose partials combine with ``lax.psum``
over the mesh's data axis — see ``agent_tpu.parallel.collectives.mesh_reduce``.
Small payloads keep the host path (device dispatch would dominate).
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Optional

from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input

# Below this many values the host reduce wins; above it the mesh psum path is
# worth the transfer. Chosen conservatively; bench.py can sweep it.
DEVICE_THRESHOLD = 4096


def _merge_partials(payload: Dict[str, Any], t0: float) -> Dict[str, Any]:
    """Merge per-shard stat partials — the reduce stage of a map-reduce drain.

    ``partials`` is a list of prior risk_accumulate results (count/sum/min/
    max); the controller materializes them from the shard jobs' results when
    a reduce job submitted with ``collect_partials`` leases.
    """
    partials = payload["partials"]
    if not isinstance(partials, list):
        raise ValueError("partials must be a list of stat dicts")
    count = 0
    total = 0.0
    mn: Optional[float] = None
    mx: Optional[float] = None
    nan_in = False
    for i, p in enumerate(partials):
        if isinstance(p, dict) and p.get("ok") is False:
            # A soft-failed shard slipped through as a SUCCEEDED dep — its
            # rows are missing, so the reduce must FAIL visibly (RuntimeError
            # → failed result) and surface the shard's own error, not a
            # schema complaint about the error dict.
            raise RuntimeError(
                f"partial #{i} is a failed shard result: {p.get('error')!r}"
            )
        c = p.get("count") if isinstance(p, dict) else None
        if isinstance(c, bool) or not isinstance(c, int) or c < 0:
            raise ValueError(
                "each partial needs a non-negative integer 'count' (+sum/min/max)"
            )
        if c == 0:
            continue
        for key in ("sum", "min", "max"):
            v = p.get(key)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"each non-empty partial needs numeric {key!r}")
        count += c
        s, lo, hi = float(p["sum"]), float(p["min"]), float(p["max"])
        # A NaN-poisoned shard partial (the map stage emits min=max=sum=NaN
        # for NaN-carrying shards) must poison the MERGE order-independently
        # too: Python min/max keep or drop NaN depending on argument order
        # (min(nan, x) = nan, min(x, nan) = x), so a flag — not the bare
        # min/max chain — carries the poison.
        nan_in = nan_in or math.isnan(s) or math.isnan(lo) or math.isnan(hi)
        total += s
        mn = lo if mn is None else min(mn, lo)
        mx = hi if mx is None else max(mx, hi)
    if nan_in:
        total = mn = mx = float("nan")
    if count == 0:
        out = _zero_result(t0)
        out["n_partials"] = len(partials)  # same schema as non-empty merges
        return out
    return {
        "ok": True,
        "count": count,
        "sum": total,
        "mean": total / count,
        "min": mn,
        "max": mx,
        "n_partials": len(partials),
        "compute_time_ms": (time.perf_counter() - t0) * 1000.0,
    }


def _extract_values(payload: Dict[str, Any]) -> List[float]:
    if "source_uri" in payload:
        # CSV shard addressing: stats over a numeric column of the shard —
        # risk_accumulate as the *map* stage of a map-reduce drain. Shared
        # shard-reading contract with the text ops (read_shard_column):
        # RuntimeError/OSError propagate → the shard FAILS and retries.
        from agent_tpu.data.csv_index import read_shard_column

        raw_values = read_shard_column(payload, "field", "risk")
        out = []
        for raw in raw_values:
            try:
                out.append(float(raw))
            except ValueError as exc:
                raise RuntimeError(
                    f"non-numeric value {raw!r} in shard column "
                    f"{payload.get('field', 'risk')!r}"
                ) from exc
        return out
    if "values" in payload:
        values = payload["values"]
        if not isinstance(values, list):
            raise ValueError("values must be a list of numbers")
        out = []
        for v in values:
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError("values must be numeric")
            out.append(float(v))
        return out
    if "items" in payload:
        items = payload["items"]
        if not isinstance(items, list):
            raise ValueError("items must be a list of dicts")
        fieldname = payload.get("field", "risk")
        out = []
        for it in items:
            if not isinstance(it, dict):
                raise ValueError("items must be dicts")
            v = it.get(fieldname)
            if v is None:
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"field {fieldname!r} must be numeric")
            out.append(float(v))
        return out
    raise ValueError("payload requires 'values' or 'items'")


def _zero_result(t0: float) -> Dict[str, Any]:
    return {
        "ok": True,
        "count": 0,
        "sum": 0.0,
        "mean": 0.0,
        "min": None,
        "max": None,
        "compute_time_ms": (time.perf_counter() - t0) * 1000.0,
    }


@register_op("risk_accumulate")
def run(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    t0 = time.perf_counter()
    if not isinstance(payload, dict):
        return bad_input("payload must be a dict")
    # Validate the threshold before any early return so a malformed payload is
    # rejected consistently, not only when the device path would consult it.
    threshold = payload.get("device_threshold", DEVICE_THRESHOLD)
    if isinstance(threshold, bool) or not isinstance(threshold, (int, float)) or threshold <= 0:
        return bad_input("device_threshold must be a positive number")

    if "partials" in payload:
        try:
            return _merge_partials(payload, t0)
        except ValueError as exc:
            return bad_input(str(exc))

    try:
        values = _extract_values(payload)
    except ValueError as exc:
        return bad_input(str(exc))
    # Usage rows (ISSUE 9): the MAP path counts its shard's values; the
    # partials merge above deliberately does not — those rows were already
    # counted by the shard tasks that produced the partials.
    from agent_tpu.ops._model_common import stamp_rows

    stamp_rows(ctx, len(values))
    if not values:
        return _zero_result(t0)

    use_device = (
        ctx is not None
        and getattr(ctx, "runtime", None) is not None
        and len(values) >= threshold
    )
    if use_device:
        from agent_tpu.parallel.collectives import mesh_reduce_stats

        stats = mesh_reduce_stats(ctx.runtime, values)
        stats.update(
            ok=True,
            device="mesh",
            compute_time_ms=(time.perf_counter() - t0) * 1000.0,
        )
        return stats

    try:
        total = math.fsum(values)
    except ValueError:
        # fsum RAISES on mixed infinities ("-inf + inf in fsum") where IEEE
        # arithmetic — and the device path — yields NaN; a valid payload
        # must not crash the op.
        total = float("nan")
    # A NaN INPUT poisons min/max as well as the sum: Python ``min``/``max``
    # are order-DEPENDENT under NaN (min([nan, 1]) = nan, min([1, nan]) = 1),
    # and the device path (``mesh_reduce_stats``) canonicalizes the same way,
    # so both paths return identical results for NaN-carrying shards. (An
    # inf + -inf sum is NaN too, but min/max stay well-defined there — the
    # gate is on the inputs, not the total.)
    nan_in = any(math.isnan(v) for v in values)
    mn, mx = (
        (float("nan"), float("nan")) if nan_in
        else (min(values), max(values))
    )
    return {
        "ok": True,
        "count": len(values),
        "sum": total,
        "mean": total / len(values),
        "min": mn,
        "max": mx,
        "compute_time_ms": (time.perf_counter() - t0) * 1000.0,
    }
