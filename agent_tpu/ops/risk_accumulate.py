"""Reduction op: count/sum/mean/min/max over numeric values.

Capability parity with reference ``ops/risk_accumulate.py:18-77``: payload is a
numeric ``values`` list or an ``items`` list-of-dicts with a ``field`` selector
(default ``"risk"``, ref ``:44``); result carries ``{count, sum, mean, min, max,
compute_time_ms}`` with the zero-input shape of ref ``:56-63``. This op is the
swarm's reduce stage: the controller combines per-shard partials.

The TPU-native upgrade (BASELINE.json north star: "risk_accumulate runs as an
on-device lax.psum reduction"): when a device runtime ``ctx`` is present and the
payload is large enough to be worth shipping to HBM, the reduction runs as a
single jitted ``shard_map`` program whose partials combine with ``lax.psum``
over the mesh's data axis — see ``agent_tpu.parallel.collectives.mesh_reduce``.
Small payloads keep the host path (device dispatch would dominate).
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Optional

from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input

# Below this many values the host reduce wins; above it the mesh psum path is
# worth the transfer. Chosen conservatively; bench.py can sweep it.
DEVICE_THRESHOLD = 4096


def _extract_values(payload: Dict[str, Any]) -> List[float]:
    if "values" in payload:
        values = payload["values"]
        if not isinstance(values, list):
            raise ValueError("values must be a list of numbers")
        out = []
        for v in values:
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError("values must be numeric")
            out.append(float(v))
        return out
    if "items" in payload:
        items = payload["items"]
        if not isinstance(items, list):
            raise ValueError("items must be a list of dicts")
        fieldname = payload.get("field", "risk")
        out = []
        for it in items:
            if not isinstance(it, dict):
                raise ValueError("items must be dicts")
            v = it.get(fieldname)
            if v is None:
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"field {fieldname!r} must be numeric")
            out.append(float(v))
        return out
    raise ValueError("payload requires 'values' or 'items'")


def _zero_result(t0: float) -> Dict[str, Any]:
    return {
        "ok": True,
        "count": 0,
        "sum": 0.0,
        "mean": 0.0,
        "min": None,
        "max": None,
        "compute_time_ms": (time.perf_counter() - t0) * 1000.0,
    }


@register_op("risk_accumulate")
def run(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    t0 = time.perf_counter()
    if not isinstance(payload, dict):
        return bad_input("payload must be a dict")
    # Validate the threshold before any early return so a malformed payload is
    # rejected consistently, not only when the device path would consult it.
    threshold = payload.get("device_threshold", DEVICE_THRESHOLD)
    if isinstance(threshold, bool) or not isinstance(threshold, (int, float)) or threshold <= 0:
        return bad_input("device_threshold must be a positive number")

    try:
        values = _extract_values(payload)
    except ValueError as exc:
        return bad_input(str(exc))
    if not values:
        return _zero_result(t0)

    use_device = (
        ctx is not None
        and getattr(ctx, "runtime", None) is not None
        and len(values) >= threshold
    )
    if use_device:
        from agent_tpu.parallel.collectives import mesh_reduce_stats

        stats = mesh_reduce_stats(ctx.runtime, values)
        stats.update(
            ok=True,
            device="mesh",
            compute_time_ms=(time.perf_counter() - t0) * 1000.0,
        )
        return stats

    total = math.fsum(values)
    return {
        "ok": True,
        "count": len(values),
        "sum": total,
        "mean": total / len(values),
        "min": min(values),
        "max": max(values),
        "compute_time_ms": (time.perf_counter() - t0) * 1000.0,
    }
