"""MPMD pipeline split of summarize: encoder and decoder as SEPARATE ops.

The stretch leg of ISSUE 7, after the MPMD pipeline-parallelism paper
(arXiv 2412.14374): pipeline *stages* live on *different agents*, with the
controller's existing dependency gating as the inter-stage queue — no new
transport. An encode-stage agent (``TASKS=summarize_encode``) leases text
shards and posts encoder activations; a decode-stage agent
(``TASKS=summarize_decode``) leases the dep-gated decode job whose
``partials`` the controller materialized from the encode results, and posts
the summaries. Capability matching routes each stage to the right fleet;
``scripts/check_multichip_drain.py`` pins the chain's output equal to the
monolithic ``map_summarize`` drain.

Wire shape between the stages (a result body, so it rides the ordinary
``/v1/results`` → ``partials`` path):

    {ok, op: "summarize_encode", model, n_rows, empty_rows,
     chunks: [{enc: [B][Ls][d] f32, lengths: [B], n: int}, ...]}

Activations ship as plain JSON floats: a float32 → JSON → float32 round
trip is exact (every f32 is representable as a double), so the decode stage
resumes from bit-identical encoder state. These are scenario ops for the
in-house ``seq2seq`` family (checkpoint families keep the fused
``map_summarize`` path).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input

DEFAULT_MAX_LENGTH = 130


def _resolve(payload: Dict[str, Any]):
    from agent_tpu.models.seq2seq import Seq2SeqConfig
    from agent_tpu.ops._model_common import (
        config_from_payload,
        resolve_model_id,
    )

    model_id = resolve_model_id(payload, "BART_MODEL", "summarize-default")
    cfg = config_from_payload(payload, Seq2SeqConfig)
    return model_id, cfg


def _params_key(model_id: str, cfg) -> str:
    """EXACTLY ``map_summarize``'s params-store key for the seq2seq family,
    so colocated stages (and the monolithic op) share one HBM copy."""
    from agent_tpu.ops._model_common import cfg_key

    return f"{model_id}#seq2seq#{hash(cfg_key(cfg)) & 0xFFFFFFFF:08x}"


def _get_params(runtime, model_id: str, cfg):
    from agent_tpu.models import seq2seq
    from agent_tpu.ops._model_common import maybe_quantize_specs
    from agent_tpu.parallel.shardings import seq2seq_param_specs

    specs = maybe_quantize_specs(seq2seq_param_specs(cfg), "seq2seq", cfg)
    from agent_tpu.ops.map_summarize import _build_params

    return runtime.get_params(
        _params_key(model_id, cfg),
        lambda: _build_params(model_id, cfg, "seq2seq"),
        specs=specs,
    )


def _runtime(ctx):
    if ctx is not None and getattr(ctx, "require_runtime", None):
        return ctx.require_runtime()
    from agent_tpu.runtime.runtime import get_runtime

    return get_runtime()


def _put(runtime, arr: np.ndarray):
    """dp-sharded placement when the batch divides the mesh, else let jit
    place it — decode batches staged by a DIFFERENT agent's mesh need not
    divide this one's dp axis."""
    if arr.shape[0] % max(1, runtime.axis_size("dp")) == 0:
        return runtime.put_batch(arr)
    return arr


def _collect_texts(payload: Dict[str, Any]) -> Tuple[List[str], List[int]]:
    """→ (texts, empty_rows); same drain-mode contract as map_summarize
    (blank CSV cells become empty summaries, not model noise)."""
    texts = payload.get("texts")
    empty_rows: List[int] = []
    if texts is None and "source_uri" in payload:
        from agent_tpu.data.csv_index import read_shard_texts

        texts = read_shard_texts(payload)  # ValueError → soft, I/O raises
        empty_rows = [i for i, t in enumerate(texts) if not t]
        if empty_rows:
            texts = [t or " " for t in texts]
    if not isinstance(texts, list) or not texts or not all(
        isinstance(t, str) and t for t in texts
    ):
        raise ValueError(
            "payload requires 'texts' (non-empty strings) or 'source_uri' "
            "shard addressing"
        )
    return texts, empty_rows


@register_op("summarize_encode")
def run_encode(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    """Encoder stage: texts → encoder activations (the inter-stage wire)."""
    t0 = time.perf_counter()
    if not isinstance(payload, dict):
        return bad_input("payload must be a dict")
    try:
        texts, empty_rows = _collect_texts(payload)
    except ValueError as exc:
        return bad_input(str(exc))
    model_id, cfg = _resolve(payload)

    import jax

    runtime = _runtime(ctx)
    from agent_tpu.ops.map_summarize import _stage_chunks

    chunks = _stage_chunks(
        runtime.axis_size("dp"), texts, cfg, num_beams=1, family="seq2seq",
        model_id=model_id,
    )
    params = _get_params(runtime, model_id, cfg)
    attn_fn = runtime.attention_fn()
    out_chunks = []
    for ids, lengths, n in chunks:
        B, Ls = ids.shape

        def build(Ls=Ls):
            import jax.numpy as jnp

            from agent_tpu.models import seq2seq

            def run_enc(p, i, nlen):
                mask = (jnp.arange(Ls)[None, :] < nlen[:, None]).astype(
                    jnp.int32
                )
                enc = seq2seq.encode(
                    p, i.astype(jnp.int32), mask, cfg, attn_fn=attn_fn
                )
                # f32 on the wire regardless of compute dtype: exact JSON
                # round trip, and the decode stage re-casts to its own
                # compute dtype (a bf16→f32 widening is lossless).
                return enc.astype(jnp.float32)

            return jax.jit(run_enc)

        from agent_tpu.ops._model_common import cfg_key

        fn = runtime.compiled(
            ("summarize_encode", model_id, B, Ls, cfg_key(cfg)), build
        )
        enc = np.asarray(
            fn(params, _put(runtime, ids), _put(runtime, lengths))
        )
        out_chunks.append({
            "enc": enc.tolist(),
            "lengths": np.asarray(lengths).astype(int).tolist(),
            "n": int(n),
        })
    return {
        "ok": True,
        "op": "summarize_encode",
        "model": model_id,
        "device": runtime.platform,
        "n_rows": len(texts),
        "empty_rows": empty_rows,
        "chunks": out_chunks,
        "elapsed_ms": (time.perf_counter() - t0) * 1000.0,
    }


def _encoded_inputs(payload: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The encode-stage results to decode: ``encoded`` (one result object)
    or ``partials`` (the controller's dep-gated materialization)."""
    if "encoded" in payload:
        sources = [payload["encoded"]]
    elif "partials" in payload:
        sources = payload["partials"]
    else:
        raise ValueError(
            "payload requires 'encoded' (one summarize_encode result) or "
            "dep-gated 'partials'"
        )
    if not isinstance(sources, list) or not sources:
        raise ValueError("no encode-stage results to decode")
    for src in sources:
        if not (
            isinstance(src, dict) and src.get("op") == "summarize_encode"
            and isinstance(src.get("chunks"), list) and src["chunks"]
        ):
            raise ValueError(
                "each encoded input must be a summarize_encode result "
                "carrying 'chunks'"
            )
    return sources


@register_op("summarize_decode")
def run_decode(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    """Decoder stage: encoder activations → summaries. ``model_config`` /
    ``model_path`` must match the encode stage's — the decoder resumes with
    the same (deterministically seeded) weights."""
    t0 = time.perf_counter()
    if not isinstance(payload, dict):
        return bad_input("payload must be a dict")
    try:
        sources = _encoded_inputs(payload)
    except ValueError as exc:
        return bad_input(str(exc))
    max_new = payload.get("max_length", DEFAULT_MAX_LENGTH)
    if isinstance(max_new, bool) or not isinstance(max_new, int) \
            or max_new <= 0:
        return bad_input("max_length must be a positive int")
    model_id, cfg = _resolve(payload)
    max_new = min(max_new, cfg.max_tgt_len)

    import jax

    runtime = _runtime(ctx)
    params = _get_params(runtime, model_id, cfg)
    from agent_tpu.models.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    summaries: List[str] = []
    n_rows = 0
    for src in sources:
        src_summaries: List[str] = []
        for chunk in src["chunks"]:
            enc = np.asarray(chunk["enc"], dtype=np.float32)
            lengths = np.asarray(chunk["lengths"], dtype=np.int32)
            n = int(chunk["n"])
            if enc.ndim != 3 or lengths.ndim != 1 \
                    or enc.shape[0] != lengths.shape[0]:
                return bad_input(
                    f"malformed encode chunk: enc {enc.shape}, "
                    f"lengths {lengths.shape}"
                )
            B, Ls, _d = enc.shape

            def build(Ls=Ls):
                import jax.numpy as jnp

                from agent_tpu.models import seq2seq

                def run_dec(p, e, nlen):
                    mask = (jnp.arange(Ls)[None, :] < nlen[:, None]).astype(
                        jnp.int32
                    )
                    toks, _lens = seq2seq.greedy_generate_from_encoded(
                        p, e, mask, cfg, max_new
                    )
                    return toks

                return jax.jit(run_dec)

            from agent_tpu.ops._model_common import cfg_key

            fn = runtime.compiled(
                ("summarize_decode", model_id, B, Ls, max_new, cfg_key(cfg)),
                build,
            )
            toks = np.asarray(
                fn(params, _put(runtime, enc), _put(runtime, lengths))
            )[:n]
            src_summaries.extend(
                tok.decode([t for t in row if t > 0]) for row in toks
            )
        for i in src.get("empty_rows") or []:
            if 0 <= int(i) < len(src_summaries):
                src_summaries[int(i)] = ""  # drain blanks stay blank
        summaries.extend(src_summaries)
        n_rows += len(src_summaries)
    return {
        "ok": True,
        "op": "summarize_decode",
        "model": model_id,
        "device": runtime.platform,
        "n_rows": n_rows,
        "summaries": summaries,
        "elapsed_ms": (time.perf_counter() - t0) * 1000.0,
    }
