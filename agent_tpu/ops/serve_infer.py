"""Request-serving ops: the agent half of the ``POST /v1/infer`` path.

The controller's front door (``controller/serving.py``) coalesces single
requests into length-bucketed batch jobs; these ops execute them:

- ``serve_classify`` — one batched encoder forward through the existing
  ``map_classify_tpu`` guts, fanned back out per request. Monolithic: a
  classify is a single dispatch, there is nothing to batch continuously.
- ``serve_summarize`` — the decode path, split prefill/decode (ISSUE 15):
  **prefill** runs as its own batched compiled step (``seq2seq.encode`` —
  the ``summarize_mpmd`` encoded-handoff shape), then the requests join a
  process-persistent :class:`~agent_tpu.models.decoding.ContinuousBatcher`
  whose fixed-capacity running batch decodes ``SERVE_DECODE_SLOTS``
  requests × ``num_beams`` beam rows per step, finished sequences exiting
  and queued ones joining *between steps*. Each request carries its own
  ``max_length`` as the per-slot token limit — short answers free their
  slot early instead of riding the batch to the longest request's length,
  which is the whole throughput story vs. the static-batch decode.

Phase contract for the pipelined drain: ``stage``/``finalize`` as usual,
plus the serving hooks the runner's continuous loop drives —
``serve_admit`` (prefill + join), ``serve_pump`` (one engine iteration),
``serve_done``/``serve_collect``. Monolithic callers (serial agent loop,
tests) get the composed ``run`` which pumps to completion inline.

Scenario ops for the in-house seq2seq family (like ``summarize_mpmd``);
checkpoint families keep the batch ``map_summarize`` path.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input

# Process-wide engine store, keyed by (runtime identity, model/config/shape
# signature). Device-thread only (engines are created and stepped inside op
# execute paths — the TPU single-owner rule), so no lock.
_ENGINES: Dict[Tuple, Any] = {}

# Process-wide prefix cache (ISSUE 16), rebuilt when its knobs change.
_PREFIX_CACHE: Any = None
_PREFIX_KNOBS: Optional[Tuple] = None


def reset_engines() -> None:
    """Drop every cached engine (tests; a fresh runtime invalidates them)."""
    global _PREFIX_CACHE, _PREFIX_KNOBS
    _ENGINES.clear()
    _PREFIX_CACHE = None
    _PREFIX_KNOBS = None


def _get_prefix_cache(serve):
    """The process prefix cache per the active knobs, or ``None`` when
    disabled."""
    global _PREFIX_CACHE, _PREFIX_KNOBS
    if not serve.prefix_cache_enabled or serve.prefix_cache_entries < 1 \
            or serve.prefix_cache_mb <= 0:
        return None
    knobs = (serve.prefix_cache_entries, serve.prefix_cache_mb)
    if _PREFIX_CACHE is None or _PREFIX_KNOBS != knobs:
        from agent_tpu.ops.prefix_cache import PrefixCache

        _PREFIX_CACHE = PrefixCache(
            max_entries=serve.prefix_cache_entries,
            max_bytes=int(serve.prefix_cache_mb * 2 ** 20),
        )
        _PREFIX_KNOBS = knobs
    return _PREFIX_CACHE


def _clamp_ttft(first_wall: Optional[float], arrived: Any) -> Optional[float]:
    """first-token wall − controller arrival wall, in ms, clamped at 0
    (the two clocks are different hosts' ``time.time()``; sub-ms skew must
    not produce negative TTFT)."""
    if first_wall is None or not isinstance(arrived, (int, float)):
        return None
    return round(max(0.0, (first_wall - float(arrived)) * 1e3), 3)


def _validate_requests(payload: Dict[str, Any]):
    reqs = payload.get("requests")
    if not isinstance(reqs, list) or not reqs:
        raise ValueError("payload requires a non-empty 'requests' list")
    for r in reqs:
        if not (
            isinstance(r, dict)
            and isinstance(r.get("req_id"), str) and r["req_id"]
            and isinstance(r.get("text"), str) and r["text"]
        ):
            raise ValueError(
                "each request needs a string req_id and a non-empty text"
            )
    return reqs


# ---------------------------------------------------------------------------
# serve_classify
# ---------------------------------------------------------------------------

@register_op("serve_classify")
def run_classify(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    """Batched interactive classify: requests in, per-request top-k out."""
    t0 = time.perf_counter()
    t0_wall = time.time()
    if not isinstance(payload, dict):
        return bad_input("payload must be a dict")
    try:
        reqs = _validate_requests(payload)
    except ValueError as exc:
        return bad_input(str(exc))
    topk = payload.get("topk", 1)
    if isinstance(topk, bool) or not isinstance(topk, int) or topk < 1:
        return bad_input("topk must be a positive int")

    from agent_tpu.ops import get_op

    sub: Dict[str, Any] = {
        "texts": [r["text"] for r in reqs],
        "topk": topk,
        "allow_fallback": False,
        "result_format": "columnar",
    }
    if isinstance(payload.get("model_config"), dict):
        sub["model_config"] = payload["model_config"]
    # The negotiated binary wire ("b1" in ctx.tags) would make classify
    # emit deflated result columns — this op fans the columns out PER
    # REQUEST, so it needs them plain; pop the tag for the delegated call
    # (everything else — timings, usage, FLOPs stamps — keeps flowing).
    tags = getattr(ctx, "tags", None) if ctx is not None else None
    wire_fmt = tags.pop("wire", None) if isinstance(tags, dict) else None
    try:
        out = get_op("map_classify_tpu")(sub, ctx)
    finally:
        if wire_fmt is not None:
            tags["wire"] = wire_fmt
    if not (isinstance(out, dict) and out.get("ok") is True):
        return out  # soft error shape propagates as this op's result
    now = time.time()
    results = [
        {
            "req_id": r["req_id"],
            "indices": out["indices"][i],
            "scores": out["scores"][i],
            # No decode stream: the first answer byte IS the whole answer.
            "ttft_ms": _clamp_ttft(now, r.get("arrived_wall")),
            "tokens": 0,
            # Per-request telemetry (ISSUE 17): classify is one forward —
            # the whole device window is "prefill", first token == done.
            "telemetry": {
                "path": "colocated",
                "prefill_t0_wall": t0_wall,
                "prefill_t1_wall": now,
                "admitted_wall": now,
                "joined_wall": now,
                "first_token_wall": now,
                "done_wall": now,
                "kv_wait_ms": 0.0,
                "occupancy_at_join": len(reqs),
                "cache_hit": False,
                "steps": 0,
            },
        }
        for i, r in enumerate(reqs)
    ]
    return {
        "ok": True,
        "op": "serve_classify",
        "device": out.get("device"),
        "model": out.get("model"),
        "n_requests": len(reqs),
        "results": results,
        "occupancy": float(len(reqs)),
        "max_occupancy": len(reqs),
        "elapsed_ms": (time.perf_counter() - t0) * 1000.0,
    }


# ---------------------------------------------------------------------------
# serve_summarize
# ---------------------------------------------------------------------------

def _resolve(payload: Dict[str, Any]):
    from agent_tpu.models import bert
    from agent_tpu.models.seq2seq import Seq2SeqConfig
    from agent_tpu.ops._model_common import (
        config_from_payload,
        resolve_model_id,
    )

    model_id = resolve_model_id(payload, "BART_MODEL", "summarize-default")
    if bert.is_hf_dir(model_id):
        raise ValueError(
            "serve_summarize serves the in-house seq2seq family; checkpoint "
            "directories stay on the batch map_summarize path"
        )
    cfg = config_from_payload(payload, Seq2SeqConfig)
    return model_id, cfg


def _runtime(ctx):
    if ctx is not None and getattr(ctx, "require_runtime", None):
        return ctx.require_runtime()
    from agent_tpu.runtime.runtime import get_runtime

    return get_runtime()


def _serve_knobs(ctx):
    """The agent's :class:`~agent_tpu.config.ServeConfig` (SERVE_* env)."""
    cfg = getattr(ctx, "config", None) if ctx is not None else None
    serve = getattr(cfg, "serve", None) if cfg is not None else None
    if serve is None:
        from agent_tpu.config import ServeConfig

        serve = ServeConfig.from_env()
    return serve


def stage(payload: Any, ctx: Optional[object] = None):
    """Host phase: validate the batch, fused byte-tokenize+pad every request
    to the bucket length the controller coalesced on."""
    t0 = time.perf_counter()
    if not isinstance(payload, dict):
        return "done", bad_input("payload must be a dict")
    try:
        reqs = _validate_requests(payload)
        model_id, cfg = _resolve(payload)
    except ValueError as exc:
        return "done", bad_input(str(exc))

    num_beams = payload.get("num_beams", 1)
    if isinstance(num_beams, bool) or not isinstance(num_beams, int) or \
            not 1 <= num_beams <= 16:
        return "done", bad_input("num_beams must be an int in [1, 16]")
    length_penalty = payload.get("length_penalty", 1.0)
    if isinstance(length_penalty, bool) or \
            not isinstance(length_penalty, (int, float)) or \
            not -4.0 <= float(length_penalty) <= 4.0:
        return "done", bad_input("length_penalty must be a number in [-4, 4]")
    early_stopping = payload.get("early_stopping", False)
    if not isinstance(early_stopping, bool):
        return "done", bad_input("early_stopping must be a bool")
    min_length = payload.get("min_length", 0)
    if isinstance(min_length, bool) or not isinstance(min_length, int) or \
            min_length < 0:
        return "done", bad_input("min_length must be a non-negative int")
    bucket = payload.get("bucket", cfg.max_src_len)
    if isinstance(bucket, bool) or not isinstance(bucket, int) or bucket < 1:
        return "done", bad_input("bucket must be a positive int")
    bucket = min(bucket, cfg.max_src_len)

    from agent_tpu.models.tokenizer import byte_encode_pad

    # One fixed padded length per batch (the controller's length bucket):
    # the prefill program and the engine's encoder block key on it.
    ids, lengths = byte_encode_pad(
        [r["text"] for r in reqs], buckets=(bucket,), max_len_cap=bucket,
        add_bos=True, add_eos=True,
    )
    limits = []
    for r in reqs:
        lim = r.get("max_length")
        if lim is None:
            lim = cfg.max_tgt_len
        if isinstance(lim, bool) or not isinstance(lim, int) or lim < 1:
            return "done", bad_input("max_length must be a positive int")
        limits.append(min(lim, cfg.max_tgt_len))
    state = {
        "t0": t0,
        "reqs": reqs,
        "ids": ids.astype(np.int32),
        "lengths": np.asarray(lengths, dtype=np.int32),
        "limits": limits,
        "bucket": int(ids.shape[1]),
        "model_id": model_id,
        "cfg": cfg,
        "num_beams": num_beams,
        "length_penalty": float(length_penalty),
        "early_stopping": early_stopping,
        "min_length": min_length,
        "t_staged": time.perf_counter(),
    }
    return "staged", state


def _params_key(model_id: str, cfg) -> str:
    """EXACTLY ``map_summarize``'s params-store key for the seq2seq family,
    so colocated serving + batch ops share one HBM weight copy."""
    from agent_tpu.ops._model_common import cfg_key

    return f"{model_id}#seq2seq#{hash(cfg_key(cfg)) & 0xFFFFFFFF:08x}"


def _get_params(runtime, model_id: str, cfg):
    from agent_tpu.ops._model_common import maybe_quantize_specs
    from agent_tpu.ops.map_summarize import _build_params
    from agent_tpu.parallel.shardings import seq2seq_param_specs

    specs = maybe_quantize_specs(seq2seq_param_specs(cfg), "seq2seq", cfg)
    return runtime.get_params(
        _params_key(model_id, cfg),
        lambda: _build_params(model_id, cfg, "seq2seq"),
        specs=specs,
    )


def _get_engine(runtime, params, state, serve):
    from agent_tpu.models import seq2seq
    from agent_tpu.models.decoding import ContinuousBatcher
    from agent_tpu.models.tokenizer import BOS_ID, EOS_ID, PAD_ID
    from agent_tpu.ops._model_common import cfg_key

    cfg = state["cfg"]
    slots = int(serve.decode_slots)
    micro_steps = int(serve.decode_micro_steps)
    paged = serve.kv_layout == "paged"
    key = (
        id(runtime), state["model_id"], cfg_key(cfg), state["bucket"],
        state["num_beams"], state["min_length"], state["length_penalty"],
        state["early_stopping"], slots, micro_steps,
        serve.kv_layout, serve.kv_block_size, serve.kv_pool_blocks,
    )
    engine = _ENGINES.get(key)
    if engine is None:
        if paged:
            cache_factory = seq2seq.make_paged_cache_factory(
                cfg, block_size=serve.kv_block_size,
                pool_blocks=serve.kv_pool_blocks,
            )
        else:
            cache_factory = seq2seq.make_cache_factory(cfg)
        engine = ContinuousBatcher(
            seq2seq.make_positional_step(params, cfg),
            cache_factory,
            slots=slots,
            vocab_size=cfg.vocab_size,
            max_tokens=cfg.max_tgt_len,
            enc_len=state["bucket"],
            d_model=cfg.d_model,
            start_id=BOS_ID, eos_id=EOS_ID, pad_id=PAD_ID,
            num_beams=state["num_beams"],
            min_length=state["min_length"],
            length_penalty=state["length_penalty"],
            early_stopping=state["early_stopping"],
            micro_steps=micro_steps,
        )
        _ENGINES[key] = engine
    return engine


def _prefill_rows(runtime, params, state, serve):
    """Prefill this batch: prefix-cache hits come back from host RAM, only
    the MISS rows run the compiled encoder. Returns
    ``(enc f32 [B, Ls, d_model], prefix delta dict)``.

    A hit row is the exact ``float32`` array the cold prefill produced
    when it populated the cache — bit-identical by construction. The miss
    rows compile per distinct miss count (like the batch dim already did);
    length buckets keep that key space small.
    """
    import jax

    ids, lengths = state["ids"], state["lengths"]
    B, Ls = ids.shape
    cfg, model_id = state["cfg"], state["model_id"]
    cache = _get_prefix_cache(serve)
    enc = np.zeros((B, Ls, cfg.d_model), dtype=np.float32)
    hit = np.zeros((B,), dtype=bool)
    keys: List[Optional[str]] = [None] * B
    if cache is not None:
        from agent_tpu.ops.prefix_cache import prefix_key

        version = _params_key(model_id, cfg)
        for i in range(B):
            keys[i] = prefix_key(version, ids[i])
            row = cache.get(keys[i])
            if row is not None:
                enc[i] = row
                hit[i] = True
    miss = np.nonzero(~hit)[0]
    ev0 = cache.evictions if cache is not None else 0
    t_pf0 = time.time()
    if miss.size:

        def build(Ls=Ls, n=int(miss.size)):
            import jax.numpy as jnp

            from agent_tpu.models import seq2seq

            def run_enc(p, i, nlen):
                mask = (
                    jnp.arange(Ls)[None, :] < nlen[:, None]
                ).astype(jnp.int32)
                out = seq2seq.encode(p, i.astype(jnp.int32), mask, cfg)
                # f32 handoff like summarize_mpmd: a bf16→f32 widening is
                # lossless and the engine re-casts to its compute dtype.
                return out.astype(jnp.float32)

            return jax.jit(run_enc)

        from agent_tpu.ops._model_common import cfg_key

        fn = runtime.compiled(
            ("serve_prefill", model_id, int(miss.size), Ls, cfg_key(cfg)),
            build,
        )
        got = np.asarray(
            fn(params, ids[miss], lengths[miss])
        )
        enc[miss] = got
        if cache is not None:
            for j, i in enumerate(miss):
                cache.put(keys[i], got[j])
    return enc, {
        "hits": int(hit.sum()),
        "misses": int(miss.size),
        "evictions": int(
            (cache.evictions - ev0) if cache is not None else 0
        ),
        # Per-row hit flags + the encoder-forward wall window (ISSUE 17):
        # the telemetry side channel — finalize pops them out of the
        # controller-visible prefix counters.
        "row_hits": hit.tolist(),
        "prefill_t0_wall": t_pf0,
        "prefill_t1_wall": time.time(),
    }


def serve_admit(state: Dict[str, Any], ctx: Optional[object] = None
                ) -> Dict[str, Any]:
    """Device phase, part 1 — prefill as its own batched step (prefix-cache
    hits skip it, ISSUE 16), then join the continuous engine (between
    decode iterations, never inside one). Returns the handle the runner
    pumps. Disaggregated decode jobs arrive with ``enc_rows`` already in
    the state (the serve_prefill agent's b1-wire handoff) and skip prefill
    entirely."""
    runtime = _runtime(ctx)
    cfg, model_id = state["cfg"], state["model_id"]
    params = _get_params(runtime, model_id, cfg)
    serve = _serve_knobs(ctx)
    engine = _get_engine(runtime, params, state, serve)
    if state.get("enc_rows") is not None:
        enc = np.asarray(state.pop("enc_rows"), dtype=np.float32)
        prefix = state.pop("prefix", None) or {
            "hits": 0, "misses": 0, "evictions": 0,
        }
    else:
        enc, prefix = _prefill_rows(runtime, params, state, serve)
    Ls = state["ids"].shape[1]
    masks = (
        np.arange(Ls)[None, :] < state["lengths"][:, None]
    ).astype(np.int32)
    t_admit = time.perf_counter()
    steps0, occ0 = engine.steps_run, engine.occupancy_sum
    tickets = []
    for i, r in enumerate(state["reqs"][: len(state["limits"])]):
        tickets.append(
            engine.admit(
                enc[i], masks[i], state["limits"][i],
                data={"req_id": r["req_id"],
                      "arrived_wall": r.get("arrived_wall")},
            )
        )
    return {
        "engine": engine,
        "tickets": tickets,
        "state": state,
        "prefix": prefix,
        "t_admit": t_admit,
        "steps0": steps0,
        "occ0": occ0,
        "device": runtime.platform,
    }


def serve_pump(handle: Dict[str, Any]) -> int:
    """One decode iteration of the handle's engine (finished sequences exit,
    backlog joins). Returns the live occupancy after the step."""
    engine = handle["engine"]
    engine.step()
    return engine.occupancy


def serve_done(handle: Dict[str, Any]) -> bool:
    return all(t.done_wall is not None for t in handle["tickets"])


def serve_collect(handle: Dict[str, Any]) -> Dict[str, Any]:
    """Handle → executed-state (the poster thread's finalize input)."""
    engine, state = handle["engine"], handle["state"]
    d_steps = max(1, engine.steps_run - handle["steps0"])
    d_occ = engine.occupancy_sum - handle["occ0"]
    return {
        "state": state,
        "tickets": handle["tickets"],
        "device": handle["device"],
        "occupancy": round(d_occ / d_steps, 3),
        "max_occupancy": engine.max_occupancy,
        "prefix": handle.get("prefix"),
        "kv_blocks_total": engine.kv_blocks_total,
        "kv_blocks_free": engine.kv_blocks_free,
        "t_admit": handle["t_admit"],
        "t_device": time.perf_counter(),
    }


def execute(state: Dict[str, Any], ctx: Optional[object] = None
            ) -> Dict[str, Any]:
    """Monolithic device phase: admit, pump this job's tickets to
    completion inline (the pipelined runner interleaves instead)."""
    handle = serve_admit(state, ctx)
    handle["engine"].run(handle["tickets"])
    return serve_collect(handle)


def finalize(executed: Dict[str, Any], ctx: Optional[object] = None
             ) -> Dict[str, Any]:
    """Host phase: detokenize each ticket's emitted tokens, shape the
    per-request fan-out entries the controller's front door expects."""
    from agent_tpu.models.tokenizer import ByteTokenizer

    state = executed["state"]
    tok = ByteTokenizer()
    prefix = dict(executed.get("prefix") or {
        "hits": 0, "misses": 0, "evictions": 0,
    })
    # Telemetry side channel riding the prefix dict (ISSUE 17): per-row
    # cache-hit flags + the prefill wall window — popped here so the
    # controller-facing prefix counters stay {hits, misses, evictions}.
    row_hits = prefix.pop("row_hits", None)
    pf_t0 = prefix.pop("prefill_t0_wall", None)
    pf_t1 = prefix.pop("prefill_t1_wall", None)
    path = "disagg" if state.get("op_name") == "serve_decode" \
        else "colocated"
    results: List[Dict[str, Any]] = []
    for i, ticket in enumerate(executed["tickets"]):
        row = ticket.tokens if ticket.tokens is not None else np.array([], int)
        results.append({
            "req_id": ticket.data["req_id"],
            "summary": tok.decode([t for t in row if t > 0]),
            "tokens": int(ticket.length),
            "steps": int(ticket.steps),
            "ttft_ms": _clamp_ttft(
                ticket.first_token_wall, ticket.data.get("arrived_wall")
            ),
            # Raw decomposition material for the controller's
            # serve_ttft_component_seconds / serve_tpot_seconds feeds and
            # the synthesized request-trace spans: lifecycle walls stamped
            # by the continuous engine + the prefill window above. Walls
            # on either side of a process boundary telescope — the
            # component sum equals first_token − arrival exactly.
            "telemetry": {
                "path": path,
                "prefill_t0_wall": pf_t0,
                "prefill_t1_wall": pf_t1,
                "admitted_wall": ticket.admitted_wall,
                "joined_wall": ticket.joined_wall,
                "first_token_wall": ticket.first_token_wall,
                "done_wall": ticket.done_wall,
                "kv_wait_ms": round(ticket.kv_wait_s * 1e3, 3),
                "join_step": int(ticket.join_step),
                "occupancy_at_join": int(ticket.occupancy_at_join),
                "cache_hit": bool(row_hits[i]) if (
                    isinstance(row_hits, list) and i < len(row_hits)
                ) else False,
                "steps": int(ticket.steps),
                "events": [
                    [name, wall] for name, wall in ticket.events
                ],
            },
        })
    if ctx is not None and hasattr(ctx, "tags"):
        ctx.tags.setdefault("timings", {}).update(
            stage_ms=round((state["t_staged"] - state["t0"]) * 1e3, 3),
            device_ms=round(
                (executed["t_device"] - executed["t_admit"]) * 1e3, 3
            ),
        )
    from agent_tpu.ops._model_common import stamp_rows

    stamp_rows(ctx, len(results))
    # A disaggregated decode job carries the PREFILL agent's counters
    # forward (so the controller's reap sees them on the one job it
    # watches) — but that agent already billed the cache hits; billing
    # again here would double-count the saved prefill.
    forwarded = bool(prefix.pop("forwarded", False))
    if prefix.get("hits") and not forwarded and ctx is not None \
            and hasattr(ctx, "tags"):
        from agent_tpu.obs.usage import stamp_usage

        # Saved prefill bills as cache hits — the showback line that says
        # what a tenant's repeated prefixes DIDN'T cost (ISSUE 16).
        stamp_usage(ctx.tags, cache_hit_rows=float(prefix["hits"]))
    return {
        "ok": True,
        "op": state.get("op_name", "serve_summarize"),
        "device": executed["device"],
        "model": state["model_id"],
        "num_beams": state["num_beams"],
        "n_requests": len(results),
        "results": results,
        "occupancy": executed["occupancy"],
        "max_occupancy": executed["max_occupancy"],
        "prefix_cache": prefix,
        "kv_blocks_total": executed.get("kv_blocks_total", 0),
        "kv_blocks_free": executed.get("kv_blocks_free", 0),
        "elapsed_ms": (time.perf_counter() - state["t0"]) * 1000.0,
    }


@register_op("serve_summarize")
def run_summarize(payload: Any, ctx: Optional[object] = None
                  ) -> Dict[str, Any]:
    """Classic monolithic entry: stage → execute → finalize inline."""
    phase, value = stage(payload, ctx)
    if phase == "done":
        return value
    return finalize(execute(value, ctx), ctx)


# Phase hooks for the pipelined drain, plus the serving hooks its
# continuous loop drives (agent_tpu.agent.pipeline).
run_summarize.stage = stage
run_summarize.execute = execute
run_summarize.finalize = finalize
run_summarize.serve_admit = serve_admit
run_summarize.serve_pump = serve_pump
run_summarize.serve_done = serve_done
run_summarize.serve_collect = serve_collect


# ---------------------------------------------------------------------------
# disaggregated prefill/decode pools (ISSUE 16)
# ---------------------------------------------------------------------------

@register_op("serve_prefill")
def run_prefill(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    """Prefill half of the disaggregated pool split (``SERVE_DISAGG=1``):
    tokenize the batch and run the prefix-cached encoder forward, posting
    the encoded rows as this job's RESULT — binary (b1) columns to a
    negotiated controller, plain JSON floats otherwise. Both decode to the
    identical f32 rows (exact bit patterns on b1; exact float→double→float
    round trip on JSON, the ``summarize_mpmd`` argument), so the decode
    pool resumes bit-identically either way. The dep-gated ``serve_decode``
    job receives this result as its ``partials``."""
    phase, state = stage(payload, ctx)
    if phase == "done":
        return state
    runtime = _runtime(ctx)
    params = _get_params(runtime, state["model_id"], state["cfg"])
    serve = _serve_knobs(ctx)
    enc, prefix = _prefill_rows(runtime, params, state, serve)
    if ctx is not None and hasattr(ctx, "tags"):
        ctx.tags.setdefault("timings", {}).update(
            stage_ms=round((state["t_staged"] - state["t0"]) * 1e3, 3),
        )
        if prefix.get("hits"):
            from agent_tpu.obs.usage import stamp_usage

            # The prefill agent is where the saved work lives in disagg
            # mode, so cache hits bill HERE (the decode job forwards the
            # counters for metrics only — see finalize).
            stamp_usage(ctx.tags, cache_hit_rows=float(prefix["hits"]))
    out: Dict[str, Any] = {
        "ok": True,
        "op": "serve_prefill",
        "device": runtime.platform,
        "model": state["model_id"],
        "n_requests": len(state["reqs"]),
        "bucket": state["bucket"],
        "prefix_cache": prefix,
        "elapsed_ms": (time.perf_counter() - state["t0"]) * 1000.0,
    }
    tags = getattr(ctx, "tags", None) if ctx is not None else None
    if isinstance(tags, dict) and tags.get("wire") == "b1":
        from agent_tpu.data import wire

        return wire.attach_result_columns(out, {
            "enc_rows": np.ascontiguousarray(enc),
            "lengths": np.ascontiguousarray(state["lengths"]),
        })
    out["enc_rows"] = enc.tolist()
    out["lengths"] = state["lengths"].astype(int).tolist()
    return out


def _handoff_rows(
    payload: Dict[str, Any], state: Dict[str, Any]
) -> Tuple[np.ndarray, Dict[str, Any]]:
    """The serve_prefill result riding this decode job: ``encoded`` (one
    result object — tests, manual chains) or dep-gated ``partials`` (the
    controller's lease-time materialization). Returns the f32 encoded rows
    and the prefill agent's prefix-cache delta, marked ``forwarded`` so the
    decode side reports it without re-billing it."""
    if "encoded" in payload:
        sources: Any = [payload["encoded"]]
    elif "partials" in payload:
        sources = payload["partials"]
    else:
        raise ValueError(
            "serve_decode requires 'encoded' (one serve_prefill result) or "
            "dep-gated 'partials'"
        )
    if not isinstance(sources, list) or len(sources) != 1:
        raise ValueError(
            "serve_decode expects exactly one prefill result to resume from"
        )
    src = sources[0]
    if not (
        isinstance(src, dict) and src.get("ok") is True
        and src.get("op") == "serve_prefill"
    ):
        raise ValueError("handoff is not an ok serve_prefill result")
    enc = np.asarray(src.get("enc_rows"), dtype=np.float32)
    B, Ls = state["ids"].shape
    d_model = state["cfg"].d_model
    if enc.ndim != 3 or enc.shape != (B, Ls, d_model):
        raise ValueError(
            f"handoff enc_rows shape {enc.shape} does not match the batch "
            f"({B}, {Ls}, {d_model}) — prefill and decode saw different "
            f"payloads?"
        )
    prefix = dict(src.get("prefix_cache") or {})
    prefix["forwarded"] = True
    return enc, prefix


def _decode_stage(payload: Any, ctx: Optional[object] = None):
    """serve_decode's stage: the ordinary serving stage plus the prefill
    handoff — the encoded rows land in the state, so ``serve_admit`` skips
    the encoder entirely (the whole point of the split pool). The byte
    tokenizer is deterministic, so re-tokenizing the same texts here yields
    the very ids/lengths the prefill stage hashed and encoded."""
    phase, state = stage(payload, ctx)
    if phase == "done":
        return phase, state
    try:
        enc, prefix = _handoff_rows(payload, state)
    except ValueError as exc:
        return "done", bad_input(str(exc))
    state["enc_rows"] = enc
    state["prefix"] = prefix
    state["op_name"] = "serve_decode"
    return "staged", state


@register_op("serve_decode")
def run_decode(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    """Decode half of the disaggregated pool split: resume from the
    serve_prefill result's encoded rows and run ONLY the continuous decode
    engine — bit-identical to the colocated serve_summarize path, because
    the engine is handed the very same f32 rows either way."""
    phase, value = _decode_stage(payload, ctx)
    if phase == "done":
        return value
    return finalize(execute(value, ctx), ctx)


run_decode.stage = _decode_stage
run_decode.execute = execute
run_decode.finalize = finalize
run_decode.serve_admit = serve_admit
run_decode.serve_pump = serve_pump
run_decode.serve_done = serve_done
run_decode.serve_collect = serve_collect
