"""Request-serving ops: the agent half of the ``POST /v1/infer`` path.

The controller's front door (``controller/serving.py``) coalesces single
requests into length-bucketed batch jobs; these ops execute them:

- ``serve_classify`` — one batched encoder forward through the existing
  ``map_classify_tpu`` guts, fanned back out per request. Monolithic: a
  classify is a single dispatch, there is nothing to batch continuously.
- ``serve_summarize`` — the decode path, split prefill/decode (ISSUE 15):
  **prefill** runs as its own batched compiled step (``seq2seq.encode`` —
  the ``summarize_mpmd`` encoded-handoff shape), then the requests join a
  process-persistent :class:`~agent_tpu.models.decoding.ContinuousBatcher`
  whose fixed-capacity running batch decodes ``SERVE_DECODE_SLOTS``
  requests × ``num_beams`` beam rows per step, finished sequences exiting
  and queued ones joining *between steps*. Each request carries its own
  ``max_length`` as the per-slot token limit — short answers free their
  slot early instead of riding the batch to the longest request's length,
  which is the whole throughput story vs. the static-batch decode.

Phase contract for the pipelined drain: ``stage``/``finalize`` as usual,
plus the serving hooks the runner's continuous loop drives —
``serve_admit`` (prefill + join), ``serve_pump`` (one engine iteration),
``serve_done``/``serve_collect``. Monolithic callers (serial agent loop,
tests) get the composed ``run`` which pumps to completion inline.

Scenario ops for the in-house seq2seq family (like ``summarize_mpmd``);
checkpoint families keep the batch ``map_summarize`` path.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input

# Process-wide engine store, keyed by (runtime identity, model/config/shape
# signature). Device-thread only (engines are created and stepped inside op
# execute paths — the TPU single-owner rule), so no lock.
_ENGINES: Dict[Tuple, Any] = {}


def reset_engines() -> None:
    """Drop every cached engine (tests; a fresh runtime invalidates them)."""
    _ENGINES.clear()


def _clamp_ttft(first_wall: Optional[float], arrived: Any) -> Optional[float]:
    """first-token wall − controller arrival wall, in ms, clamped at 0
    (the two clocks are different hosts' ``time.time()``; sub-ms skew must
    not produce negative TTFT)."""
    if first_wall is None or not isinstance(arrived, (int, float)):
        return None
    return round(max(0.0, (first_wall - float(arrived)) * 1e3), 3)


def _validate_requests(payload: Dict[str, Any]):
    reqs = payload.get("requests")
    if not isinstance(reqs, list) or not reqs:
        raise ValueError("payload requires a non-empty 'requests' list")
    for r in reqs:
        if not (
            isinstance(r, dict)
            and isinstance(r.get("req_id"), str) and r["req_id"]
            and isinstance(r.get("text"), str) and r["text"]
        ):
            raise ValueError(
                "each request needs a string req_id and a non-empty text"
            )
    return reqs


# ---------------------------------------------------------------------------
# serve_classify
# ---------------------------------------------------------------------------

@register_op("serve_classify")
def run_classify(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    """Batched interactive classify: requests in, per-request top-k out."""
    t0 = time.perf_counter()
    if not isinstance(payload, dict):
        return bad_input("payload must be a dict")
    try:
        reqs = _validate_requests(payload)
    except ValueError as exc:
        return bad_input(str(exc))
    topk = payload.get("topk", 1)
    if isinstance(topk, bool) or not isinstance(topk, int) or topk < 1:
        return bad_input("topk must be a positive int")

    from agent_tpu.ops import get_op

    sub: Dict[str, Any] = {
        "texts": [r["text"] for r in reqs],
        "topk": topk,
        "allow_fallback": False,
        "result_format": "columnar",
    }
    if isinstance(payload.get("model_config"), dict):
        sub["model_config"] = payload["model_config"]
    # The negotiated binary wire ("b1" in ctx.tags) would make classify
    # emit deflated result columns — this op fans the columns out PER
    # REQUEST, so it needs them plain; pop the tag for the delegated call
    # (everything else — timings, usage, FLOPs stamps — keeps flowing).
    tags = getattr(ctx, "tags", None) if ctx is not None else None
    wire_fmt = tags.pop("wire", None) if isinstance(tags, dict) else None
    try:
        out = get_op("map_classify_tpu")(sub, ctx)
    finally:
        if wire_fmt is not None:
            tags["wire"] = wire_fmt
    if not (isinstance(out, dict) and out.get("ok") is True):
        return out  # soft error shape propagates as this op's result
    now = time.time()
    results = [
        {
            "req_id": r["req_id"],
            "indices": out["indices"][i],
            "scores": out["scores"][i],
            # No decode stream: the first answer byte IS the whole answer.
            "ttft_ms": _clamp_ttft(now, r.get("arrived_wall")),
            "tokens": 0,
        }
        for i, r in enumerate(reqs)
    ]
    return {
        "ok": True,
        "op": "serve_classify",
        "device": out.get("device"),
        "model": out.get("model"),
        "n_requests": len(reqs),
        "results": results,
        "occupancy": float(len(reqs)),
        "max_occupancy": len(reqs),
        "elapsed_ms": (time.perf_counter() - t0) * 1000.0,
    }


# ---------------------------------------------------------------------------
# serve_summarize
# ---------------------------------------------------------------------------

def _resolve(payload: Dict[str, Any]):
    from agent_tpu.models import bert
    from agent_tpu.models.seq2seq import Seq2SeqConfig
    from agent_tpu.ops._model_common import (
        config_from_payload,
        resolve_model_id,
    )

    model_id = resolve_model_id(payload, "BART_MODEL", "summarize-default")
    if bert.is_hf_dir(model_id):
        raise ValueError(
            "serve_summarize serves the in-house seq2seq family; checkpoint "
            "directories stay on the batch map_summarize path"
        )
    cfg = config_from_payload(payload, Seq2SeqConfig)
    return model_id, cfg


def _runtime(ctx):
    if ctx is not None and getattr(ctx, "require_runtime", None):
        return ctx.require_runtime()
    from agent_tpu.runtime.runtime import get_runtime

    return get_runtime()


def _serve_knobs(ctx) -> Tuple[int, int]:
    """(decode_slots, micro_steps) from the agent config (SERVE_* env)."""
    cfg = getattr(ctx, "config", None) if ctx is not None else None
    serve = getattr(cfg, "serve", None) if cfg is not None else None
    if serve is None:
        from agent_tpu.config import ServeConfig

        serve = ServeConfig.from_env()
    return int(serve.decode_slots), int(serve.decode_micro_steps)


def stage(payload: Any, ctx: Optional[object] = None):
    """Host phase: validate the batch, fused byte-tokenize+pad every request
    to the bucket length the controller coalesced on."""
    t0 = time.perf_counter()
    if not isinstance(payload, dict):
        return "done", bad_input("payload must be a dict")
    try:
        reqs = _validate_requests(payload)
        model_id, cfg = _resolve(payload)
    except ValueError as exc:
        return "done", bad_input(str(exc))

    num_beams = payload.get("num_beams", 1)
    if isinstance(num_beams, bool) or not isinstance(num_beams, int) or \
            not 1 <= num_beams <= 16:
        return "done", bad_input("num_beams must be an int in [1, 16]")
    length_penalty = payload.get("length_penalty", 1.0)
    if isinstance(length_penalty, bool) or \
            not isinstance(length_penalty, (int, float)) or \
            not -4.0 <= float(length_penalty) <= 4.0:
        return "done", bad_input("length_penalty must be a number in [-4, 4]")
    early_stopping = payload.get("early_stopping", False)
    if not isinstance(early_stopping, bool):
        return "done", bad_input("early_stopping must be a bool")
    min_length = payload.get("min_length", 0)
    if isinstance(min_length, bool) or not isinstance(min_length, int) or \
            min_length < 0:
        return "done", bad_input("min_length must be a non-negative int")
    bucket = payload.get("bucket", cfg.max_src_len)
    if isinstance(bucket, bool) or not isinstance(bucket, int) or bucket < 1:
        return "done", bad_input("bucket must be a positive int")
    bucket = min(bucket, cfg.max_src_len)

    from agent_tpu.models.tokenizer import byte_encode_pad

    # One fixed padded length per batch (the controller's length bucket):
    # the prefill program and the engine's encoder block key on it.
    ids, lengths = byte_encode_pad(
        [r["text"] for r in reqs], buckets=(bucket,), max_len_cap=bucket,
        add_bos=True, add_eos=True,
    )
    limits = []
    for r in reqs:
        lim = r.get("max_length")
        if lim is None:
            lim = cfg.max_tgt_len
        if isinstance(lim, bool) or not isinstance(lim, int) or lim < 1:
            return "done", bad_input("max_length must be a positive int")
        limits.append(min(lim, cfg.max_tgt_len))
    state = {
        "t0": t0,
        "reqs": reqs,
        "ids": ids.astype(np.int32),
        "lengths": np.asarray(lengths, dtype=np.int32),
        "limits": limits,
        "bucket": int(ids.shape[1]),
        "model_id": model_id,
        "cfg": cfg,
        "num_beams": num_beams,
        "length_penalty": float(length_penalty),
        "early_stopping": early_stopping,
        "min_length": min_length,
        "t_staged": time.perf_counter(),
    }
    return "staged", state


def _params_key(model_id: str, cfg) -> str:
    """EXACTLY ``map_summarize``'s params-store key for the seq2seq family,
    so colocated serving + batch ops share one HBM weight copy."""
    from agent_tpu.ops._model_common import cfg_key

    return f"{model_id}#seq2seq#{hash(cfg_key(cfg)) & 0xFFFFFFFF:08x}"


def _get_params(runtime, model_id: str, cfg):
    from agent_tpu.ops._model_common import maybe_quantize_specs
    from agent_tpu.ops.map_summarize import _build_params
    from agent_tpu.parallel.shardings import seq2seq_param_specs

    specs = maybe_quantize_specs(seq2seq_param_specs(cfg), "seq2seq", cfg)
    return runtime.get_params(
        _params_key(model_id, cfg),
        lambda: _build_params(model_id, cfg, "seq2seq"),
        specs=specs,
    )


def _get_engine(runtime, params, state, slots: int, micro_steps: int = 1):
    from agent_tpu.models import seq2seq
    from agent_tpu.models.decoding import ContinuousBatcher
    from agent_tpu.models.tokenizer import BOS_ID, EOS_ID, PAD_ID
    from agent_tpu.ops._model_common import cfg_key

    cfg = state["cfg"]
    key = (
        id(runtime), state["model_id"], cfg_key(cfg), state["bucket"],
        state["num_beams"], state["min_length"], state["length_penalty"],
        state["early_stopping"], slots, micro_steps,
    )
    engine = _ENGINES.get(key)
    if engine is None:
        engine = ContinuousBatcher(
            seq2seq.make_positional_step(params, cfg),
            seq2seq.make_cache_factory(cfg),
            slots=slots,
            vocab_size=cfg.vocab_size,
            max_tokens=cfg.max_tgt_len,
            enc_len=state["bucket"],
            d_model=cfg.d_model,
            start_id=BOS_ID, eos_id=EOS_ID, pad_id=PAD_ID,
            num_beams=state["num_beams"],
            min_length=state["min_length"],
            length_penalty=state["length_penalty"],
            early_stopping=state["early_stopping"],
            micro_steps=micro_steps,
        )
        _ENGINES[key] = engine
    return engine


def serve_admit(state: Dict[str, Any], ctx: Optional[object] = None
                ) -> Dict[str, Any]:
    """Device phase, part 1 — prefill as its own batched step, then join
    the continuous engine (between decode iterations, never inside one).
    Returns the handle the runner pumps."""
    import jax

    runtime = _runtime(ctx)
    cfg, model_id = state["cfg"], state["model_id"]
    params = _get_params(runtime, model_id, cfg)
    slots, micro_steps = _serve_knobs(ctx)
    engine = _get_engine(runtime, params, state, slots, micro_steps)
    ids, lengths = state["ids"], state["lengths"]
    B, Ls = ids.shape

    def build(Ls=Ls):
        import jax.numpy as jnp

        from agent_tpu.models import seq2seq

        def run_enc(p, i, nlen):
            mask = (jnp.arange(Ls)[None, :] < nlen[:, None]).astype(jnp.int32)
            enc = seq2seq.encode(p, i.astype(jnp.int32), mask, cfg)
            # f32 handoff like summarize_mpmd: a bf16→f32 widening is
            # lossless and the engine re-casts to its compute dtype.
            return enc.astype(jnp.float32)

        return jax.jit(run_enc)

    from agent_tpu.ops._model_common import cfg_key

    fn = runtime.compiled(
        ("serve_prefill", model_id, B, Ls, cfg_key(cfg)), build
    )
    enc = np.asarray(fn(params, ids, lengths))
    masks = (
        np.arange(Ls)[None, :] < state["lengths"][:, None]
    ).astype(np.int32)
    t_admit = time.perf_counter()
    steps0, occ0 = engine.steps_run, engine.occupancy_sum
    tickets = []
    for i, r in enumerate(state["reqs"][: len(state["limits"])]):
        tickets.append(
            engine.admit(
                enc[i], masks[i], state["limits"][i],
                data={"req_id": r["req_id"],
                      "arrived_wall": r.get("arrived_wall")},
            )
        )
    return {
        "engine": engine,
        "tickets": tickets,
        "state": state,
        "t_admit": t_admit,
        "steps0": steps0,
        "occ0": occ0,
        "device": runtime.platform,
    }


def serve_pump(handle: Dict[str, Any]) -> int:
    """One decode iteration of the handle's engine (finished sequences exit,
    backlog joins). Returns the live occupancy after the step."""
    engine = handle["engine"]
    engine.step()
    return engine.occupancy


def serve_done(handle: Dict[str, Any]) -> bool:
    return all(t.done_wall is not None for t in handle["tickets"])


def serve_collect(handle: Dict[str, Any]) -> Dict[str, Any]:
    """Handle → executed-state (the poster thread's finalize input)."""
    engine, state = handle["engine"], handle["state"]
    d_steps = max(1, engine.steps_run - handle["steps0"])
    d_occ = engine.occupancy_sum - handle["occ0"]
    return {
        "state": state,
        "tickets": handle["tickets"],
        "device": handle["device"],
        "occupancy": round(d_occ / d_steps, 3),
        "max_occupancy": engine.max_occupancy,
        "t_admit": handle["t_admit"],
        "t_device": time.perf_counter(),
    }


def execute(state: Dict[str, Any], ctx: Optional[object] = None
            ) -> Dict[str, Any]:
    """Monolithic device phase: admit, pump this job's tickets to
    completion inline (the pipelined runner interleaves instead)."""
    handle = serve_admit(state, ctx)
    handle["engine"].run(handle["tickets"])
    return serve_collect(handle)


def finalize(executed: Dict[str, Any], ctx: Optional[object] = None
             ) -> Dict[str, Any]:
    """Host phase: detokenize each ticket's emitted tokens, shape the
    per-request fan-out entries the controller's front door expects."""
    from agent_tpu.models.tokenizer import ByteTokenizer

    state = executed["state"]
    tok = ByteTokenizer()
    results: List[Dict[str, Any]] = []
    for ticket in executed["tickets"]:
        row = ticket.tokens if ticket.tokens is not None else np.array([], int)
        results.append({
            "req_id": ticket.data["req_id"],
            "summary": tok.decode([t for t in row if t > 0]),
            "tokens": int(ticket.length),
            "steps": int(ticket.steps),
            "ttft_ms": _clamp_ttft(
                ticket.first_token_wall, ticket.data.get("arrived_wall")
            ),
        })
    if ctx is not None and hasattr(ctx, "tags"):
        ctx.tags.setdefault("timings", {}).update(
            stage_ms=round((state["t_staged"] - state["t0"]) * 1e3, 3),
            device_ms=round(
                (executed["t_device"] - executed["t_admit"]) * 1e3, 3
            ),
        )
    from agent_tpu.ops._model_common import stamp_rows

    stamp_rows(ctx, len(results))
    return {
        "ok": True,
        "op": "serve_summarize",
        "device": executed["device"],
        "model": state["model_id"],
        "num_beams": state["num_beams"],
        "n_requests": len(results),
        "results": results,
        "occupancy": executed["occupancy"],
        "max_occupancy": executed["max_occupancy"],
        "elapsed_ms": (time.perf_counter() - state["t0"]) * 1000.0,
    }


@register_op("serve_summarize")
def run_summarize(payload: Any, ctx: Optional[object] = None
                  ) -> Dict[str, Any]:
    """Classic monolithic entry: stage → execute → finalize inline."""
    phase, value = stage(payload, ctx)
    if phase == "done":
        return value
    return finalize(execute(value, ctx), ctx)


# Phase hooks for the pipelined drain, plus the serving hooks its
# continuous loop drives (agent_tpu.agent.pipeline).
run_summarize.stage = stage
run_summarize.execute = execute
run_summarize.finalize = finalize
run_summarize.serve_admit = serve_admit
run_summarize.serve_pump = serve_pump
run_summarize.serve_done = serve_done
run_summarize.serve_collect = serve_collect
