"""Content-hashed prefix cache: repeated prompts skip prefill (ISSUE 16).

At serving scale the input stream is dominated by repeats — system prompts,
shared document contexts, retry storms — and every repeat re-pays the full
prefill forward. This cache keys a request's prefill output by a **chained
content hash** of ``(model version, length bucket, token-block chain)`` so a
repeated prompt's encoded rows come back from host RAM instead of the
device:

- the key chain hashes the padded token row in fixed-size token blocks
  (``h_{j+1} = sha256(h_j || block_j)``), seeded with the model's params
  key and the bucket length — two models, two quantization modes, or two
  pad buckets can never collide, and the chain shape mirrors the paged KV
  cache's block structure (a future partial-prefix variant reuses the
  per-block chain values as-is);
- values are the EXACT ``float32`` rows the prefill program produced, so a
  hit is bit-identical to the cold encode that populated it by
  construction (for this encoder-decoder family prefill == the encoder
  forward; decoder KV starts empty, so the encoder output **is** the whole
  prefill state);
- bounded LRU on both entries and bytes; hits/misses/evictions counters
  feed the ``serve_prefix_cache_*`` controller metrics and the usage
  ledger's ``cache_hit_rows`` billing line.

Process-local and device-thread-only (it is only touched inside op execute
paths, like the engine store in ``serve_infer``), so no lock.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Optional

import numpy as np

# Tokens hashed per chain link. Independent of the KV pool's block size —
# the chain only needs SOME fixed block structure; 64 keeps the link count
# low for kilobyte prompts.
HASH_BLOCK_TOKENS = 64


def prefix_key(model_version: str, ids_row: np.ndarray) -> str:
    """Chained content hash of one padded token row under one model."""
    row = np.ascontiguousarray(ids_row, dtype=np.int32)
    h = hashlib.sha256(
        f"{model_version}|L{row.shape[0]}".encode("utf-8")
    )
    for start in range(0, row.shape[0], HASH_BLOCK_TOKENS):
        block = row[start:start + HASH_BLOCK_TOKENS]
        h = hashlib.sha256(h.digest() + block.tobytes())
    return h.hexdigest()


class PrefixCache:
    """Bounded LRU of prefill rows keyed by :func:`prefix_key`."""

    def __init__(
        self, max_entries: int = 512, max_bytes: int = 256 * 2 ** 20
    ) -> None:
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = max(1, int(max_bytes))
        self._store: "OrderedDict[str, np.ndarray]" = OrderedDict()
        self.bytes_used = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._store)

    def get(self, key: str) -> Optional[np.ndarray]:
        row = self._store.get(key)
        if row is None:
            self.misses += 1
            return None
        self._store.move_to_end(key)
        self.hits += 1
        return row

    def put(self, key: str, row: np.ndarray) -> None:
        if key in self._store:
            self._store.move_to_end(key)
            return
        row = np.ascontiguousarray(row, dtype=np.float32)
        if row.nbytes > self.max_bytes:
            return  # one row larger than the whole budget: never cacheable
        self._store[key] = row
        self.bytes_used += row.nbytes
        while (
            len(self._store) > self.max_entries
            or self.bytes_used > self.max_bytes
        ):
            _, victim = self._store.popitem(last=False)
            self.bytes_used -= victim.nbytes
            self.evictions += 1

    def clear(self) -> None:
        self._store.clear()
        self.bytes_used = 0

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "entries": len(self._store),
            "bytes": self.bytes_used,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "hit_rate": round(self.hits / total, 4) if total else 0.0,
        }
