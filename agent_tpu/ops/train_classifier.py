"""Train a classifier inside the swarm and emit a servable artifact.

The reference never trains — it *serves* pretrained immutable artifacts (a
compiled ``.tflite`` at a well-known path, reference ``_tpu_runtime.py:23-31``;
HF hub weights, reference ``ops/map_summarize.py:29-32``). This op closes the
framework's model lifecycle: a shard-addressed labeled CSV (or inline rows)
goes in, a ``.npz`` checkpoint comes out at ``output_path``, and
``map_classify_tpu`` serves it via ``model_path`` with the ``model_config``
echoed in this op's result — train → checkpoint → serve without leaving the
lease protocol.

Training is the sharded step from ``models/train.py``: one jitted
forward+backward+adamw update over the runtime mesh, batch over ``dp``,
params Megatron-sharded over ``tp`` when the mesh has one (same specs the
serving path uses, so anything trainable here is servable there).

Payload:

- rows: ``texts`` + ``labels`` lists, or ``source_uri`` (+ optional
  ``start_row``/``shard_size``, default = the whole file) with ``text_field``
  (default ``"text"``) / ``label_field`` (default ``"label"``).
- ``output_path`` (required): where the ``.npz`` artifact lands.
- ``model_config``: EncoderConfig overrides; ``n_classes`` defaults to the
  number of distinct labels.
- knobs: ``epochs`` (3), ``batch_size`` (64, rounded up to a dp multiple),
  ``learning_rate`` (1e-3), ``eval_fraction`` (0.2), ``seed`` (0),
  ``init_from`` (model id or ``.npz`` to warm-start).

Result: ``{ok, op, output_path, n_train, n_eval, n_steps, first_epoch_loss,
last_epoch_loss, eval_accuracy, label_names?, model_config, device}``.
String labels map to ids by sorted order; the mapping ships in the result and
in a ``<output_path>.labels.json`` sidecar.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from agent_tpu.ops import register_op
from agent_tpu.utils.errors import bad_input

DEFAULT_EPOCHS = 3
DEFAULT_BATCH = 64
DEFAULT_LR = 1e-3
DEFAULT_EVAL_FRACTION = 0.2


def _collect_rows(payload: Dict[str, Any]) -> Tuple[List[str], List[Any]]:
    """Payload → (texts, raw_labels); ValueError on malformed payloads
    (→ soft bad_input), Runtime/OSError on shard integrity (→ failed task)."""
    texts = payload.get("texts")
    labels = payload.get("labels")
    if texts is not None or labels is not None:
        if (
            not isinstance(texts, list)
            or not isinstance(labels, list)
            or not texts
            or len(texts) != len(labels)
            or not all(isinstance(t, str) and t for t in texts)
        ):
            raise ValueError(
                "texts and labels must be equal-length non-empty lists"
            )
        return texts, labels
    if "source_uri" not in payload:
        raise ValueError(
            "payload requires 'texts'+'labels' or 'source_uri' CSV addressing"
        )
    from agent_tpu.data.csv_index import (
        count_rows,
        read_shard,
        resolve_shard_payload,
    )

    text_field = payload.get("text_field", "text")
    label_field = payload.get("label_field", "label")
    for key, val in (("text_field", text_field), ("label_field", label_field)):
        if not isinstance(val, str) or not val:
            raise ValueError(f"{key} must be a non-empty string")
    p = dict(payload)
    if "shard_size" not in p:
        # Training defaults to the whole file, not the 100-row shard default.
        path, start, _ = resolve_shard_payload({**p, "shard_size": 1})
        p["shard_size"] = max(1, count_rows(path) - start)
    path, start, size = resolve_shard_payload(p)
    # One parse serves both columns (read_shard_column would re-read the
    # whole shard per field — twice the IO on a whole-file train set). Error
    # contract matches it: integrity problems raise RuntimeError → the task
    # FAILS and retries, never a soft result that silently trains on nothing.
    rows = read_shard(path, start, size)
    if not rows:
        raise RuntimeError(f"shard [{start}, {start + size}) of {path!r} is empty")
    for field in (text_field, label_field):
        missing = sum(1 for r in rows if field not in r)
        if missing:
            raise RuntimeError(
                f"column {field!r} missing from {missing} rows of {path!r}"
            )
    return [r[text_field] for r in rows], [r[label_field] for r in rows]


def _map_labels(raw: List[Any]) -> Tuple[np.ndarray, Optional[List[str]]]:
    """Labels → int ids. All-int labels pass through; strings map by sorted
    order (returned as label_names, index = class id)."""
    try:
        ids = [int(v) for v in raw]
        if ids and min(ids) >= 0 and all(
            str(v).strip().lstrip("+").isdigit() for v in raw
        ):
            return np.asarray(ids, dtype=np.int32), None
    except (TypeError, ValueError):
        pass
    names = sorted({str(v) for v in raw})
    index = {n: i for i, n in enumerate(names)}
    return np.asarray([index[str(v)] for v in raw], dtype=np.int32), names


@register_op("train_classifier")
def run(payload: Any, ctx: Optional[object] = None) -> Dict[str, Any]:
    t0 = time.perf_counter()
    if not isinstance(payload, dict):
        return bad_input("payload must be a dict")
    output_path = payload.get("output_path")
    if not isinstance(output_path, str) or not output_path.endswith(".npz"):
        return bad_input("output_path is required and must end in .npz")

    epochs = payload.get("epochs", DEFAULT_EPOCHS)
    batch_size = payload.get("batch_size", DEFAULT_BATCH)
    lr = payload.get("learning_rate", DEFAULT_LR)
    eval_fraction = payload.get("eval_fraction", DEFAULT_EVAL_FRACTION)
    seed = payload.get("seed", 0)
    for name, v, lo in (("epochs", epochs, 1), ("batch_size", batch_size, 1)):
        if isinstance(v, bool) or not isinstance(v, int) or v < lo:
            return bad_input(f"{name} must be an int >= {lo}")
    if not isinstance(lr, (int, float)) or isinstance(lr, bool) or lr <= 0:
        return bad_input("learning_rate must be a positive number")
    if not isinstance(eval_fraction, (int, float)) or isinstance(eval_fraction, bool) \
            or not 0 <= eval_fraction < 1:
        return bad_input("eval_fraction must be in [0, 1)")

    init_from = payload.get("init_from")
    if init_from is not None and (
        not isinstance(init_from, str) or not init_from
    ):
        return bad_input("init_from must be a non-empty string")
    if isinstance(init_from, str) and init_from.endswith(".npz"):
        import os

        if not os.path.exists(init_from):
            # Silently training from scratch on a typo'd warm-start path
            # would ship a model that never saw the intended weights.
            return bad_input(f"init_from checkpoint not found: {init_from!r}")

    try:
        texts, raw_labels = _collect_rows(payload)
    except ValueError as exc:
        return bad_input(str(exc))
    labels, label_names = _map_labels(raw_labels)
    n_labels = int(labels.max()) + 1 if labels.size else 2

    from agent_tpu.models.encoder import EncoderConfig
    from agent_tpu.ops._model_common import config_from_payload

    cfg = config_from_payload(payload, EncoderConfig)
    overrides = payload.get("model_config") or {}
    if "n_classes" not in overrides:
        cfg = cfg.scaled(n_classes=max(2, n_labels))
    if labels.size and int(labels.max()) >= cfg.n_classes:
        return bad_input(
            f"label id {int(labels.max())} >= n_classes {cfg.n_classes}"
        )
    # MoE configs train for real: cross_entropy_loss adds the Switch
    # load-balancing aux term (models/train.py MOE_AUX_WEIGHT) so the
    # router learns balanced routing. The pp schedule, by contrast, is not
    # wired into the train step — reject rather than silently train dense.
    if cfg.pp > 1:
        return bad_input("train_classifier does not support pp configs")
    if cfg.moe_experts > 0 and cfg.quant != "none":
        return bad_input(
            f"MoE training does not support quant={cfg.quant}"
        )

    if ctx is not None and getattr(ctx, "require_runtime", None):
        runtime = ctx.require_runtime()
    else:
        from agent_tpu.runtime.runtime import get_runtime

        runtime = get_runtime()

    import jax
    import optax

    from agent_tpu.models import encoder, train
    from agent_tpu.models.tokenizer import DEFAULT_BUCKETS, byte_encode_pad
    from agent_tpu.parallel import shardings

    # One static shape for the whole run: the smallest bucket covering the
    # longest row (capped by the model), every batch padded to it.
    buckets = [b for b in DEFAULT_BUCKETS if b <= cfg.max_len] or [cfg.max_len]
    ids_all, len_all = byte_encode_pad(texts, buckets=buckets, max_len_cap=cfg.max_len)
    L = ids_all.shape[1]
    mask_all = (np.arange(L)[None, :] < len_all[:, None]).astype(np.int32)

    # Deterministic holdout: every round(1/f)-th row evaluates, the rest train.
    n = len(texts)
    idx = np.arange(n)
    if eval_fraction > 0 and n >= 5:
        stride = max(2, int(round(1.0 / eval_fraction)))
        eval_idx = idx[::stride]
        train_idx = np.setdiff1d(idx, eval_idx)
    else:
        eval_idx = np.empty(0, dtype=np.int64)
        train_idx = idx
    if train_idx.size == 0:
        return bad_input("no training rows after eval split")

    dp = runtime.axis_size("dp")
    B = -(-batch_size // dp) * dp  # round up to a dp multiple
    rng = np.random.default_rng(seed)

    # Mutable training weights bypass the (immutable) params store: placed
    # directly with the same sanitized specs the serving path uses, so a
    # tp-sharded mesh trains sharded. Size-1 axes make the specs replicated.
    host_params = _init_params(payload, cfg)
    specs = shardings.sanitize_specs(
        runtime.mesh, host_params, shardings.encoder_param_specs(cfg)
    )
    params = train.place_sharded(runtime, host_params, specs)
    # Differentiable attention from the runtime: the Pallas flash pair on
    # TPU, so long-context fine-tunes (buckets ≥ 2048) never materialize
    # [B, H, L, L] score matrices in the backward.
    init_state, step = train.make_train_step(
        cfg, optax.adamw(float(lr)), attn_fn=runtime.train_attention_fn()
    )
    opt_state = init_state(params)

    first_epoch_loss = last_epoch_loss = None
    n_steps = 0
    for epoch in range(epochs):
        order = rng.permutation(train_idx)
        # Tile the tail so every step sees a full [B, L] batch (static shape);
        # np.resize cycles the array, so n_train < B still fills a batch.
        order = np.resize(order, -(-order.size // B) * B)
        losses = []
        for s in range(0, order.size, B):
            take = order[s : s + B]
            params, opt_state, loss = step(
                params,
                opt_state,
                runtime.put_batch(ids_all[take]),
                runtime.put_batch(mask_all[take]),
                runtime.put_batch(labels[take]),
            )
            losses.append(loss)
            n_steps += 1
        epoch_loss = float(np.mean([float(x) for x in losses]))
        if first_epoch_loss is None:
            first_epoch_loss = epoch_loss
        last_epoch_loss = epoch_loss

    # Holdout accuracy through the same forward the serving path compiles.
    eval_accuracy = None
    if eval_idx.size:
        take = np.resize(eval_idx, -(-eval_idx.size // dp) * dp)
        logits = jax.jit(
            lambda p, i, m: encoder.forward(p, i, m, cfg)
        )(params, runtime.put_batch(ids_all[take]), runtime.put_batch(mask_all[take]))
        pred = np.asarray(jax.numpy.argmax(logits, axis=-1))[: eval_idx.size]
        eval_accuracy = float(np.mean(pred == labels[eval_idx]))

    from agent_tpu.models import checkpoint

    checkpoint.save_npz(params, output_path)
    if label_names is not None:
        with open(output_path + ".labels.json", "w", encoding="utf-8") as f:
            json.dump(label_names, f)

    from agent_tpu.ops._model_common import cfg_key

    out: Dict[str, Any] = {
        "ok": True,
        "op": "train_classifier",
        "output_path": output_path,
        "n_train": int(train_idx.size),
        "n_eval": int(eval_idx.size),
        "n_steps": n_steps,
        "first_epoch_loss": first_epoch_loss,
        "last_epoch_loss": last_epoch_loss,
        "eval_accuracy": eval_accuracy,
        # Serve with: {"model_path": output_path, "model_config": this}.
        "model_config": dict(cfg_key(cfg)),
        "device": runtime.platform,
        "elapsed_ms": (time.perf_counter() - t0) * 1000.0,
    }
    if label_names is not None:
        out["label_names"] = label_names
    return out


def _init_params(payload: Dict[str, Any], cfg):
    """Fresh or warm-started initial weights (``init_from`` path existence is
    validated up front in ``run`` — a missing warm-start must error, not
    silently train from scratch)."""
    from agent_tpu.models import encoder

    init_from = payload.get("init_from")
    if isinstance(init_from, str) and init_from:
        if init_from.endswith(".npz"):
            return encoder.load_npz(init_from, cfg)
        return encoder.init_params(cfg, model_id=init_from)
    return encoder.init_params(cfg, model_id=f"train-seed:{payload.get('seed', 0)}")
