"""Version-compat shims for the narrow band of jax APIs that moved.

``shard_map`` graduated from ``jax.experimental.shard_map`` to ``jax.shard_map``
(and its replication-check kwarg was renamed ``check_rep`` → ``check_vma``)
across the jax versions this framework must run on — the pinned TPU image on
one end, CI's resolver-picked wheel on the other. Every internal call site
imports the ONE wrapper below instead of touching ``jax.shard_map`` directly,
so a jax bump (either direction) is a one-file change and an old wheel fails
at import time with a clear error rather than ``AttributeError`` mid-trace.
"""

from __future__ import annotations

import jax
from jax import lax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` where it exists, else the ``jax.experimental``
    original with ``check_vma`` mapped onto its older ``check_rep`` name
    (same role: the replication/varying checker toggle — and on old jax
    ``check_rep=False`` is REQUIRED for pallas-containing bodies, whose
    ``pallas_call`` has no replication rule). ``check_vma=None`` means
    "library default" on either path.

    Legacy-jax caveat that lives in :func:`stack_leaves`, not here: a
    traced ``jnp.stack`` feeding a shard_map operand sharded over the
    stacked dim miscompiles under an outer jit regardless of the
    ``check_rep`` setting — stage such operands via ``stack_leaves``."""
    if hasattr(jax, "shard_map"):
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def shape_dtype_struct(shape, dtype, vma=None):
    """``jax.ShapeDtypeStruct`` with the vma annotation where the kwarg
    exists; silently dropped otherwise (pre-vma jax has no varying-axes
    checking for the annotation to feed)."""
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)


def stack_leaves(leaves):
    """``jnp.stack`` for leaves that feed a ``shard_map`` operand sharded
    over the stacked dim (the pp pipeline's staged weights). On legacy jax
    the GSPMD partitioner miscompiles a traced concatenate flowing into a
    ``P("pp")`` shard_map operand under an outer jit — the pp forward came
    back wrong by O(1) (reproduced minimally: ``jnp.stack`` of traced
    leaves → shard_map in_spec P("pp") → wrong; same leaves staged via
    ``zeros().at[i].set`` → correct). The dynamic-update-slice formulation
    partitions correctly on both paths, so legacy jax takes it."""
    import jax.numpy as jnp

    if hasattr(jax, "shard_map"):
        return jnp.stack(leaves)
    out = jnp.zeros((len(leaves),) + leaves[0].shape, leaves[0].dtype)
    for i, leaf in enumerate(leaves):
        out = out.at[i].set(leaf)
    return out


def pcast_varying(x, axis_name):
    """``lax.pcast(..., to="varying")`` where the varying-manual-axes (vma)
    type system exists; identity otherwise. Pre-vma jax has no per-axis
    varying/invariant distinction inside ``shard_map``, so marking a carry
    varying is simply not needed there — the cast is a type annotation, not
    a data movement, on both paths."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name=axis_name, to="varying")
    return x
