"""Shared utilities: structured logging, rate limiting, tracing spans,
errors, retry/backoff policy."""

from agent_tpu.utils.logging import RateLimiter, log
from agent_tpu.utils.errors import OpError, structured_error
from agent_tpu.utils.retry import (
    RetryPolicy,
    classify_error,
    classify_http,
    jittered,
)

__all__ = [
    "RateLimiter",
    "log",
    "OpError",
    "structured_error",
    "RetryPolicy",
    "classify_error",
    "classify_http",
    "jittered",
]
