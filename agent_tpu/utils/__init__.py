"""Shared utilities: structured logging, rate limiting, tracing spans, errors."""

from agent_tpu.utils.logging import RateLimiter, log
from agent_tpu.utils.errors import OpError, structured_error

__all__ = ["RateLimiter", "log", "OpError", "structured_error"]
