"""Shared per-op span accounting over controller result bodies.

One definition of "device-side span" for drain reports (bench.py and
scripts/drain_at_scale.py): per-shard dispatch time (``timings.device_ms``)
plus the deferred device→host fetch wait (``timings.fetch_ms``, paid on the
pipeline's poster thread). Results without phase timings fall back to their
``elapsed_ms``. Under pipeline overlap these spans can over- or under-count
true device busy time — wall-clock throughput is the primary metric; spans
are the per-op attribution signal.
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping


def result_op(result: Mapping) -> str | None:
    """The op a result body belongs to. Every op now stamps ``"op"`` into
    its result (ISSUE 2 satellite); the summaries/sink sniffing below is
    kept ONLY as a fallback for old bodies (pre-stamp journals, agents a
    version behind) and must not grow new cases — new attribution should
    come from the explicit key or from scraping ``/v1/metrics``
    (``agent_tpu.obs.scrape``)."""
    op = result.get("op")
    if op:
        return op
    if (
        "summaries" in result
        or "summary" in result
        or "map_summarize" in str(result.get("output_path", ""))
    ):
        return "map_summarize"
    return None


def op_span_ms(results: Iterable[Mapping], ops: Iterable[str]) -> Dict[str, float]:
    """Sum per-op spans (milliseconds) over result bodies."""
    spans = {op: 0.0 for op in ops}
    for r in results:
        if not isinstance(r, Mapping):
            continue
        op = result_op(r)
        if op not in spans:
            continue
        t = r.get("timings", {})
        if t.get("device_ms") is not None:
            spans[op] += float(t.get("device_ms", 0.0)) + float(
                t.get("fetch_ms", 0.0)
            )
        else:
            spans[op] += float(r.get("elapsed_ms", 0.0))
    return spans
