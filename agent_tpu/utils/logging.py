"""Structured stdout logging with per-key rate limiting.

The reference logs with a ``[agent-tpu-v1]`` prefix, ``flush=True`` (reference
``app.py:255,311-315``; ``PYTHONUNBUFFERED=1`` in its Dockerfile), and rate-limits
error logs per category key so a dead controller doesn't flood stdout (reference
``app.py:66-71``, keys like ``lease``/``result``/``exec`` at ``:261,274,308,313``).
Both behaviors are kept; the prefix is bumped for the new framework.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, Optional

PREFIX = "[agent-tpu]"


def log(msg: str, **fields: Any) -> None:
    """Print a prefixed, flushed log line; keyword fields render as compact JSON."""
    if fields:
        try:
            tail = " " + json.dumps(fields, sort_keys=True, default=str)
        except (TypeError, ValueError):
            tail = " " + repr(fields)
    else:
        tail = ""
    print(f"{PREFIX} {msg}{tail}", flush=True)


class RateLimiter:
    """Per-key 'at most once every N seconds' gate (reference ``app.py:66-71``)."""

    def __init__(self, every_sec: float = 10.0, clock=time.monotonic) -> None:
        self.every_sec = float(every_sec)
        self._clock = clock
        self._last: Dict[str, float] = {}

    def ready(self, key: str) -> bool:
        now = self._clock()
        last = self._last.get(key)
        if last is not None and (now - last) < self.every_sec:
            return False
        self._last[key] = now
        return True

    def log(self, key: str, msg: str, **fields: Any) -> bool:
        """Log if the key's window has elapsed; returns whether it logged."""
        if not self.ready(key):
            return False
        log(f"{key}: {msg}", **fields)
        return True
