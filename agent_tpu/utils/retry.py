"""Retry/backoff core — the one place fault-handling policy lives (ISSUE 3).

Two halves, shared by the agent loops, the result spool, and the controller:

- **Classification.** A failure is either ``transient`` (worth retrying:
  transport errors, HTTP 5xx, 429) or ``permanent`` (no retry can fix it:
  other 4xx, ``UnknownOp``, malformed tasks). The controller uses the same
  table to decide whether a failed job gets its retry budget or sticks
  ``failed`` immediately, so agent-side and controller-side policy can never
  drift.
- **Backoff.** ``RetryPolicy`` + ``RetryState`` implement capped exponential
  backoff with *decorrelated jitter* (the AWS-architecture variant: each
  sleep is uniform in ``[base, prev * multiplier]``, capped) — a restarted
  fleet decorrelates instead of thundering back in lockstep. ``jittered``
  is the lighter helper for spreading fixed sleeps (idle polls).

Policy knobs ride the env surface (``RETRY_BASE_SEC``, ``RETRY_MAX_SEC``,
``RETRY_DEADLINE_SEC`` — see ``config.AgentConfig``); everything here is
dependency-free and usable from both sides of the wire.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

TRANSIENT = "transient"
PERMANENT = "permanent"

# Structured-error ``type`` names (utils.errors.structured_error) that no
# retry can fix: re-running the same task yields the same failure. Anything
# not listed is assumed transient — device flakes, OOMs under contention and
# transport hiccups surface as RuntimeError/OSError subtypes, and wrongly
# retrying a permanent error once is cheaper than wrongly killing a
# recoverable job.
PERMANENT_ERROR_TYPES = frozenset(
    {"UnknownOp", "ValueError", "TypeError", "KeyError", "OpError"}
)


def classify_http(status: Any) -> str:
    """HTTP status → ``transient`` | ``permanent``.

    Status 0 is the agent's transport-error sentinel (could not reach the
    controller at all) — transient by definition. 429 is explicit backpressure
    and 5xx is a server-side fault: both transient. Remaining 4xx mean the
    request itself is wrong; resending the same bytes cannot succeed.
    """
    try:
        s = int(status)
    except (TypeError, ValueError):
        return TRANSIENT
    if s == 429:
        return TRANSIENT
    if 400 <= s < 500:
        return PERMANENT
    return TRANSIENT


def classify_error(error: Any) -> str:
    """Structured error (dict with ``type``, or a bare type name) →
    ``transient`` | ``permanent``."""
    name = error.get("type") if isinstance(error, dict) else error
    if isinstance(name, str) and name in PERMANENT_ERROR_TYPES:
        return PERMANENT
    return TRANSIENT


def jittered(
    value: float, frac: float = 0.25, rng: Optional[random.Random] = None
) -> float:
    """``value`` ± ``frac`` uniform jitter, floored at 0 — spreads fixed
    sleeps (idle polls) so a fleet restarted together doesn't long-poll in
    lockstep."""
    if value <= 0:
        return 0.0
    r = (rng or random).uniform(-frac, frac)
    return max(0.0, value * (1.0 + r))


@dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with decorrelated jitter.

    ``deadline_sec`` is the overall budget for one logical operation (0 =
    unbounded); ``RetryState.expired()`` reports when it's spent — the caller
    decides what giving up means (the spool drops the entry, a lease loop
    just keeps polling).
    """

    base_sec: float = 0.5
    max_sec: float = 30.0
    multiplier: float = 3.0
    deadline_sec: float = 0.0

    def start(
        self,
        rng: Optional[random.Random] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> "RetryState":
        return RetryState(self, rng=rng, clock=clock)


class RetryState:
    """Mutable per-operation backoff state (one per thing being retried)."""

    def __init__(
        self,
        policy: RetryPolicy,
        rng: Optional[random.Random] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.policy = policy
        self._rng = rng or random.Random()
        self._clock = clock
        self._prev = 0.0
        self._started: Optional[float] = None
        self.attempts = 0

    def next_backoff(self) -> float:
        """The next sleep: uniform in ``[base, prev * multiplier]``, capped at
        ``max_sec``. The first call returns something in ``[base, base *
        multiplier]``; repeated failures grow toward the cap without ever
        synchronizing two independent retriers."""
        p = self.policy
        if self._started is None:
            self._started = self._clock()
        self.attempts += 1
        prev = self._prev if self._prev > 0 else p.base_sec
        hi = max(p.base_sec, prev * p.multiplier)
        sleep = min(p.max_sec, self._rng.uniform(p.base_sec, hi))
        self._prev = sleep
        return sleep

    def expired(self) -> bool:
        """True once the overall deadline is spent (never before the first
        ``next_backoff``; a policy without a deadline never expires)."""
        return (
            self.policy.deadline_sec > 0
            and self._started is not None
            and self._clock() - self._started >= self.policy.deadline_sec
        )

    def reset(self) -> None:
        """Forget the failure streak (call on success)."""
        self._prev = 0.0
        self._started = None
        self.attempts = 0
