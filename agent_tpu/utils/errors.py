"""Error contracts.

Two error surfaces, matching the reference's split:

- Ops return ``{"ok": False, "error": "..."}`` for *bad input* instead of raising
  (reference ``ops/csv_shard.py:46-76``, ``ops/map_tokenize.py:25-32``).
- The agent loop converts *raised* exceptions into a structured
  ``{"type", "message", "trace"}`` error shipped with a ``failed`` result
  (reference ``app.py:288-294``).
"""

from __future__ import annotations

import traceback
from typing import Any, Dict


class OpError(Exception):
    """Raised by ops for contract violations that should fail the task."""


def structured_error(exc: BaseException) -> Dict[str, Any]:
    """Exception → the wire error shape the controller expects (ref app.py:290-294)."""
    return {
        "type": type(exc).__name__,
        "message": str(exc),
        "trace": "".join(
            traceback.format_exception(type(exc), exc, exc.__traceback__)
        )[-4000:],
    }


def bad_input(message: str, **extra: Any) -> Dict[str, Any]:
    """The ops-level soft-failure shape (ref ops/map_tokenize.py:25-32)."""
    out: Dict[str, Any] = {"ok": False, "error": message}
    out.update(extra)
    return out
