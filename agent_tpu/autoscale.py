"""Elastic fleet autoscaler — the loop that *changes fleet size under load*
(ISSUE 10 tentpole b; ROADMAP item 4).

The control plane can already crash agents (chaos), judge the fleet
(``GET /v1/health``), and bill it (``/v1/usage``) — this module closes the
loop: it consumes the signal vector health already exports (queue depth and
per-tier pressure, starvation age, SLO burn states, per-agent duty cycle,
staleness) and spawns or retires fleet members to match the offered load.

Design:

- **Signals, not bespoke probes.** :func:`read_signals` is a pure projection
  of the ``/v1/health`` body (in-process ``Controller.health_json()`` or an
  HTTP scrape — the autoscaler cannot tell the difference).
- **Hysteresis + cooldown, never flap.** Scale-up triggers on queue pressure
  per live agent, SLO burn with work queued, or starvation age; scale-down
  requires ``down_idle_evals`` *consecutive* idle judgments (queue empty and
  every live agent's duty cycle under ``down_max_duty``) and honors separate
  up/down cooldowns. Capacity *replacement* after a reclaim (live < min, or
  live below the last desired size because a member died) bypasses the up
  cooldown — repairing a spot reclaim is not a scaling decision.
- **Graceful retirement.** Scale-down retires members through the drain
  protocol (``Agent.request_drain`` / SIGTERM): the member stops asking for
  work, finishes or releases its in-flight lease, flushes its spool and
  final metrics (the lease poll carries ``draining: true`` so
  ``/v1/status`` marks it), then exits. The scheduler never places on it
  again because a draining member never asks — the pull protocol is the
  fence.
- **Pluggable actuation.** A :class:`FleetDriver` owns member lifecycles:
  :class:`ProcessFleetDriver` spawns real pinned agent processes via
  ``agent/fleet.py``; :class:`ThreadFleetDriver` runs in-process ``Agent``
  loops for deterministic soaks and tests (``scripts/elastic_soak.py``).

Observability (the new ``autoscale_*`` / ``fleet_size`` families): desired
vs actual vs draining member counts, every decision with its reason, and
scale-event counters — wired into whatever registry the caller passes
(the soak passes the controller's, so ``/v1/metrics`` serves them).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from agent_tpu.config import AutoscaleConfig
from agent_tpu.obs.metrics import MetricsRegistry
from agent_tpu.utils.logging import log

# Decision actions (the `action` label of autoscale_decisions_total).
UP = "up"
DOWN = "down"
HOLD = "hold"
REPLACE = "replace"


@dataclass(frozen=True)
class Signals:
    """The autoscaler's view of one ``/v1/health`` body."""

    queue_depth: int = 0
    starvation_age_sec: Optional[float] = None
    # True when any SLO objective is in warn/page (burning budget).
    slo_burning: bool = False
    verdict: str = "ok"
    # Live = polled recently AND not draining; duty cycles are the live
    # members' rolling device_duty_cycle gauges (None = no data yet).
    live_agents: int = 0
    draining_agents: int = 0
    max_duty: Optional[float] = None
    # Non-terminal job count (pending + leased): the "work still exists"
    # signal that keeps scale-down honest while leases are in flight.
    active_jobs: int = 0
    healthy: bool = True


def read_signals(health: Optional[Dict[str, Any]]) -> Signals:
    """Project a ``/v1/health`` body into :class:`Signals`. ``None`` (an
    unreachable controller) yields ``healthy=False`` — the loop holds
    rather than acting blind."""
    if not isinstance(health, dict):
        return Signals(healthy=False)
    queue = health.get("queue") or {}
    slo = health.get("slo") or {}
    burning = any(
        obj.get("state") in ("warn", "page")
        for obj in slo.get("objectives") or []
    )
    live = 0
    draining = 0
    duties: List[float] = []
    for row in (health.get("agents") or {}).values():
        if row.get("draining"):
            draining += 1
            continue
        if row.get("stale"):
            continue
        live += 1
        duty = row.get("duty_cycle")
        if isinstance(duty, (int, float)):
            duties.append(float(duty))
    counts = health.get("counts") or {}
    active = int(counts.get("pending", 0)) + int(counts.get("leased", 0))
    return Signals(
        queue_depth=int(queue.get("depth") or 0),
        starvation_age_sec=queue.get("starvation_age_sec"),
        slo_burning=burning,
        verdict=str(health.get("verdict", "ok")),
        live_agents=live,
        draining_agents=draining,
        max_duty=max(duties) if duties else None,
        active_jobs=active,
        healthy=True,
    )


@dataclass(frozen=True)
class Decision:
    action: str
    n: int = 0
    reason: str = ""


class FleetDriver:
    """Actuation interface: member lifecycles. ``size()`` counts live
    (non-retired) members — the capacity the controller can lease to;
    ``spawn(n)`` adds members; ``retire(n)`` gracefully drains the
    driver's choice of ``n`` members and returns their names."""

    def size(self) -> int:
        raise NotImplementedError

    def spawn(self, n: int) -> List[str]:
        raise NotImplementedError

    def retire(self, n: int) -> List[str]:
        raise NotImplementedError


class Autoscaler:
    """The control loop. ``health_fn`` returns a ``/v1/health`` body (dict)
    or None; ``driver`` actuates. One ``step()`` = read → decide → act;
    ``run()`` loops until the stop event fires."""

    def __init__(
        self,
        driver: FleetDriver,
        health_fn: Callable[[], Optional[Dict[str, Any]]],
        config: Optional[AutoscaleConfig] = None,
        registry: Optional[MetricsRegistry] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.driver = driver
        self.health_fn = health_fn
        self.config = config or AutoscaleConfig()
        self._clock = clock
        self._idle_evals = 0
        self._last_up = float("-inf")
        self._last_scale = float("-inf")  # either direction (down cooldown)
        # The size the last decision wanted — live members below it mean a
        # member died (reclaim) and replacement is repair, not scaling.
        self.desired = max(self.config.min_agents, 0)
        self.scale_ups = 0
        self.scale_downs = 0
        self.replacements = 0
        m = registry if registry is not None else MetricsRegistry()
        self.metrics = m
        self._g_size = m.gauge(
            "fleet_size",
            "Elastic fleet membership by state "
            "(desired/actual/draining)", ("state",))
        self._m_decisions = m.counter(
            "autoscale_decisions_total",
            "Autoscaler decisions by action and reason",
            ("action", "reason"))
        self._m_events = m.counter(
            "autoscale_scale_events_total",
            "Members actually added/retired", ("direction",))
        self._g_size.set(self.desired, state="desired")

    # ---- decision (pure given Signals + internal hysteresis state) ----

    def decide(self, sig: Signals, now: Optional[float] = None) -> Decision:
        cfg = self.config
        if now is None:
            now = self._clock()
        if not sig.healthy:
            self._idle_evals = 0
            return Decision(HOLD, reason="health_unreachable")
        actual = self.driver.size()
        # Repair before policy: capacity the controller believes in but the
        # driver lost (spot reclaim, hard kill, crashed member) comes back
        # immediately — a reclaim must never silently shrink the fleet
        # below what the load earned.
        floor = max(cfg.min_agents, min(self.desired, cfg.max_agents))
        if actual < floor:
            self._idle_evals = 0
            return Decision(
                REPLACE, n=floor - actual,
                reason="below_min" if actual < cfg.min_agents
                else "capacity_lost",
            )
        pressure = sig.queue_depth / max(1, actual)
        starving = (
            sig.starvation_age_sec is not None
            and sig.starvation_age_sec > cfg.up_starvation_sec
        )
        want_up = (
            pressure > cfg.up_queue_per_agent
            or (sig.slo_burning and sig.queue_depth > 0)
            or starving
        )
        if want_up:
            self._idle_evals = 0
            if actual >= cfg.max_agents:
                return Decision(HOLD, reason="at_max")
            if now - self._last_up < cfg.up_cooldown_sec:
                return Decision(HOLD, reason="up_cooldown")
            reason = (
                "queue_pressure" if pressure > cfg.up_queue_per_agent
                else ("slo_burn" if sig.slo_burning else "starvation")
            )
            n = min(cfg.step_up, cfg.max_agents - actual)
            return Decision(UP, n=n, reason=reason)
        idle = (
            sig.queue_depth == 0
            and sig.active_jobs == 0
            and (sig.max_duty is None or sig.max_duty < cfg.down_max_duty)
        )
        if not idle:
            self._idle_evals = 0
            return Decision(HOLD, reason="busy")
        self._idle_evals += 1
        if actual <= cfg.min_agents:
            return Decision(HOLD, reason="at_min")
        if self._idle_evals < cfg.down_idle_evals:
            return Decision(HOLD, reason="idle_confirming")
        if now - self._last_scale < cfg.down_cooldown_sec:
            return Decision(HOLD, reason="down_cooldown")
        n = min(cfg.step_down, actual - cfg.min_agents)
        return Decision(DOWN, n=n, reason="idle")

    # ---- actuation ----

    def apply(self, decision: Decision, now: Optional[float] = None) -> None:
        if now is None:
            now = self._clock()
        self._m_decisions.inc(action=decision.action, reason=decision.reason)
        if decision.action in (UP, REPLACE) and decision.n > 0:
            names = self.driver.spawn(decision.n)
            self._m_events.inc(len(names), direction="up")
            if decision.action == UP:
                self.scale_ups += 1
                self._last_up = now
                self._last_scale = now
                self.desired = min(
                    self.config.max_agents, self.driver.size()
                )
            else:
                self.replacements += 1
            log(
                "autoscale: spawned members", n=len(names),
                reason=decision.reason, fleet=self.driver.size(),
            )
        elif decision.action == DOWN and decision.n > 0:
            names = self.driver.retire(decision.n)
            self._m_events.inc(len(names), direction="down")
            self.scale_downs += 1
            self._last_scale = now
            self._idle_evals = 0
            self.desired = max(self.config.min_agents, self.driver.size())
            log(
                "autoscale: retired members", names=names,
                reason=decision.reason, fleet=self.driver.size(),
            )

    def step(self) -> Decision:
        sig = read_signals(self.health_fn())
        now = self._clock()
        decision = self.decide(sig, now)
        self.apply(decision, now)
        self._g_size.set(self.desired, state="desired")
        self._g_size.set(self.driver.size(), state="actual")
        self._g_size.set(sig.draining_agents, state="draining")
        return decision

    def run(
        self,
        stop: threading.Event,
        interval_sec: Optional[float] = None,
    ) -> None:
        interval = (
            self.config.interval_sec if interval_sec is None
            else max(0.05, float(interval_sec))
        )
        while not stop.wait(interval):
            try:
                self.step()
            except Exception as exc:  # noqa: BLE001 — the loop must outlive
                # one bad evaluation; a dead autoscaler strands the fleet.
                log("autoscale step failed", error=str(exc)[:200])


# ---- drivers ----

class ThreadFleetDriver(FleetDriver):
    """In-process members: each ``spawn`` builds an ``Agent`` via
    ``agent_factory(name)`` and runs its real loop on a daemon thread;
    ``retire`` requests the drain path (``Agent.request_drain``) and joins.
    The deterministic actuation the elastic soak and tests use — same drain
    code the SIGTERM handler runs, no processes to babysit.

    ``kill(name)`` is the hard-preemption hook (chaos ``hard_kill``): the
    member's transport is severed and its loop stopped WITHOUT the drain
    path — in-flight work is lost and must be recovered by lease-TTL expiry
    + epoch fencing, exactly like a SIGKILLed process."""

    def __init__(
        self,
        agent_factory: Callable[[str], Any],
        name_prefix: str = "elastic",
        join_timeout_sec: float = 30.0,
    ) -> None:
        self.agent_factory = agent_factory
        self.name_prefix = name_prefix
        self.join_timeout_sec = join_timeout_sec
        self._lock = threading.Lock()
        self._members: Dict[str, Dict[str, Any]] = {}
        self.retired: List[Dict[str, Any]] = []
        self.killed: List[str] = []
        self._seq = 0

    def size(self) -> int:
        with self._lock:
            return len(self._members)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._members)

    def agent(self, name: str) -> Optional[Any]:
        with self._lock:
            entry = self._members.get(name)
        return entry["agent"] if entry else None

    def spawn(self, n: int) -> List[str]:
        names = []
        for _ in range(max(0, n)):
            with self._lock:
                self._seq += 1
                name = f"{self.name_prefix}-{self._seq}"
            agent = self.agent_factory(name)
            thread = threading.Thread(
                target=agent.run, name=f"member-{name}", daemon=True
            )
            with self._lock:
                self._members[name] = {"agent": agent, "thread": thread}
            thread.start()
            names.append(name)
        return names

    def retire(self, n: int) -> List[str]:
        """Gracefully drain the ``n`` newest members (LIFO keeps the
        longest-lived — warmest — members serving)."""
        with self._lock:
            victims = list(self._members)[-max(0, n):] if n > 0 else []
        return [name for name in victims if self.retire_member(name)]

    def retire_member(self, name: str) -> bool:
        with self._lock:
            entry = self._members.pop(name, None)
        if entry is None:
            return False
        agent, thread = entry["agent"], entry["thread"]
        agent.request_drain(reason="autoscale_retire")
        thread.join(timeout=self.join_timeout_sec)
        self.retired.append({
            "name": name,
            "agent": agent,
            "clean_exit": not thread.is_alive(),
            "spool_len": len(agent.spool),
        })
        return True

    def kill(self, name: str) -> bool:
        """Hard preemption: sever transport, stop the loop, no drain."""
        with self._lock:
            entry = self._members.pop(name, None)
        if entry is None:
            return False
        agent, thread = entry["agent"], entry["thread"]
        from agent_tpu.chaos import GatedSession

        dead = GatedSession(agent.session)
        dead.down = True
        agent.session = dead
        agent.running = False
        thread.join(timeout=self.join_timeout_sec)
        self.killed.append(name)
        return True


class ProcessFleetDriver(FleetDriver):
    """Real pinned agent processes via ``agent/fleet.py``: ``spawn`` launches
    ``python -m agent_tpu.agent.fleet`` children with unique names against
    ``controller_url``; ``retire`` sends SIGTERM — the agent's handler runs
    the same drain path as autoscaler retirement (finish/release the
    in-flight lease, flush spool + final metrics, exit 0) — and a later
    ``reap()`` collects the exit. Device slices come from a bounded pool of
    ``max_agents`` disjoint ``CHIP_SLICE`` assignments, recycled on exit."""

    def __init__(
        self,
        controller_url: str,
        tasks: str,
        max_agents: int = 4,
        devices_per_agent: int = 1,
        platform: str = "cpu",
        name_prefix: str = "elastic",
        extra_env: Optional[Dict[str, str]] = None,
        log_dir: Optional[str] = None,
    ) -> None:
        self.controller_url = controller_url
        self.tasks = tasks
        self.max_agents = max(1, max_agents)
        self.devices_per_agent = max(1, devices_per_agent)
        self.platform = platform
        self.name_prefix = name_prefix
        self.extra_env = dict(extra_env or {})
        self.log_dir = log_dir
        self._lock = threading.Lock()
        self._members: Dict[str, Dict[str, Any]] = {}
        self._draining: Dict[str, Dict[str, Any]] = {}
        self._free_slots = list(range(self.max_agents))
        self.retired: List[str] = []

    def size(self) -> int:
        self.reap()
        with self._lock:
            return len(self._members)

    def spawn(self, n: int) -> List[str]:
        import subprocess
        import sys

        from agent_tpu.agent.fleet import agent_env

        names: List[str] = []
        for _ in range(max(0, n)):
            with self._lock:
                if not self._free_slots:
                    break
                slot = self._free_slots.pop(0)
            name = f"{self.name_prefix}-{uuid.uuid4().hex[:6]}"
            env = agent_env(
                slot, self.max_agents, self.devices_per_agent,
                controller_url=self.controller_url, tasks=self.tasks,
                platform=self.platform, name_prefix=self.name_prefix,
                extra_env=self.extra_env,
            )
            env["AGENT_NAME"] = name
            out: Any = None
            if self.log_dir:
                import os

                os.makedirs(self.log_dir, exist_ok=True)
                out = open(
                    os.path.join(self.log_dir, f"{name}.log"), "ab"
                )
            proc = subprocess.Popen(
                [sys.executable, "-m", "agent_tpu.agent.fleet"],
                env=env, stdout=out,
                stderr=subprocess.STDOUT if out else None,
                close_fds=True,
            )
            if out is not None:
                out.close()
            with self._lock:
                self._members[name] = {"proc": proc, "slot": slot}
            names.append(name)
        return names

    def retire(self, n: int) -> List[str]:
        with self._lock:
            victims = list(self._members)[-max(0, n):] if n > 0 else []
            moved = {}
            for name in victims:
                moved[name] = self._members.pop(name)
                self._draining[name] = moved[name]
        for name, entry in moved.items():
            try:
                entry["proc"].terminate()  # SIGTERM → the agent drain path
            except OSError:
                pass
            entry["since"] = time.monotonic()
        return list(moved)

    def reap(self, kill_after_sec: float = 60.0) -> None:
        """Collect exited members (crashed live ones free their slot so
        replacement can land; drained ones finish retirement), escalating
        to SIGKILL past ``kill_after_sec`` of drain."""
        now = time.monotonic()
        with self._lock:
            for name in list(self._members):
                if self._members[name]["proc"].poll() is not None:
                    entry = self._members.pop(name)
                    self._free_slots.append(entry["slot"])
            for name in list(self._draining):
                entry = self._draining[name]
                if entry["proc"].poll() is not None:
                    self._draining.pop(name)
                    self._free_slots.append(entry["slot"])
                    self.retired.append(name)
                elif now - entry.get("since", now) > kill_after_sec:
                    try:
                        entry["proc"].kill()
                    except OSError:
                        pass

    def stop_all(self, timeout: float = 30.0) -> None:
        with self._lock:
            entries = list(self._members.values()) + list(
                self._draining.values()
            )
            self._members.clear()
            self._draining.clear()
        for entry in entries:
            try:
                entry["proc"].terminate()
            except OSError:
                pass
        deadline = time.monotonic() + timeout
        for entry in entries:
            try:
                entry["proc"].wait(
                    timeout=max(0.1, deadline - time.monotonic())
                )
            except Exception:  # noqa: BLE001
                try:
                    entry["proc"].kill()
                except OSError:
                    pass


def main(argv: Optional[List[str]] = None) -> int:
    """Operator CLI: ``python -m agent_tpu.autoscale --controller URL
    --tasks op1,op2`` — scales a process fleet against a live controller's
    ``/v1/health`` with the AUTOSCALE_* env knobs."""
    import argparse

    from agent_tpu.obs.scrape import fetch_health

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--controller", required=True)
    ap.add_argument("--tasks", required=True,
                    help="TASKS for spawned members (comma-separated ops)")
    ap.add_argument("--platform", default="cpu", choices=("cpu", "tpu"))
    ap.add_argument("--devices-per-agent", type=int, default=1)
    ap.add_argument("--log-dir", default="")
    args = ap.parse_args(argv)

    cfg = AutoscaleConfig.from_env()
    driver = ProcessFleetDriver(
        args.controller, args.tasks, max_agents=cfg.max_agents,
        devices_per_agent=args.devices_per_agent, platform=args.platform,
        log_dir=args.log_dir or None,
    )
    scaler = Autoscaler(
        driver, lambda: fetch_health(args.controller), config=cfg
    )
    stop = threading.Event()
    import signal

    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    log(
        "autoscaler up", controller=args.controller,
        min=cfg.min_agents, max=cfg.max_agents,
        interval_sec=cfg.interval_sec,
    )
    try:
        scaler.run(stop)
    finally:
        driver.stop_all()
    log(
        "autoscaler stopped", scale_ups=scaler.scale_ups,
        scale_downs=scaler.scale_downs, replacements=scaler.replacements,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
