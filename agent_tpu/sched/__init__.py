"""Swarm scheduler subsystem (ISSUE 4).

The controller delegates every lease decision here. ``fifo`` (default)
replays the historical inline scan bit-for-bit; ``fair`` adds priority
tiers, weighted tenant fair-share (deficit round-robin), load-aware
placement, admission control, and deadline handling — see ``base.py`` for
the policy contract and ``fair.py`` for the dispatch rules.
"""

from agent_tpu.sched.base import (
    DEFAULT_PRIORITY,
    DEFAULT_TENANT,
    PRIORITY_MAX,
    PRIORITY_MIN,
    AdmissionError,
    LeaseContext,
    Scheduler,
    make_scheduler,
)
from agent_tpu.sched.fair import FairScheduler
from agent_tpu.sched.fifo import FifoScheduler

__all__ = [
    "AdmissionError",
    "DEFAULT_PRIORITY",
    "DEFAULT_TENANT",
    "FairScheduler",
    "FifoScheduler",
    "LeaseContext",
    "PRIORITY_MAX",
    "PRIORITY_MIN",
    "Scheduler",
    "make_scheduler",
]
