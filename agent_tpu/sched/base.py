"""Scheduler interface — the seam between lease mechanics and lease *policy*.

The controller owns correctness (job state machine, epoch fencing, label
matching, dependency gating, journal durability); a ``Scheduler`` owns only
*order and placement*: which of the currently-leasable jobs go out on this
lease, and how many. That split is what lets ``fifo`` stay bit-compatible
with the pre-scheduler controller (the policy replays the exact inline scan
it replaced) while ``fair`` layers priority tiers, tenant fair-share, and
load-aware placement on the same state machine.

Contract:

- The controller calls ``add(job)`` whenever a job becomes queued (submit,
  retry requeue, lease-expiry requeue) and ``take(ctx, eligible)`` under its
  lock on every lease. ``take`` returns jobs **removed** from the queue in
  dispatch order; jobs not returned must keep their relative order (the
  fifo compatibility guarantee) or their policy-defined position (fair).
- ``eligible(job)`` is the controller's leasability check (state, not_before,
  capability ops, labels, dependencies). Policies never re-implement it; they
  only decide *among* eligible jobs — plus placement deferral, which may skip
  an eligible job a bounded number of times waiting for a better-suited agent.
- Queues hold Job references (the controller's own objects); the scheduler
  never mutates job state except the placement-deferral counter.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, FrozenSet, List, Optional

PRIORITY_MIN = 0
PRIORITY_MAX = 9
DEFAULT_PRIORITY = 4
DEFAULT_TENANT = "default"


class AdmissionError(Exception):
    """Submit rejected by admission control (wire: HTTP 429).

    Carries ``retry_after_ms`` so the HTTP layer can tell the client when to
    come back; ``utils/retry.py`` already classifies 429 as transient, so an
    unmodified agent-side ``RetryPolicy`` backs off and retries correctly.
    """

    def __init__(
        self,
        message: str,
        retry_after_ms: int = 1000,
        tenant: Optional[str] = None,
        scope: str = "global",
    ) -> None:
        super().__init__(message)
        self.retry_after_ms = int(retry_after_ms)
        self.tenant = tenant
        self.scope = scope


@dataclass(frozen=True)
class LeaseContext:
    """Everything a policy may consider about the polling agent.

    ``limit`` is the number of distinct jobs the controller will actually
    hand out this lease (post fault-injection accounting); ``requested`` is
    the agent's raw ``max_tasks``. The device/load fields come from the
    enriched lease ``capabilities`` (``device_kind``/``mesh_devices`` from
    ``TpuRuntime.describe()``, ``queue_depth`` = the agent's staged-queue
    occupancy) and are None for agents that predate the enrichment — a
    policy must degrade to capability-only behavior for those.
    """

    agent: str = ""
    now: float = 0.0
    limit: int = 1
    requested: int = 1
    ops: FrozenSet[str] = frozenset()
    labels: Dict[str, Any] = field(default_factory=dict)
    device_kind: Optional[str] = None
    mesh_devices: Optional[int] = None
    queue_depth: Optional[int] = None


class Scheduler:
    """Base policy: queue bookkeeping shared by every implementation."""

    name = "?"

    def __init__(
        self, on_decision: Optional[Callable[..., None]] = None
    ) -> None:
        # Counter hook (controller-provided): policy-internal decisions
        # (placement deferrals) surface in sched_decisions_total without the
        # policy importing the metrics registry. Policies that know WHICH
        # job a decision concerns pass ``job_id=`` so the controller can
        # also pin a span to that job's trace (ISSUE 5); hooks that ignore
        # it must accept the kwarg.
        self.on_decision = on_decision or (
            lambda decision, **_kw: None
        )
        self._depth_by_tenant: Dict[str, int] = {}

    # -- bookkeeping helpers for subclasses --

    def _note_add(self, job: Any) -> None:
        t = job.tenant
        self._depth_by_tenant[t] = self._depth_by_tenant.get(t, 0) + 1

    def _note_remove(self, job: Any) -> None:
        t = job.tenant
        n = self._depth_by_tenant.get(t, 0) - 1
        if n <= 0:
            self._depth_by_tenant.pop(t, None)
        else:
            self._depth_by_tenant[t] = n

    # -- depth introspection (admission control + gauges) --

    def total(self) -> int:
        return sum(self._depth_by_tenant.values())

    def depth_for(self, tenant: str) -> int:
        return self._depth_by_tenant.get(tenant, 0)

    def depth_by_tenant(self) -> Dict[str, int]:
        return dict(self._depth_by_tenant)

    def depth_by_priority(self) -> Dict[int, int]:
        """Queued jobs per priority tier — the per-tier queue-pressure feed
        ``GET /v1/health`` reports next to SLO attainment (ISSUE 8).
        Subclasses with a cheaper view override; the default derives it
        from ``queued_ids`` via the policy's own job references and is only
        called off the hot path (health endpoint, swarmtop)."""
        return {}

    # -- the policy surface (subclasses implement) --

    def add(self, job: Any) -> None:
        raise NotImplementedError

    def discard(self, job_id: str) -> bool:
        """Drop a queued job (deadline death while pending). Returns whether
        it was queued."""
        raise NotImplementedError

    def reprioritize(self, job: Any) -> None:
        """Re-bucket a queued job after its ``priority`` changed (deadline
        escalation). Default: discard + re-add (tail of the new tier)."""
        if self.discard(job.job_id):
            self.add(job)

    def take(
        self,
        ctx: LeaseContext,
        eligible: Callable[[Any], bool],
    ) -> List[Any]:
        raise NotImplementedError

    def queued_ids(self) -> List[str]:
        raise NotImplementedError


def make_scheduler(
    config: Any = None,
    on_decision: Optional[Callable[[str], None]] = None,
) -> Scheduler:
    """Build the policy named by ``config.policy`` (``SCHED_POLICY``).

    ``fifo`` (default) is bit-compatible with the pre-scheduler controller;
    ``fair`` enables priority tiers + tenant fair-share + placement.
    """
    from agent_tpu.sched.fair import FairScheduler
    from agent_tpu.sched.fifo import FifoScheduler

    policy = getattr(config, "policy", "fifo") or "fifo"
    if policy == "fifo":
        return FifoScheduler(on_decision=on_decision)
    if policy == "fair":
        return FairScheduler(config, on_decision=on_decision)
    raise ValueError(f"unknown SCHED_POLICY {policy!r} (want fifo|fair)")
