"""FIFO policy — bit-compatible with the pre-scheduler controller.

This is the exact inline scan ``Controller.lease`` used to run over its
``self._queue: List[str]``: walk the queue in arrival order, take eligible
jobs until the grant limit, and leave every other job in its original
relative position. Priority, tenant, and the agent's load advertisement are
deliberately ignored — ``SCHED_POLICY=fifo`` must produce the same drain
order (and therefore the same journal bytes) as HEAD for any interleaving
of submit/lease/report/expire, which ``tests/test_sched.py`` pins with a
model-based property test.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from agent_tpu.sched.base import LeaseContext, Scheduler


class FifoScheduler(Scheduler):
    name = "fifo"

    def __init__(self, on_decision: Optional[Callable[[str], None]] = None
                 ) -> None:
        super().__init__(on_decision=on_decision)
        self._order: List[Any] = []  # Job refs in arrival order

    def add(self, job: Any) -> None:
        self._order.append(job)
        self._note_add(job)

    def discard(self, job_id: str) -> bool:
        for i, job in enumerate(self._order):
            if job.job_id == job_id:
                del self._order[i]
                self._note_remove(job)
                return True
        return False

    def reprioritize(self, job: Any) -> None:
        # Priority has no queue effect under FIFO: escalation updates the
        # job's field (visible in snapshots) but must not reorder anything.
        pass

    def take(
        self, ctx: LeaseContext, eligible: Callable[[Any], bool]
    ) -> List[Any]:
        # The historical scan, verbatim: one pass, eligibility checked in
        # queue order, ineligible and over-limit jobs keep their positions.
        taken: List[Any] = []
        remaining: List[Any] = []
        for job in self._order:
            if len(taken) < ctx.limit and eligible(job):
                taken.append(job)
                self._note_remove(job)
            else:
                remaining.append(job)
        self._order = remaining
        return taken

    def queued_ids(self) -> List[str]:
        return [job.job_id for job in self._order]

    def depth_by_priority(self) -> dict:
        # Health-endpoint feed (ISSUE 8): O(queue), called off the hot path.
        # FIFO ignores priority for ORDER but the pressure split is still
        # the signal the autoscaler wants.
        out: dict = {}
        for job in self._order:
            out[job.priority] = out.get(job.priority, 0) + 1
        return out
