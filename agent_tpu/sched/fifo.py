"""FIFO policy — bit-compatible with the pre-scheduler controller.

This is the exact inline scan ``Controller.lease`` used to run over its
``self._queue: List[str]``: walk the queue in arrival order, take eligible
jobs until the grant limit, and leave every other job in its original
relative position. Priority, tenant, and the agent's load advertisement are
deliberately ignored — ``SCHED_POLICY=fifo`` must produce the same drain
order (and therefore the same journal bytes) as HEAD for any interleaving
of submit/lease/report/expire, which ``tests/test_sched.py`` pins with a
model-based property test.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from agent_tpu.sched.base import LeaseContext, Scheduler


class FifoScheduler(Scheduler):
    name = "fifo"

    def __init__(self, on_decision: Optional[Callable[[str], None]] = None
                 ) -> None:
        super().__init__(on_decision=on_decision)
        self._order: List[Any] = []  # Job refs in arrival order

    def add(self, job: Any) -> None:
        self._order.append(job)
        self._note_add(job)

    def discard(self, job_id: str) -> bool:
        for i, job in enumerate(self._order):
            if job.job_id == job_id:
                del self._order[i]
                self._note_remove(job)
                return True
        return False

    def reprioritize(self, job: Any) -> None:
        # Priority has no queue effect under FIFO: escalation updates the
        # job's field (visible in snapshots) but must not reorder anything.
        pass

    def take(
        self, ctx: LeaseContext, eligible: Callable[[Any], bool]
    ) -> List[Any]:
        # The historical scan with one refinement (ISSUE 19): jobs on a
        # workflow's critical path (``critical_path`` = longest remaining
        # stage count, 0 for plain jobs) are scanned first. The sort is
        # stable, so with no DAG jobs queued the scan order — and therefore
        # the drain order and journal bytes — is bit-identical to the
        # historical one-pass walk. Linear chains have strictly decreasing
        # critical_path along arrival order, so they also degrade to plain
        # FIFO (pinned by tests/test_flow.py's property test). Non-taken
        # jobs keep their original arrival positions either way.
        scan = sorted(
            self._order, key=lambda j: -getattr(j, "critical_path", 0)
        )
        taken: List[Any] = []
        taken_ids: set = set()
        for job in scan:
            if len(taken) >= ctx.limit:
                break
            if eligible(job):
                taken.append(job)
                taken_ids.add(id(job))
                self._note_remove(job)
        if taken:
            self._order = [j for j in self._order if id(j) not in taken_ids]
        return taken

    def queued_ids(self) -> List[str]:
        return [job.job_id for job in self._order]

    def depth_by_priority(self) -> dict:
        # Health-endpoint feed (ISSUE 8): O(queue), called off the hot path.
        # FIFO ignores priority for ORDER but the pressure split is still
        # the signal the autoscaler wants.
        out: dict = {}
        for job in self._order:
            out[job.priority] = out.get(job.priority, 0) + 1
        return out
