"""Cross-partition work stealing policy (ISSUE 18).

A partitioned control plane shards jobs by consistent hash of
``{tenant, job_id}`` (``controller/partition.py``), which balances *keys*,
not *load*: one hot tenant can pile work onto a single partition while the
others idle. The fix is the classic work-stealing move — an agent whose
home partition has nothing leasable takes work from the partition with the
deepest leasable queue — and the decision of *when* that is worth doing is
a scheduling concern, so it lives here, next to the dispatch policies.

The policy is deliberately stateless and side-effect free: callers (the
router's lease path, or an agent running with an explicit partition map)
feed it the home partition plus a depth sample per partition and get back
the victim to poll, or ``None``. Safety does not depend on this policy at
all — a stolen lease is just an ordinary lease against the partition that
owns the job, so epoch fencing and the terminal-state duplicate guard make
the handoff idempotent; stealing only decides where an idle agent polls
next.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from agent_tpu.config import env_bool, env_int


@dataclass(frozen=True)
class StealPolicy:
    """When does an idle agent poll a foreign partition?

    ``min_advantage`` is the hysteresis: a victim's leasable depth must
    exceed the home partition's by at least this many jobs. 1 steals
    aggressively (any deeper queue qualifies); larger values keep agents
    home unless the imbalance is real, which bounds the extra lease
    traffic stealing adds to an already-loaded partition.
    """

    enabled: bool = True          # STEAL_ENABLED
    min_advantage: int = 1        # STEAL_MIN_ADVANTAGE

    @staticmethod
    def from_env() -> "StealPolicy":
        return StealPolicy(
            enabled=env_bool("STEAL_ENABLED", True),
            min_advantage=max(1, env_int("STEAL_MIN_ADVANTAGE", 1)),
        )

    def pick_victim(
        self, home: str, depths: Dict[str, Optional[int]]
    ) -> Optional[str]:
        """The partition an idle-at-home agent should steal from, or None.

        ``depths`` maps partition name -> leasable queue depth (None =
        unknown/unreachable, never stolen from). Deterministic: deepest
        eligible victim wins, ties break by name — two routers looking at
        the same sample send their idle agents to the same place, which is
        fine (the victim fences via its own lease path).
        """
        if not self.enabled:
            return None
        home_depth = depths.get(home) or 0
        best: Optional[str] = None
        best_depth = 0
        for name in sorted(depths):
            if name == home:
                continue
            depth = depths.get(name)
            if depth is None:
                continue
            if depth - home_depth < self.min_advantage:
                continue
            if depth > best_depth:
                best, best_depth = name, depth
        return best
