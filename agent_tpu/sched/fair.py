"""Fair policy: priority tiers → weighted deficit-round-robin → FIFO,
with load- and capability-aware placement.

Dispatch order (the tentpole contract):

1. **Priority tier first.** Jobs carry ``priority`` 0–9 (9 = most urgent);
   a lower tier never dispatches while a higher tier has an eligible job
   for this agent.
2. **Deficit round-robin across tenants within a tier.** Each tier keeps a
   rotation of tenants (arrival order); every visit banks the tenant's
   weight (``SCHED_TENANT_WEIGHTS``, default 1) into a deficit counter and
   serves one job per unit of deficit. A tenant with weight 3 drains 3×
   the jobs per rotation of a weight-1 tenant; with equal weights this is
   plain round-robin — one tenant's 10k-shard bulk job can no longer starve
   another tenant's interactive singles. Deficits do not bank while a
   tenant has nothing serviceable (classic DRR anti-hoarding).
3. **FIFO within a tenant.** Arrival order, with ineligible jobs skipped in
   place (a dependency-gated reduce must not block the shards behind it).

Placement (the MPMD insight — unequal work belongs on unequal hardware,
arXiv:2412.14374 — applied to the lease protocol):

- A TPU-tagged job (op name ``*_tpu`` or a truthy ``tpu`` required label)
  **prefers** agents advertising ``device_kind == "tpu"``: a non-TPU agent
  is refused the job up to ``SCHED_PLACEMENT_PATIENCE`` times, after which
  any capable agent may take it — preference, never starvation.
- Bulk shards (``shard-*`` job ids) prefer **idle** agents: an agent whose
  advertised staged ``queue_depth`` exceeds ``SCHED_BUSY_QUEUE_DEPTH`` is
  deferred the same bounded way.
- Disaggregated-serving prefill jobs (``serve_prefill``, ISSUE 16) prefer
  agents that do **not** advertise ``serve_decode`` — encoder bursts stay
  off the continuous-decode fleet — with the same bounded deferral.
- Deep-queue agents get **shrunken grants**: the grant limit drops by the
  staged backlog beyond the busy threshold (floor 1), so a backed-up agent
  stops accumulating work it cannot start — the tf.data backpressure idea
  (arXiv:2101.12127) applied to ``max_tasks``.

Everything is deterministic: no randomness, dict/deque iteration in
insertion order, the rotation cursor persists across leases. The same
submit/lease sequence always yields the same dispatch order (pinned by
``tests/test_sched.py``; the chaos soak relies on it for seeded replay).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from agent_tpu.config import TRUTHY_TOKENS
from agent_tpu.sched.base import LeaseContext, Scheduler


def _truthy(value: Any) -> bool:
    if isinstance(value, str):
        return value.strip().lower() in TRUTHY_TOKENS
    return bool(value)


def wants_tpu(job: Any) -> bool:
    """TPU-tagged: the op is device-bound by name convention (``*_tpu``) or
    the submitter required a truthy ``tpu`` label."""
    if job.op.endswith("_tpu"):
        return True
    return _truthy(job.required_labels.get("tpu"))


def is_bulk(job: Any) -> bool:
    """Bulk shard of a sharded drain (``submit_csv_job`` id convention)."""
    return job.job_id.startswith("shard-")


class FairScheduler(Scheduler):
    name = "fair"

    def __init__(
        self,
        config: Any = None,
        on_decision: Optional[Callable[[str], None]] = None,
    ) -> None:
        super().__init__(on_decision=on_decision)
        weights = dict(getattr(config, "tenant_weights", None) or {})
        self._weights: Dict[str, float] = {
            str(k): max(0.0, float(v)) for k, v in weights.items()
        }
        self.placement_patience = max(
            0, int(getattr(config, "placement_patience", 3))
        )
        self.busy_queue_depth = max(
            0, int(getattr(config, "busy_queue_depth", 2))
        )
        # priority → tenant → FIFO of Job refs
        self._tiers: Dict[int, Dict[str, Deque[Any]]] = {}
        # priority → persistent DRR rotation (deque of tenant names); the
        # head is the next tenant to visit, surviving across take() calls.
        self._rotation: Dict[int, Deque[str]] = {}
        # priority → tenant → banked deficit
        self._deficit: Dict[int, Dict[str, float]] = {}
        # job_id → (priority, tenant) for O(1) discard
        self._where: Dict[str, Tuple[int, str]] = {}

    # ---- queue maintenance ----

    def _weight(self, tenant: str) -> float:
        return self._weights.get(tenant, 1.0)

    def add(self, job: Any) -> None:
        prio = int(job.priority)
        tier = self._tiers.setdefault(prio, {})
        if job.tenant not in tier:
            tier[job.tenant] = deque()
            self._rotation.setdefault(prio, deque()).append(job.tenant)
            self._deficit.setdefault(prio, {}).setdefault(job.tenant, 0.0)
        tier[job.tenant].append(job)
        self._where[job.job_id] = (prio, job.tenant)
        self._note_add(job)

    def discard(self, job_id: str) -> bool:
        loc = self._where.pop(job_id, None)
        if loc is None:
            return False
        prio, tenant = loc
        q = self._tiers.get(prio, {}).get(tenant)
        if q is None:
            return False
        for job in q:
            if job.job_id == job_id:
                q.remove(job)
                self._note_remove(job)
                self._gc_tenant(prio, tenant)
                return True
        return False

    def _gc_tenant(self, prio: int, tenant: str) -> None:
        """Drop empty tenant queues (and tiers) so rotation stays tight.
        Deficit resets with the queue: an empty tenant banks nothing."""
        tier = self._tiers.get(prio)
        if tier is None:
            return
        q = tier.get(tenant)
        if q is not None and not q:
            del tier[tenant]
            self._deficit.get(prio, {}).pop(tenant, None)
            rot = self._rotation.get(prio)
            if rot is not None and tenant in rot:
                rot.remove(tenant)
        if not tier:
            self._tiers.pop(prio, None)
            self._rotation.pop(prio, None)
            self._deficit.pop(prio, None)

    def depth_by_priority(self) -> Dict[int, int]:
        # Health-endpoint feed (ISSUE 8): the tier structure already holds
        # the split, so this is O(tiers × tenants), not O(jobs).
        return {
            prio: sum(len(q) for q in tier.values())
            for prio, tier in self._tiers.items()
            if tier
        }

    # ---- placement ----

    def score(self, job: Any, ctx: LeaseContext) -> float:
        """Suitability of handing ``job`` to ``ctx``'s agent, >= 0 means
        acceptable now. Unknown fields (legacy agents) never penalize —
        a fleet that predates the enrichment behaves capability-only."""
        s = 1.0
        if wants_tpu(job) and ctx.device_kind is not None:
            if ctx.device_kind == "tpu":
                # Bigger meshes edge out smaller ones for device-bound work.
                s += 2.0 + min(int(ctx.mesh_devices or 0), 64) / 64.0
            else:
                s -= 2.0
        if is_bulk(job) and ctx.queue_depth is not None:
            s -= 0.5 * max(0, int(ctx.queue_depth) - self.busy_queue_depth)
        if job.op == "serve_prefill" and "serve_decode" in ctx.ops:
            # Disaggregated serving (ISSUE 16): prefill is a bulk encoder
            # burst; landing it on an agent that also runs the continuous
            # decode engine steals decode iterations and blows TTFT. Steer
            # it toward prefill-only agents the bounded way (same
            # preference-never-starvation contract as the TPU rule): a
            # decode-capable agent defers it up to placement_patience.
            s -= 1.0
        return s

    def _placement_ok(self, job: Any, ctx: LeaseContext) -> bool:
        if self.score(job, ctx) >= 0.5:
            return True
        if job.placement_defers >= self.placement_patience:
            return True  # patience exhausted: any capable agent may take it
        job.placement_defers += 1
        # job_id lets the controller pin the deferral onto the job's trace
        # (a sched.defer span) as well as the aggregate counter.
        self.on_decision("deferred_placement", job_id=job.job_id)
        return False

    # ---- dispatch ----

    def _grant_limit(self, ctx: LeaseContext) -> int:
        limit = ctx.limit
        if ctx.queue_depth is not None:
            excess = max(0, int(ctx.queue_depth) - self.busy_queue_depth)
            if excess:
                limit = max(1, limit - excess)
        return limit

    def take(
        self, ctx: LeaseContext, eligible: Callable[[Any], bool]
    ) -> List[Any]:
        limit = self._grant_limit(ctx)
        out: List[Any] = []
        for prio in sorted(self._tiers, reverse=True):
            if len(out) >= limit:
                break
            self._take_tier(prio, ctx, eligible, limit, out)
        return out

    def _take_tier(
        self,
        prio: int,
        ctx: LeaseContext,
        eligible: Callable[[Any], bool],
        limit: int,
        out: List[Any],
    ) -> None:
        rotation = self._rotation.get(prio)
        if not rotation:
            return
        deficits = self._deficit.setdefault(prio, {})
        # Classic DRR with a persistent cursor: the head of ``rotation`` is
        # the tenant currently being served. Arriving at a tenant with a
        # spent deficit banks its weight once; it then serves jobs until
        # the deficit runs out (cursor advances) or the grant fills (cursor
        # STAYS, so the next lease resumes this tenant's turn — that
        # carry-over is what makes per-lease grants of 1 still honor the
        # weights). A full fruitless cycle (every tenant visited, nothing
        # serviceable for this agent) terminates the pass.
        fruitless = 0
        while len(out) < limit and rotation and fruitless < len(rotation):
            tenant = rotation[0]
            q = self._tiers.get(prio, {}).get(tenant)
            if not q:
                deficits[tenant] = 0.0
                rotation.rotate(-1)
                fruitless += 1
                continue
            if deficits.get(tenant, 0.0) < 1.0:
                deficits[tenant] = (
                    deficits.get(tenant, 0.0) + self._weight(tenant)
                )
            if deficits[tenant] < 1.0:
                # Sub-unit weight: still banking toward its next grant.
                rotation.rotate(-1)
                fruitless += 1
                continue
            served = 0
            while deficits[tenant] >= 1.0 and len(out) < limit:
                job = self._pop_serviceable(q, ctx, eligible)
                if job is None:
                    # Nothing serviceable now: no banking (anti-hoard).
                    deficits[tenant] = 0.0
                    break
                self._where.pop(job.job_id, None)
                self._note_remove(job)
                out.append(job)
                deficits[tenant] -= 1.0
                served += 1
            fruitless = 0 if served else fruitless + 1
            if (
                len(out) >= limit
                and deficits.get(tenant, 0.0) >= 1.0
                and q
            ):
                break  # mid-turn: cursor stays for the next lease
            self._gc_tenant(prio, tenant)
            if prio not in self._tiers:
                return  # tier fully drained; rotation is gone
            if tenant in rotation:
                rotation.rotate(-1)

    def _pop_serviceable(
        self,
        q: Deque[Any],
        ctx: LeaseContext,
        eligible: Callable[[Any], bool],
    ) -> Optional[Any]:
        """First job in FIFO order that is leasable *and* placeable on this
        agent; ineligible/deferred jobs keep their positions (no
        head-of-line blocking by a dep-gated reduce or a TPU-tagged job
        waiting out its placement patience).

        Critical-path-first (ISSUE 19): when workflow stage jobs are queued
        (``critical_path`` > 0 = longest remaining stage count), the
        serviceable job with the most downstream work wins the pop; ties —
        and the all-plain-jobs case — keep exact FIFO order, so non-DAG
        drains are byte-identical to the pre-DAG scheduler."""
        if any(getattr(j, "critical_path", 0) > 0 for j in q):
            best = None
            for job in q:
                if (
                    (best is None or getattr(job, "critical_path", 0)
                     > getattr(best, "critical_path", 0))
                    and eligible(job)
                    and self._placement_ok(job, ctx)
                ):
                    best = job
            if best is not None:
                q.remove(best)
            return best
        for job in q:
            if eligible(job) and self._placement_ok(job, ctx):
                q.remove(job)
                return job
        return None

    def queued_ids(self) -> List[str]:
        out: List[str] = []
        for prio in sorted(self._tiers, reverse=True):
            rot = self._rotation.get(prio)
            tenants = list(rot) if rot else list(self._tiers[prio])
            for tenant in tenants:
                out.extend(
                    j.job_id for j in self._tiers[prio].get(tenant, ())
                )
        return out
