"""Compiled-executable cache — the successor of the interpreter singleton.

The reference cached one native interpreter per model path so every task after
the first skipped model load (reference ``ops/_tpu_runtime.py:8-13,42-43``).
Under XLA the expensive artifact is the *compiled executable*: a traced +
compiled jit program for one (op, shape-bucket, dtype, sharding) combination.
This cache makes compilation a once-per-bucket cost, which is why ops feed it
bucketed static shapes (``agent_tpu.models.tokenizer.pad_batch``) — the cache
stays small and stops missing once the buckets are warm.

Keys are caller-built tuples of hashables (op name, shape tuple, dtype string,
mesh axis sizes). Stats are exported for the metrics channel (SURVEY.md §5.5).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Hashable, Optional, Tuple

from agent_tpu.obs import trace as obs_trace


class ExecutableCache:
    """Thread-safe build-once cache: key → built value (a compiled callable for
    executables; any expensive device-resident object in general — the runtime
    also uses it for HBM params, where double-build means double transfer).

    A single lock guards the map; the build itself runs outside the lock so a
    slow XLA compile does not serialize unrelated ops, with a per-key event so
    concurrent builders of the same key trigger exactly one build.

    Compile-cost attribution (ISSUE 5): with ``trace_label`` set (the
    default, ``"xla.compile"``), every miss emits a span named after it —
    attributed to the ambient :mod:`agent_tpu.obs.trace` task context, so a
    cold compile shows up inside the triggering job's ``execute`` span —
    plus ``runtime_compile_seconds_total{op}`` and per-op hit/miss counters.
    The params store passes ``trace_label=None``: an HBM transfer is not a
    compile and must not pollute the compile-cost series.
    """

    def __init__(self, trace_label: Optional[str] = "xla.compile") -> None:
        self._lock = threading.Lock()
        self._cache: Dict[Tuple[Hashable, ...], Any] = {}
        self._building: Dict[Tuple[Hashable, ...], threading.Event] = {}
        self._generation = 0  # bumped by clear(); fences in-flight builds
        self._trace_label = trace_label
        self.hits = 0
        self.misses = 0

    def get_or_build(
        self, key: Tuple[Hashable, ...], build: Callable[[], Any]
    ) -> Any:
        while True:
            with self._lock:
                fn = self._cache.get(key)
                if fn is not None:
                    self.hits += 1
                    if self._trace_label:
                        obs_trace.record_cache_event(key, hit=True)
                    return fn
                ev = self._building.get(key)
                if ev is None:
                    self._building[key] = threading.Event()
                    self.misses += 1
                    gen = self._generation
                    break
            ev.wait()  # someone else is compiling this key
        if self._trace_label:
            obs_trace.record_cache_event(key, hit=False)
        try:
            t0 = time.perf_counter()
            fn = build()
            if self._trace_label:
                obs_trace.record_compile(
                    key, time.perf_counter() - t0, name=self._trace_label
                )
            with self._lock:
                # A clear() that raced this build wins: return the value to
                # the caller but do NOT cache it, so a post-clear store is
                # actually empty (for params, the HBM is released as soon as
                # the caller drops the tree — the point of clear_params).
                if gen == self._generation:
                    self._cache[key] = fn
            return fn
        finally:
            with self._lock:
                self._building.pop(key).set()

    def evict(self, key: Tuple[Hashable, ...]) -> None:
        with self._lock:
            self._cache.pop(key, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"entries": len(self._cache), "hits": self.hits, "misses": self.misses}

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()
            self._generation += 1
