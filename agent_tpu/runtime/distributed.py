"""Multi-host SPMD wiring — the ICI/DCN two-tier design of SURVEY.md §5.8.

The reference's only inter-node transport was agent↔controller HTTP
(reference ``app.py:143-158``); there was no agent↔agent communication at
all. On a multi-host TPU slice that is not enough: every host must enter the
same XLA program in lockstep or the collective ops deadlock. The design
(SURVEY.md §7 "hard parts", scaling-book recipe):

- **DCN tier**: exactly one lease loop per pod slice. Host 0 talks to the
  controller; other hosts never open an HTTP connection.
- **ICI tier**: host 0 broadcasts each leased task (as bounded JSON) to all
  hosts via a device all-reduce (`_broadcast_bytes`), then *every* host calls
  the same op entry point, so the jit-compiled SPMD program runs on the full
  global mesh. Host 0 alone posts the result.

``jax.distributed.initialize`` is env-gated (COORDINATOR_ADDRESS /
NUM_PROCESSES / PROCESS_ID — the standard JAX multi-host trio); without it
everything degrades to the single-process path, so the CPU test mesh and the
single-chip bench run the identical code.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, Optional

MIN_BCAST_BYTES = 1 << 12   # smallest broadcast bucket (4 KiB)
MAX_TASK_BYTES = 1 << 26    # sanity ceiling (64 MiB) — not a payload budget
_SHUTDOWN = {"__control__": "shutdown"}


@dataclass(frozen=True)
class DistInfo:
    process_index: int
    process_count: int

    @property
    def is_leader(self) -> bool:
        return self.process_index == 0


def maybe_initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> DistInfo:
    """Initialize JAX multi-host coordination when configured; else no-op.

    Idempotent: a second call (or a call after someone else initialized)
    returns the live process info without re-initializing.
    """
    import jax

    if coordinator_address:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        except RuntimeError:
            # Tolerate only the idempotent case: the service was already
            # joined, so the process count shows a real multi-process runtime
            # (and matches num_processes when one was requested). Anything
            # else — typically "backend already initialized" because
            # something touched jax.devices() first — must surface:
            # swallowing it silently degrades this host to single-process
            # mode while its peers deadlock waiting in collectives.
            joined = jax.process_count() > 1 and (
                num_processes is None or jax.process_count() == num_processes
            )
            if not joined:
                raise
    return DistInfo(
        process_index=jax.process_index(), process_count=jax.process_count()
    )


def _bucket(n: int) -> int:
    """Power-of-two buffer bucket ≥ n — bounded executable count for the
    shape-specialized broadcast, no hard payload cap. (The payload size
    travels in its own separate 8-byte broadcast, not in this buffer.)"""
    size = MIN_BCAST_BYTES
    while size < n:
        size *= 2
    return size


def _broadcast_bytes(payload: bytes, source: int = 0) -> bytes:
    """Broadcast ``payload`` from process ``source`` to all processes.

    Two-phase: an 8-byte size broadcast picks the power-of-two bucket, then
    the payload travels in a buffer of that bucket size — every host compiles
    the same small set of shapes, and payloads are bounded only by the 64 MiB
    sanity ceiling (single-host agents have no cap, so multi-host must not
    quietly impose a much smaller one).
    """
    import jax
    import numpy as np
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return payload
    if len(payload) > MAX_TASK_BYTES:
        raise ValueError(
            f"broadcast payload {len(payload)}B exceeds {MAX_TASK_BYTES}B"
        )
    is_source = jax.process_index() == source
    size_buf = np.zeros(8, dtype=np.uint8)
    if is_source:
        size_buf[:] = np.frombuffer(len(payload).to_bytes(8, "little"), np.uint8)
    size_out = multihost_utils.broadcast_one_to_all(size_buf, is_source=is_source)
    n = int.from_bytes(bytes(size_out), "little")

    buf = np.zeros(_bucket(n), dtype=np.uint8)
    if is_source:
        buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf, is_source=is_source)
    return bytes(out[:n])


def broadcast_task(task: Optional[Dict[str, Any]], source: int = 0
                   ) -> Optional[Dict[str, Any]]:
    """Leader broadcasts its leased task dict (or None for 'idle tick') to all
    hosts; every host returns the same value. Single-process: passthrough."""
    import jax

    if jax.process_count() == 1:
        return task
    if jax.process_index() == source:
        payload = b"" if task is None else json.dumps(task).encode("utf-8")
    else:
        payload = b""
    raw = _broadcast_bytes(payload, source=source)
    if not raw:
        return None
    return json.loads(raw.decode("utf-8"))


def broadcast_shutdown(source: int = 0) -> None:
    """Leader tells followers to exit their follower loop."""
    broadcast_task(_SHUTDOWN, source=source)


def is_shutdown(task: Optional[Dict[str, Any]]) -> bool:
    return isinstance(task, dict) and task.get("__control__") == "shutdown"
