"""Device runtime — the mesh-owning successor of the reference's interpreter
singleton (reference ``ops/_tpu_runtime.py:34-63``).

The reference's L2 was a process-wide Edge-TPU interpreter cache keyed by model
path: one native handle, loaded lazily, shared by every op invocation. The
TPU-native inversion (BASELINE.json north star) is that the *mesh* is the
execution substrate: this package owns

- platform/backend selection (proof-based, like reference
  ``worker_sizing.py:203-213`` — we only claim what ``jax.devices()`` shows),
- :class:`~agent_tpu.runtime.mesh.MeshSpec` / mesh construction over the
  canonical ``(dp, tp, sp)`` axes,
- an executable cache keyed by (op, static shape key) — the successor of the
  interpreter singleton, except a "handle" is now an XLA executable
  (:mod:`agent_tpu.runtime.executor`),
- a params store: model weights resident in HBM keyed by model id (the
  ``TPUHandle`` cache generalized, reference ``_tpu_runtime.py:8-13``),
- :class:`OpContext`, the optional ``ctx`` every op accepts.

Everything works identically on the CPU backend — ``allow_fallback`` semantics
(reference ``ops/map_classify_tpu.py:84-90``) are "same program, different
backend", not a second code path.
"""

from agent_tpu.runtime.context import OpContext
from agent_tpu.runtime.executor import ExecutableCache
from agent_tpu.runtime.mesh import MeshSpec, build_mesh
from agent_tpu.runtime.runtime import TpuRuntime, get_runtime, reset_runtime

__all__ = [
    "ExecutableCache",
    "MeshSpec",
    "OpContext",
    "TpuRuntime",
    "build_mesh",
    "get_runtime",
    "reset_runtime",
]
