"""Mesh construction over the canonical ``(dp, tp, sp)`` axes.

Axis vocabulary (fixed across the framework so every sharding spec and
collective agrees):

- ``dp`` — data parallelism: batch rows sharded, params replicated.
- ``tp`` — tensor/model parallelism: heads and MLP hidden sharded.
- ``sp`` — sequence/context parallelism: the sequence axis for ring attention
  (SURVEY.md §5.7).

An expert axis (``ep``) is deliberately *not* pre-created but nothing below
assumes three axes — :func:`build_mesh` takes any ordered axis dict, so an MoE
model can build its own mesh (SURVEY.md §2.8: "mesh design must not preclude
it").

The reference had no mesh — its device model was one Edge TPU behind one
interpreter (reference ``ops/_tpu_runtime.py:34-63``). The mesh shape here comes
from ``DeviceConfig.mesh_shape`` (``MESH_SHAPE="dp=4,tp=2"``) or is derived from
the device count (everything on ``dp`` — the right default for the map-style ops
this swarm runs).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

# Canonical axis order. dp outermost: DCN/ICI-friendliest for pure-data work,
# and the axis most collectives (psum of partials) ride.
AXES: Tuple[str, ...] = ("dp", "tp", "sp")


@dataclass(frozen=True)
class MeshSpec:
    """A validated mesh shape: ordered axis name → size, covering all devices."""

    axes: Tuple[Tuple[str, int], ...] = field(default_factory=tuple)

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.axes)

    @property
    def sizes(self) -> Tuple[int, ...]:
        return tuple(s for _, s in self.axes)

    @property
    def n_devices(self) -> int:
        n = 1
        for _, s in self.axes:
            n *= s
        return n

    @staticmethod
    def resolve(n_devices: int, shape: Optional[Dict[str, int]] = None) -> "MeshSpec":
        """Fill a possibly-partial shape dict into a full spec over n_devices.

        Unknown sizes (axes absent from ``shape``) default to 1, except ``dp``
        which absorbs every device not claimed by other axes. A shape that does
        not divide the device count is an error — silent truncation would strand
        chips.
        """
        shape = dict(shape or {})
        for name, size in shape.items():
            if not isinstance(size, int) or size <= 0:
                raise ValueError(f"mesh axis {name!r} must be a positive int, got {size!r}")
        extra = [n for n in shape if n not in AXES]
        names = AXES + tuple(extra)  # unknown axes appended innermost
        claimed = 1
        for n in names:
            if n != "dp" and n in shape:
                claimed *= shape[n]
        if n_devices % claimed:
            raise ValueError(
                f"mesh shape {shape} claims {claimed} devices per dp-slice but "
                f"{n_devices} devices are available (not divisible)"
            )
        dp = shape.get("dp", n_devices // claimed)
        sizes = {**{n: 1 for n in names}, **shape, "dp": dp}
        total = 1
        for n in names:
            total *= sizes[n]
        if total != n_devices:
            raise ValueError(
                f"mesh shape {shape} covers {total} devices, have {n_devices}"
            )
        return MeshSpec(axes=tuple((n, sizes[n]) for n in names))


def build_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    shape: Optional[Dict[str, int]] = None,
) -> Mesh:
    """Build a :class:`jax.sharding.Mesh` over ``devices`` with spec ``shape``.

    Device order is kept as given (``jax.devices()`` order respects ICI
    topology on TPU, so neighboring mesh coordinates are ICI neighbors — the
    property ring collectives need).
    """
    if devices is None:
        devices = jax.devices()
    spec = MeshSpec.resolve(len(devices), shape)
    grid = np.asarray(devices, dtype=object).reshape(spec.sizes)
    return Mesh(grid, spec.names)
