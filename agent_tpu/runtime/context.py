"""OpContext — the optional ``ctx`` argument every op accepts.

The reference's TPU op took an optional ``ctx`` dict it never used (reference
``ops/map_classify_tpu.py:32,44``). Here the context is the typed channel
through which the agent loop hands ops the device runtime and config; pure host
ops ignore it, device ops use ``ctx.runtime`` (falling back to the process
singleton when run standalone).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from agent_tpu.config import Config


@dataclass
class OpContext:
    runtime: Optional[object] = None   # TpuRuntime; object to keep import light
    config: Optional[Config] = None
    # Free-form per-task annotations (job id, trace tags); ops may add timings.
    tags: Dict[str, Any] = field(default_factory=dict)

    def require_runtime(self):
        """The runtime, building the process singleton if none was injected."""
        if self.runtime is None:
            from agent_tpu.runtime.runtime import get_runtime

            self.runtime = get_runtime(self.config.device if self.config else None)
        return self.runtime
