"""The device runtime object: owns the mesh, the executable cache, and HBM-
resident model params.

Successor of reference ``ops/_tpu_runtime.py`` (the Edge-TPU interpreter
singleton): `get_tpu_handle(model_path)` becomes :meth:`TpuRuntime.get_params`
(weights live in HBM keyed by model id) + :meth:`TpuRuntime.run` (a cached
pjit-compiled executable instead of ``interpreter.invoke()``). Detection stays
proof-based like reference ``worker_sizing.py:203-213``: we claim only the
platform ``jax.devices()`` actually reports; env vars are hints, never proof.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Hashable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from agent_tpu.config import DeviceConfig
from agent_tpu.runtime.executor import ExecutableCache
from agent_tpu.runtime.mesh import build_mesh
from agent_tpu.utils.logging import log


def parse_chip_slice(spec: str) -> Tuple[int, int]:
    """``"start:count"`` → ``(start, count)``, strictly validated.

    The slice grammar is deliberately tiny (two non-negative ints, count
    ≥ 1): a fleet launcher computes these, and a typo must fail the agent at
    boot — an agent silently running on the wrong chips would corrupt the
    whole fleet's placement arithmetic.
    """
    parts = spec.split(":")
    if len(parts) != 2:
        raise ValueError(
            f"CHIP_SLICE must be 'start:count', got {spec!r}"
        )
    try:
        start, count = int(parts[0]), int(parts[1])
    except ValueError as exc:
        raise ValueError(
            f"CHIP_SLICE must be 'start:count' ints, got {spec!r}"
        ) from exc
    if start < 0 or count < 1:
        raise ValueError(
            f"CHIP_SLICE needs start >= 0 and count >= 1, got {spec!r}"
        )
    return start, count


def apply_chip_slice(devices: Sequence, spec: str) -> list:
    """The ``[start, start+count)`` slice of ``devices`` — the device-pinning
    primitive of fleet mode (ISSUE 7). Out-of-range slices raise: truncating
    silently would run a 2-chip agent on 1 chip and skew every per-chip
    number derived from its leases."""
    start, count = parse_chip_slice(spec)
    if start + count > len(devices):
        raise ValueError(
            f"CHIP_SLICE {spec!r} wants devices [{start}, {start + count}) "
            f"but only {len(devices)} are visible"
        )
    return list(devices)[start:start + count]


def detect_platform(tpu_disabled: bool = False) -> str:
    """The platform we can *prove* we have: 'tpu' only if jax.devices() shows
    TPU devices (and the TPU_DISABLED kill-switch is off); else jax's default
    backend ('cpu'/'gpu'). Mirrors reference worker_sizing.py:195-213.

    With the kill-switch on we return 'cpu' *without* querying the default
    backend at all — ``jax.devices()`` would initialize the TPU plugin (HBM
    prealloc, possible hang on a wedged chip), which is exactly what the
    switch exists to prevent.
    """
    if tpu_disabled:
        return "cpu"
    try:
        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001 — no backend at all ⇒ cpu fallback
        return "cpu"


class TpuRuntime:
    """One process-wide runtime: mesh + executable cache + HBM params store.

    Single-owner-of-the-device invariant (SURVEY.md §5.2): exactly one runtime
    owns the mesh; host threads stage data but never touch device state except
    through this object.
    """

    def __init__(
        self,
        config: Optional[DeviceConfig] = None,
        devices: Optional[Sequence[jax.Device]] = None,
    ) -> None:
        self.config = config or DeviceConfig()
        if self.config.compile_cache_dir:
            # Persistent XLA compile cache: restarts skip recompiles (§5.4).
            jax.config.update("jax_compilation_cache_dir", self.config.compile_cache_dir)
        # Multi-host: join the coordination service BEFORE device discovery so
        # jax.devices() reports the global slice (SURVEY.md §5.8).
        from agent_tpu.runtime.distributed import maybe_initialize

        self.dist = maybe_initialize(
            self.config.coordinator_address,
            self.config.num_processes,
            self.config.process_id,
        )
        if devices is None:
            platform = detect_platform(self.config.tpu_disabled)
            devices = jax.devices(platform)
            if self.config.chip_slice:
                # Device-pinned fleet member (ISSUE 7): own only this
                # process's slice of the host's devices. Explicit `devices`
                # callers already chose, so the slice applies only to the
                # discovery path.
                devices = apply_chip_slice(devices, self.config.chip_slice)
        self.devices = list(devices)
        self.platform = self.devices[0].platform
        if self.config.profile_port:
            # Live XProf endpoint (SURVEY.md §5.1): `xprof --port` /
            # TensorBoard can attach to capture device traces on demand.
            jax.profiler.start_server(self.config.profile_port)
        self.mesh: Mesh = build_mesh(self.devices, self.config.mesh_shape)
        self.cache = ExecutableCache()
        # Build-once dedup like executables, but NOT a compile: params
        # builds are HBM transfers and stay out of the xla.compile series.
        self._params = ExecutableCache(trace_label=None)
        self._model_ids: set = set()
        self._params_lock = threading.Lock()
        self._attention_fn = None
        self._train_attention_fn = None
        self._t5_kernel = None
        self._t5_kernel_built = False
        self.compute_dtype = self.config.compute_dtype

    # ---- topology ----

    @property
    def n_devices(self) -> int:
        return len(self.devices)

    def axis_size(self, name: str) -> int:
        return self.mesh.shape.get(name, 1)

    # ---- shardings ----

    def sharding(self, *spec: Any) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def data_sharding(self) -> NamedSharding:
        """Batch-dim-sharded over dp; trailing dims replicated."""
        return self.sharding("dp")

    def attention_fn(self):
        """The attention kernel for this mesh and platform.

        Selection (built once per runtime; kept out of the executable cache so
        its stats keep meaning "compiled programs"):

        - mesh has an ``sp`` axis > 1 → ring attention over ``sp``
          (``agent_tpu.parallel.ring``);
        - real TPU (and ``PALLAS_ATTN`` not disabled) → the fused Pallas
          flash kernel (``agent_tpu.kernels.flash_attention``);
        - otherwise → the dense XLA dot-product path.

        Each choice silently degrades to dense for unsupported shapes, so the
        returned callable is always a safe drop-in ``attn_fn``.
        """
        if self._attention_fn is None:
            if self.axis_size("sp") > 1:
                from agent_tpu.parallel.ring import make_ring_attention

                self._attention_fn = make_ring_attention(self.mesh)
            elif self.platform == "tpu" and self.config.pallas_attn:
                from agent_tpu.kernels import make_flash_attention

                self._attention_fn = make_flash_attention(self.mesh)
            else:
                from agent_tpu.models.layers import dot_product_attention

                self._attention_fn = dot_product_attention
        return self._attention_fn

    def train_attention_fn(self):
        """The DIFFERENTIABLE attention kernel for the training path.

        Same platform gate as :meth:`attention_fn`, but selects
        ``kernels.make_flash_attention_trainable`` — the ``custom_vjp``
        variant whose backward is also a Pallas kernel — instead of the
        forward-only inference kernel (which autodiff cannot trace through).
        Ring attention (``sp`` > 1) is forward-only today, so sp meshes train
        on the dense path; both flash and dense degrade to dense for
        unsupported shapes, keeping the return a safe drop-in ``attn_fn``.
        """
        if self._train_attention_fn is None:
            if (
                self.platform == "tpu"
                and self.config.pallas_attn
                and self.axis_size("sp") == 1
            ):
                from agent_tpu.kernels import make_flash_attention_trainable

                self._train_attention_fn = make_flash_attention_trainable(
                    self.mesh
                )
            else:
                from agent_tpu.models.layers import dot_product_attention

                self._train_attention_fn = dot_product_attention
        return self._train_attention_fn

    def t5_attention_kernel(self):
        """The fused T5 bias-attention kernel for this mesh, or ``None``.

        T5's encoder self-attention carries a bucketed relative-position
        bias, so it cannot ride the generic :meth:`attention_fn`; it has its
        own Pallas kernel (``kernels.flash_attention_t5``, bias computed per
        tile in VMEM) and mesh wrapper (``make_flash_attention_t5`` — batch
        over dp, heads over tp). Same platform gate as the generic kernel.
        ``None`` means "dense path" (``t5.encode`` builds the dense bias
        lazily); the kernel itself also declines unsupported shapes at
        trace time, ticking the ``t5_dense`` selection counter.
        """
        if not self._t5_kernel_built:
            self._t5_kernel_built = True
            if self.platform == "tpu" and self.config.pallas_attn:
                from agent_tpu.kernels.flash_attention import (
                    make_flash_attention_t5,
                )

                self._t5_kernel = make_flash_attention_t5(self.mesh)
        return self._t5_kernel

    def replicated(self) -> NamedSharding:
        return self.sharding()

    # ---- params store (TPUHandle cache generalized) ----

    def get_params(
        self,
        model_id: str,
        build: Callable[[], Any],
        specs: Any = None,
    ) -> Any:
        """Weights resident on device, built once per process per model id.

        ``build()`` returns a pytree. Leaves that are already device-committed
        ``jax.Array``\\ s (a model that sharded its own params over tp) are left
        exactly as built; host leaves (numpy) are placed on the mesh —
        **sharded** per ``specs`` (a PartitionSpec pytree, e.g.
        ``parallel.shardings.encoder_param_specs``) when the mesh has a
        model-parallel axis > 1, replicated otherwise. This is how the serving
        path runs models that exceed one chip's HBM (SURVEY.md §2.8 TP row):
        the op passes its spec tree and XLA inserts the tp collectives in the
        forward. Leaves whose dims don't divide the mesh replicate (see
        ``shardings.sanitize_specs``). Build-once dedup rides the same
        per-key-event cache as executables, so concurrent first callers
        trigger exactly one build / one HBM transfer.
        """
        # Any model-parallel axis (tp for dense Megatron sharding, ep for
        # MoE expert sharding) activates spec placement; sanitize_specs
        # strips axes the mesh doesn't carry.
        use_specs = specs is not None and (
            self.axis_size("tp") > 1 or self.axis_size("ep") > 1
        )

        def place() -> Any:
            host = build()
            if not use_specs:
                return jax.tree_util.tree_map(
                    lambda leaf: leaf
                    if isinstance(leaf, jax.Array) and leaf.committed
                    else jax.device_put(leaf, self.replicated()),
                    host,
                )
            from agent_tpu.parallel.shardings import sanitize_specs

            safe = sanitize_specs(self.mesh, host, specs)

            def put(leaf, spec):
                if isinstance(leaf, jax.Array) and leaf.committed:
                    return leaf
                return jax.device_put(leaf, NamedSharding(self.mesh, spec))

            return jax.tree_util.tree_map(
                put, host, safe, is_leaf=lambda x: isinstance(x, P)
            )

        with self._params_lock:
            self._model_ids.add(model_id)
        # Placement mode is part of the identity: the same model id requested
        # replicated and tp-sharded must not alias one cache entry.
        key = ("params", model_id, "tp" if use_specs else "rep")
        return self._params.get_or_build(key, place)

    def evict_params(self, model_id: str) -> None:
        with self._params_lock:
            self._model_ids.discard(model_id)
        # Both placement modes: the id may be resident sharded or replicated.
        self._params.evict(("params", model_id, "tp"))
        self._params.evict(("params", model_id, "rep"))

    def clear_params(self) -> None:
        """Drop EVERY resident model from the HBM params store.

        The store is append-only by design (serving re-uses hot weights),
        so a workload that cycles through many large one-off models — the
        bench's 8-expert MoE tree is ~2 GB — must be able to give the HBM
        back: without this, the r4 bench's later train legs hit
        RESOURCE_EXHAUSTED on a 16 GB chip. Freeing is by reference drop;
        the next ``get_params`` for any id simply re-transfers.
        """
        with self._params_lock:
            self._model_ids.clear()
        self._params.clear()

    # ---- compiled execution ----

    def compiled(
        self,
        key: Tuple[Hashable, ...],
        build: Callable[[], Callable],
    ) -> Callable:
        """Executable for ``key``, compiling at most once (see ExecutableCache)."""
        return self.cache.get_or_build(key, build)

    def _model_ids_snapshot(self) -> set:
        with self._params_lock:
            return set(self._model_ids)

    def put_batch(self, arr: np.ndarray) -> jax.Array:
        """Host batch → device, batch dim sharded over dp.

        The batch dim must divide the dp axis — callers pad with
        ``pad_batch(batch_buckets=...)`` so this holds by construction.
        """
        return jax.device_put(arr, self.data_sharding())

    def peak_flops(self) -> Optional[float]:
        """Peak dense-bf16 FLOP/s of one device (MFU denominator, ISSUE 8):
        the ``PEAK_TFLOPS`` env override first, else the public spec-sheet
        table keyed by device_kind; None when unknown — MFU is then simply
        not exported, never guessed."""
        from agent_tpu.obs.health import resolve_peak_flops

        return resolve_peak_flops(self)

    def describe(self) -> Dict[str, Any]:
        """Telemetry snapshot for the lease metrics channel (SURVEY.md §5.5)."""
        out: Dict[str, Any] = {
            "platform": self.platform,
            "n_devices": self.n_devices,
            "mesh": dict(self.mesh.shape),
            "compute_dtype": self.compute_dtype,
            # Fleet-default quantized execution mode (TPU_QUANT via
            # DeviceConfig.quant): operators can see from lease telemetry
            # whether a worker serves int8/w8a16 by default. Per-task
            # resolution stays in ops/_model_common.apply_quant_env.
            "quant_default": self.config.quant or "none",
            "executable_cache": self.cache.stats(),
            "models_resident": sorted(self._model_ids_snapshot()),
        }
        if self.config.chip_slice:
            # Fleet mode (ISSUE 7): which slice of the host this runtime
            # owns — rides the lease telemetry so the controller's fleet
            # view can attribute chips per agent.
            out["chip_slice"] = self.config.chip_slice
        # HBM telemetry across ALL owned devices (ISSUE 9 satellite — the
        # old probe read only devices[0], so a CHIP_SLICE fleet member or
        # dp=N mesh agent attributed memory for one chip out of N). The
        # legacy keys become fleet-correct TOTALS; the per-device breakdown
        # rides alongside. Absent entirely on backends without stats (CPU).
        from agent_tpu.obs.profile import hbm_totals

        try:
            hbm = hbm_totals(self.devices)
        except Exception:  # noqa: BLE001 — telemetry must never raise
            hbm = None
        if hbm:
            if "used" in hbm:
                out["hbm_bytes_in_use"] = hbm["used"]
            if "limit" in hbm:
                out["hbm_bytes_limit"] = hbm["limit"]
            if "peak" in hbm:
                out["hbm_peak_bytes"] = hbm["peak"]
            out["hbm_per_device"] = hbm["per_device"]
        return out


# Process-wide singleton, lazily built (reference _tpu_runtime.py:34-43 pattern).
_runtime: Optional[TpuRuntime] = None
_runtime_lock = threading.Lock()


def get_runtime(config: Optional[DeviceConfig] = None) -> TpuRuntime:
    global _runtime
    with _runtime_lock:
        if _runtime is None:
            _runtime = TpuRuntime(config)
            log(
                "runtime up",
                platform=_runtime.platform,
                devices=_runtime.n_devices,
                mesh=dict(_runtime.mesh.shape),
            )
        return _runtime


def reset_runtime() -> None:
    """Tests only: drop the singleton so the next get_runtime rebuilds."""
    global _runtime
    with _runtime_lock:
        _runtime = None
