"""Partitioned control plane: consistent-hash placement + routing core
(ISSUE 18).

One GIL-bound controller process is both a throughput ceiling and a blast
radius. This module shards the control plane into N independent
``Controller`` partitions — each with its own segmented journal, snapshot
cadence, and (optionally) hot standby, exactly the PR 11 machinery,
instantiated N times on distinct journal paths — and provides the
*stateless* routing brain that hides the topology from clients and agents:

- ``HashRing``: rendezvous (highest-random-weight) hashing over
  ``hashlib.blake2b`` digests. Deterministic across processes and Python
  builds (never the builtin ``hash()``, which PYTHONHASHSEED perturbs),
  and minimal-remap by construction: adding or removing one of N members
  moves only the keys whose argmax changed, ~1/N of them.
- ``placement_key(tenant, job_id)``: jobs shard by ``{tenant, job_id}``.
  Serve traffic routes by tenant alone — serving bucket keys already
  include the tenant, so whole buckets land on one home partition and
  coalescing stays intact.
- ``RouterCore``: the transport-agnostic routing logic shared by the HTTP
  router process (``controller/router.py``) and by agents running with an
  explicit partition map (``PartitionSession`` below). Stateless by
  design: every decision is a pure function of the request plus a cached
  depth sample; any number of router replicas can front the same
  partitions.

Lease handoff and idempotency: a granted lease's ``lease_id`` comes back
tagged ``<partition>!<lease_id>`` so the result post (and any spool
redelivery of it — the spool stores the tagged id) routes to the partition
that granted the lease, home or stolen. Job state never moves between
partitions: "stealing" is an idle agent *polling* a deeper partition, so a
stolen job that races its home lease resolves first-wins inside the owning
partition via the existing epoch fence and terminal-state duplicate guard.
"""

from __future__ import annotations

import hashlib
import threading
import time
import uuid
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

from agent_tpu.config import env_str
from agent_tpu.sched.base import DEFAULT_TENANT
from agent_tpu.sched.steal import StealPolicy

# Separates the granting partition's name from its native lease id in the
# tagged ids the router hands out. Safe: partition names reject it at
# parse time and native lease ids are `lease-<hex>`.
LEASE_TAG_SEP = "!"

# (status, parsed-JSON-body) — transport failures raise OSError (covers
# urllib URLError, socket timeouts, requests' RequestException, and the
# chaos harness's ChaosTransportError).
PostFn = Callable[[str, str, Dict[str, Any], float], Tuple[int, Any]]
GetFn = Callable[[str, str, float], Tuple[int, Any]]


def stable_hash(text: str) -> int:
    """64-bit digest that is identical in every process. The builtin
    ``hash()`` is salted per-process (PYTHONHASHSEED) and would scatter a
    job's home partition across restarts."""
    return int.from_bytes(
        hashlib.blake2b(text.encode("utf-8"), digest_size=8).digest(), "big"
    )


def placement_key(tenant: Optional[str], job_id: str) -> str:
    """Jobs shard by ``{tenant, job_id}``; 0x1f keeps ``("ab","c")`` and
    ``("a","bc")`` distinct."""
    return f"{tenant or DEFAULT_TENANT}\x1f{job_id}"


class HashRing:
    """Rendezvous-hash placement over a set of partition names.

    ``place(key)`` picks the member maximizing ``blake2b(member, key)`` —
    deterministic, uniform, and minimal-remap: membership changes move
    only keys whose winning member appeared/vanished (~1/N of them),
    which the ring-stability property test pins.
    """

    def __init__(self, members: Iterable[str]) -> None:
        self._members: List[str] = []
        for m in members:
            self.add(m)
        if not self._members:
            raise ValueError("HashRing needs at least one member")

    @property
    def members(self) -> Tuple[str, ...]:
        return tuple(self._members)

    def add(self, member: str) -> None:
        member = str(member)
        if LEASE_TAG_SEP in member or not member:
            raise ValueError(f"bad partition name {member!r}")
        if member not in self._members:
            self._members.append(member)
            self._members.sort()

    def remove(self, member: str) -> None:
        self._members.remove(member)
        if not self._members:
            raise ValueError("HashRing cannot become empty")

    def place(self, key: str) -> str:
        # Ties are astronomically unlikely at 64 bits but the (score,
        # name) tuple makes the argmax total-ordered regardless.
        return max(
            self._members,
            key=lambda m: (stable_hash(f"{m}\x1f{key}"), m),
        )


class PartitionMap:
    """Partition name -> ordered failover URL list.

    Spec grammar (``PARTITION_URLS``)::

        p0=http://host:8080|http://standby:8081,p1=http://host:8082

    Bare URLs are also accepted (``http://a,http://b``) and named
    ``p0..pN-1`` in order. The ``|``-separated alternates per partition
    are tried in order on transport failure — the slot a promoted hot
    standby serves on.
    """

    def __init__(self, partitions: Mapping[str, Sequence[str]]) -> None:
        if not partitions:
            raise ValueError("PartitionMap needs at least one partition")
        self._urls: Dict[str, List[str]] = {}
        for name, urls in partitions.items():
            name = str(name)
            if LEASE_TAG_SEP in name or not name:
                raise ValueError(f"bad partition name {name!r}")
            cleaned = [str(u).rstrip("/") for u in urls if str(u).strip()]
            if not cleaned:
                raise ValueError(f"partition {name!r} has no URLs")
            self._urls[name] = cleaned
        self.ring = HashRing(self._urls)

    @classmethod
    def parse(cls, spec: str) -> "PartitionMap":
        out: Dict[str, List[str]] = {}
        unnamed = 0
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            if "=" in entry and not entry.split("=", 1)[0].startswith("http"):
                name, urls = entry.split("=", 1)
                name = name.strip()
            else:
                name, urls = f"p{unnamed}", entry
                unnamed += 1
            out.setdefault(name, []).extend(
                u.strip() for u in urls.split("|") if u.strip()
            )
        return cls(out)

    @classmethod
    def from_env(cls) -> Optional["PartitionMap"]:
        spec = env_str("PARTITION_URLS", "").strip()
        return cls.parse(spec) if spec else None

    @property
    def names(self) -> Tuple[str, ...]:
        return self.ring.members

    def urls(self, name: str) -> List[str]:
        return list(self._urls[name])

    def __len__(self) -> int:
        return len(self._urls)


def job_id_for_partition(
    ring: HashRing,
    target: str,
    tenant: Optional[str] = None,
    prefix: str = "job",
    start: int = 0,
    limit: int = 100000,
) -> str:
    """A job id that the ring places on ``target`` — how tests and the
    smoke craft skewed load against one partition deterministically."""
    for i in range(start, start + limit):
        jid = f"{prefix}-{i}"
        if ring.place(placement_key(tenant, jid)) == target:
            return jid
    raise RuntimeError(f"no id landing on {target} within {limit} tries")


class PartitionDown(ConnectionError):
    """Every URL of the required partition failed at the transport."""

    def __init__(self, partition: str, last: Optional[BaseException]) -> None:
        super().__init__(f"partition {partition} unreachable: {last}")
        self.partition = partition


class RouterCore:
    """The stateless routing brain over a ``PartitionMap``.

    All state here is *soft*: per-partition URL rotation indices (which
    alternate answered last), a TTL-bounded depth sample for steal
    decisions, and monotonic counters for observability. Losing it all
    (router restart, second replica) changes nothing about correctness —
    placement is a pure hash and lease routing rides the tagged ids.
    """

    def __init__(
        self,
        pmap: PartitionMap,
        post_fn: PostFn,
        get_fn: Optional[GetFn] = None,
        steal: Optional[StealPolicy] = None,
        depth_cache_sec: float = 0.25,
        timeout_sec: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.pmap = pmap
        self.steal = steal if steal is not None else StealPolicy()
        self._post = post_fn
        self._get = get_fn
        self._timeout = timeout_sec
        self._clock = clock
        self._lock = threading.Lock()
        self._url_index: Dict[str, int] = {n: 0 for n in pmap.names}
        self._depths: Dict[str, Optional[int]] = {}
        self._depths_at = -1e9
        self._depth_cache_sec = max(0.0, depth_cache_sec)
        self.counters: Dict[str, int] = {
            "submits_total": 0,
            "rejects_429_total": 0,
            "lease_grants_home_total": 0,
            "lease_grants_stolen_total": 0,
            "results_routed_total": 0,
            "results_fanout_total": 0,
            "partition_failovers_total": 0,
        }

    # ---- placement ----

    def home_for_job(self, tenant: Optional[str], job_id: str) -> str:
        return self.pmap.ring.place(placement_key(tenant, job_id))

    def home_for_tenant(self, tenant: Optional[str]) -> str:
        # Serve buckets key on the tenant, so the whole tenant routes as a
        # unit and partition-local coalescing keeps working.
        return self.pmap.ring.place(f"tenant\x1f{tenant or DEFAULT_TENANT}")

    def home_for_agent(self, agent: str) -> str:
        return self.pmap.ring.place(f"agent\x1f{agent}")

    # ---- transport with per-partition URL failover ----

    def post_partition(
        self, name: str, path: str, body: Dict[str, Any]
    ) -> Tuple[int, Any]:
        urls = self.pmap.urls(name)
        with self._lock:
            start = self._url_index.get(name, 0)
        last: Optional[BaseException] = None
        for attempt in range(len(urls)):
            url = urls[(start + attempt) % len(urls)]
            try:
                status, parsed = self._post(url, path, body, self._timeout)
            except OSError as exc:
                last = exc
                with self._lock:
                    # Rotate only if nobody beat us to it (same benign
                    # race rule as the agent's controller failover).
                    if self._url_index.get(name, 0) == (
                        (start + attempt) % len(urls)
                    ):
                        self._url_index[name] = (
                            (start + attempt + 1) % len(urls)
                        )
                    self.counters["partition_failovers_total"] += 1
                continue
            return status, parsed
        raise PartitionDown(name, last)

    def get_partition(self, name: str, path: str) -> Tuple[int, Any]:
        if self._get is None:
            raise PartitionDown(name, None)
        urls = self.pmap.urls(name)
        with self._lock:
            start = self._url_index.get(name, 0)
        last: Optional[BaseException] = None
        for attempt in range(len(urls)):
            url = urls[(start + attempt) % len(urls)]
            try:
                return self._get(url, path, self._timeout)
            except OSError as exc:
                last = exc
                continue
        raise PartitionDown(name, last)

    # ---- write-path routing ----

    def route_submit(self, body: Dict[str, Any]) -> Tuple[int, Any]:
        """POST /v1/jobs. Single submits place by ``{tenant, job_id}`` —
        the router mints the id when the client didn't, so placement stays
        a pure function and a client retry with the same id lands on the
        same partition (preserving the duplicate-id exactly-once ack). CSV
        map-reduce submits place as one unit by ``{tenant, source_uri}``:
        shards and their reduce must share a partition for dep-gating."""
        tenant = body.get("tenant") or DEFAULT_TENANT
        if body.get("source_uri"):
            name = self.pmap.ring.place(
                placement_key(tenant, f"csv\x1f{body['source_uri']}")
            )
        else:
            job_id = body.get("job_id") or f"job-{uuid.uuid4().hex[:12]}"
            body = dict(body, job_id=job_id)
            name = self.home_for_job(tenant, job_id)
        status, parsed = self.post_partition(name, "/v1/jobs", body)
        with self._lock:
            self.counters["submits_total"] += 1
            if status == 429:
                self.counters["rejects_429_total"] += 1
        if isinstance(parsed, dict):
            # 429s aggregate trivially: only the home partition was asked,
            # so its verdict (and retry_after_ms) IS the answer; the stamp
            # lets loadgen count drops per partition.
            parsed.setdefault("partition", name)
        return status, parsed

    def route_workflow(self, body: Dict[str, Any]) -> Tuple[int, Any]:
        """POST /v1/workflows. A DAG places as ONE unit by
        ``{tenant, workflow_id}`` — the CSV ``source_uri`` rule generalized:
        every stage job of a graph must share a partition or cross-partition
        dep edges would never release. The router mints the workflow id when
        the client didn't so placement stays a pure function and a client
        retry with the same id lands on the same partition."""
        tenant = body.get("tenant") or DEFAULT_TENANT
        workflow_id = body.get("workflow_id") or f"wf-{uuid.uuid4().hex[:12]}"
        body = dict(body, workflow_id=workflow_id)
        name = self.pmap.ring.place(
            placement_key(tenant, f"wf\x1f{workflow_id}")
        )
        status, parsed = self.post_partition(name, "/v1/workflows", body)
        with self._lock:
            self.counters["submits_total"] += 1
            if status == 429:
                self.counters["rejects_429_total"] += 1
        if isinstance(parsed, dict):
            parsed.setdefault("partition", name)
        return status, parsed

    def route_infer(self, body: Dict[str, Any]) -> Tuple[int, Any]:
        tenant = body.get("tenant") or (
            (body.get("params") or {}).get("tenant")
            if isinstance(body.get("params"), dict) else None
        )
        name = self.home_for_tenant(tenant)
        status, parsed = self.post_partition(name, "/v1/infer", body)
        if isinstance(parsed, dict):
            parsed.setdefault("partition", name)
        return status, parsed

    def route_lease(self, body: Dict[str, Any]) -> Tuple[int, Any]:
        """POST /v1/leases: home partition first; an empty home plus a
        sufficiently deeper foreign queue steals one poll there. The
        granted ``lease_id`` comes back tagged with the granting
        partition so the result finds its way home."""
        agent = str(body.get("agent") or "")
        home = self.home_for_agent(agent)
        home_down: Optional[PartitionDown] = None
        try:
            status, parsed = self.post_partition(home, "/v1/leases", body)
        except PartitionDown as exc:
            # A dead home partition must NOT strand its agents — they fall
            # through to stealing from survivors (pick_victim treats an
            # unreachable home as depth 0, so any survivor with work
            # qualifies). This is the partition-kill survivability bar:
            # surviving partitions keep granting within one poll interval.
            home_down = exc
            status, parsed = 204, None
        requested = body.get("max_tasks")
        if self._granted(status, parsed):
            with self._lock:
                self.counters["lease_grants_home_total"] += 1
            return status, self._tag_lease(home, parsed)
        if requested == 0:
            # Metrics-push / spool-flush poll: a heartbeat, not a request
            # for work — never escalate it into a steal.
            if home_down is not None:
                raise home_down
            return status, parsed
        victim = self.steal.pick_victim(home, self.leasable_depths())
        if victim is None:
            if home_down is not None:
                raise home_down
            return status, parsed
        try:
            v_status, v_parsed = self.post_partition(
                victim, "/v1/leases", body
            )
        except PartitionDown:
            if home_down is not None:
                raise home_down
            return status, parsed
        if self._granted(v_status, v_parsed):
            with self._lock:
                self.counters["lease_grants_stolen_total"] += 1
            return v_status, self._tag_lease(victim, v_parsed)
        # Victim reachable but empty: an honest 204 — the agent polls
        # again shortly, which beats a 503-driven backoff even when the
        # home partition is dark.
        return status, parsed

    def route_result(self, body: Dict[str, Any]) -> Tuple[int, Any]:
        """POST /v1/results: tagged lease ids route straight to the
        partition that granted the lease (stolen or home — the spool keeps
        the tag, so redelivery follows the applying partition). Untagged
        ids (direct-to-partition agents, hand-written clients) fan out
        until some partition recognizes the job."""
        lease_id = str(body.get("lease_id") or "")
        if LEASE_TAG_SEP in lease_id:
            name, raw = lease_id.split(LEASE_TAG_SEP, 1)
            if name in self.pmap.names:
                status, parsed = self.post_partition(
                    name, "/v1/results", dict(body, lease_id=raw)
                )
                with self._lock:
                    self.counters["results_routed_total"] += 1
                return status, parsed
        with self._lock:
            self.counters["results_fanout_total"] += 1
        last: Tuple[int, Any] = (404, {"accepted": False,
                                       "reason": "unknown job"})
        down: Optional[PartitionDown] = None
        for name in self.pmap.names:
            try:
                status, parsed = self.post_partition(
                    name, "/v1/results", body
                )
            except PartitionDown as exc:
                down = exc
                continue
            if not isinstance(parsed, dict):
                last = (status, parsed)
                continue
            if parsed.get("accepted") or parsed.get("reason") not in (
                "unknown job", None
            ):
                return status, parsed
            last = (status, parsed)
        if down is not None and last[1].get("reason") == "unknown job":
            # The owner might be the unreachable partition — surface a
            # transport error so the agent spools and retries instead of
            # dropping the result on a false "unknown job".
            raise down
        return last

    # ---- steal support ----

    def leasable_depths(self) -> Dict[str, Optional[int]]:
        """Per-partition leasable queue depth, cached ``depth_cache_sec``
        — the steal decision's input. Unreachable partitions sample as
        None (never stolen from)."""
        now = self._clock()
        with self._lock:
            if now - self._depths_at < self._depth_cache_sec and self._depths:
                return dict(self._depths)
        depths: Dict[str, Optional[int]] = {}
        for name in self.pmap.names:
            try:
                status, parsed = self.get_partition(name, "/v1/depth")
            except (PartitionDown, OSError):
                depths[name] = None
                continue
            if status == 200 and isinstance(parsed, dict):
                depths[name] = int(
                    parsed.get("leasable", parsed.get("queue_depth", 0))
                )
            else:
                depths[name] = None
        with self._lock:
            self._depths = dict(depths)
            self._depths_at = now
        return depths

    # ---- helpers ----

    @staticmethod
    def _granted(status: int, parsed: Any) -> bool:
        return (
            status == 200
            and isinstance(parsed, dict)
            and bool(parsed.get("tasks"))
            and bool(parsed.get("lease_id"))
        )

    @staticmethod
    def _tag_lease(name: str, parsed: Dict[str, Any]) -> Dict[str, Any]:
        return dict(
            parsed, lease_id=f"{name}{LEASE_TAG_SEP}{parsed['lease_id']}"
        )

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "partitions": list(self.pmap.names),
                "steal": {
                    "enabled": self.steal.enabled,
                    "min_advantage": self.steal.min_advantage,
                },
                **dict(self.counters),
            }


class _ShimResponse:
    """requests.Response-shaped wrapper for ``PartitionSession``."""

    def __init__(self, status_code: int, body: Any) -> None:
        self.status_code = int(status_code)
        self._body = body

    def json(self) -> Any:
        return self._body

    @property
    def text(self) -> str:
        import json as _json

        try:
            return _json.dumps(self._body)
        except (TypeError, ValueError):
            return str(self._body)


class PartitionSession:
    """Agent-side partition map: an in-process router shim.

    When ``CONTROLLER_PARTITION_MAP`` is set, the agent wraps its HTTP
    session in one of these and keeps the rest of its loop untouched —
    ``lease_once``/``post_result``/``flush_spool`` post to the same paths
    they always did, and the shim runs the identical ``RouterCore`` logic
    the standalone router runs (home-first lease, steal, tagged lease ids,
    result routing by tag). Spooled results carry the tagged id, so
    redelivery follows the stolen job's applying partition with zero new
    spool machinery.
    """

    def __init__(
        self,
        inner: Any,
        pmap: PartitionMap,
        steal: Optional[StealPolicy] = None,
        timeout_sec: float = 10.0,
    ) -> None:
        self._inner = inner

        def post_fn(
            url: str, path: str, body: Dict[str, Any], timeout: float
        ) -> Tuple[int, Any]:
            resp = inner.post(url + path, json=body, timeout=timeout)
            try:
                parsed = resp.json()
            except ValueError:
                parsed = None
            return resp.status_code, parsed

        def get_fn(
            url: str, path: str, timeout: float
        ) -> Tuple[int, Any]:
            getter = getattr(inner, "get", None)
            if getter is None:
                raise ConnectionError("session has no GET")
            resp = getter(url + path, timeout=timeout)
            try:
                parsed = resp.json()
            except ValueError:
                parsed = None
            return resp.status_code, parsed

        self.core = RouterCore(
            pmap, post_fn, get_fn=get_fn, steal=steal,
            timeout_sec=timeout_sec,
        )

    def post(
        self,
        url: str,
        json: Optional[Dict[str, Any]] = None,
        timeout: Optional[float] = None,
    ) -> _ShimResponse:
        from urllib.parse import urlsplit

        body = json or {}
        path = urlsplit(url).path or "/"
        if path.endswith("/v1/leases"):
            status, parsed = self.core.route_lease(body)
        elif path.endswith("/v1/results"):
            status, parsed = self.core.route_result(body)
        elif path.endswith("/v1/jobs"):
            status, parsed = self.core.route_submit(body)
        elif path.endswith("/v1/workflows"):
            status, parsed = self.core.route_workflow(body)
        elif path.endswith("/v1/infer"):
            status, parsed = self.core.route_infer(body)
        else:
            # Anything else goes to the first partition (debug surfaces).
            status, parsed = self.core.post_partition(
                self.core.pmap.names[0], path, body
            )
        return _ShimResponse(status, parsed)


class LocalPartitionSet:
    """N in-process partitions behind real HTTP — the harness tests, the
    smoke, and the router's convenience single-process mode share.

    Each partition is a full ``Controller`` (own journal at
    ``<journal_base>.<name>``, own sweeper, own metrics registry) served
    by its own ``ControllerServer`` on an ephemeral port.
    """

    def __init__(
        self,
        n: int,
        journal_base: Optional[str] = None,
        controller_kwargs: Optional[Dict[str, Any]] = None,
        host: str = "127.0.0.1",
    ) -> None:
        from agent_tpu.controller.core import Controller

        self.names = [f"p{i}" for i in range(max(1, int(n)))]
        self.controllers: Dict[str, Any] = {}
        self._host = host
        kwargs = dict(controller_kwargs or {})
        for name in self.names:
            per = dict(kwargs)
            if journal_base:
                per["journal_path"] = f"{journal_base}.{name}"
            self.controllers[name] = Controller(partition=name, **per)
        self.servers: Dict[str, Any] = {}
        self.pmap: Optional[PartitionMap] = None

    def start(self) -> "LocalPartitionSet":
        from agent_tpu.controller.server import ControllerServer

        for name in self.names:
            self.servers[name] = ControllerServer(
                self.controllers[name], host=self._host, port=0
            ).start()
        self.pmap = PartitionMap(
            {name: [self.servers[name].url] for name in self.names}
        )
        return self

    def stop(self) -> None:
        for server in self.servers.values():
            try:
                server.stop()
            except Exception:  # noqa: BLE001 — teardown must not mask
                pass
        for controller in self.controllers.values():
            try:
                controller.close()
            except Exception:  # noqa: BLE001
                pass

    def __enter__(self) -> "LocalPartitionSet":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
