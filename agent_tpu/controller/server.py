"""HTTP adapter for the controller core — the server side of SURVEY.md §2.9.

Speaks exactly the contract the agent client expects (and the reference client
at ``app.py:143-218`` spoke): JSON bodies, ``POST /v1/leases`` answering 200
``{lease_id, tasks}`` or 204 when idle, ``POST /v1/results`` answering 200
``{accepted: ...}``. Stdlib ``ThreadingHTTPServer`` — no framework dependency,
good enough for a single-process controller and for in-process tests.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from agent_tpu.controller.core import Controller
from agent_tpu.sched import AdmissionError


class _Handler(BaseHTTPRequestHandler):
    controller: Controller  # set by ControllerServer on the class it builds

    def log_message(self, *args: Any) -> None:  # silence per-request stderr spam
        pass

    def _read_json(self) -> Optional[Dict[str, Any]]:
        self._request_bytes = 0
        try:
            length = int(self.headers.get("Content-Length", "0"))
            raw = self.rfile.read(length) if length else b"{}"
            self._request_bytes = length
            body = json.loads(raw or b"{}")
        except (ValueError, OSError):
            return None
        return body if isinstance(body, dict) else None

    def _send(self, status: int, body: Optional[Dict[str, Any]] = None) -> int:
        self.send_response(status)
        if body is None:
            self.end_headers()
            return 0
        data = json.dumps(body, default=str).encode()
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)
        return len(data)

    def _send_text(self, status: int, text: str, content_type: str) -> None:
        data = text.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_admission(self, exc: AdmissionError) -> None:
        """Backpressure, not failure: 429 + retry_after_ms is the admission
        contract — classify_http already calls 429 transient, so an
        unmodified RetryPolicy backs off. Shared by /v1/jobs and /v1/infer."""
        self.send_response(429)
        data = json.dumps({
            "error": str(exc),
            "retry_after_ms": exc.retry_after_ms,
            "tenant": exc.tenant,
            "scope": exc.scope,
        }).encode()
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.send_header(
            "Retry-After",
            str(max(1, (exc.retry_after_ms + 999) // 1000)),
        )
        self.end_headers()
        self.wfile.write(data)

    # ---- online serving front door (ISSUE 15) ----

    def _infer_wait_timeout(self, body: Dict[str, Any]) -> float:
        """Client ``timeout_ms`` capped by the server's SERVE_WAIT_TIMEOUT."""
        cap = self.controller.serve_config.wait_timeout_sec
        raw = body.get("timeout_ms")
        if isinstance(raw, (int, float)) and not isinstance(raw, bool) \
                and raw > 0:
            return min(float(raw) / 1e3, cap)
        return cap

    def _stream_infer(self, req_id: str, timeout_sec: float) -> None:
        """Chunked NDJSON lifecycle stream: one JSON line per request state
        (``queued`` → ``batched`` → ``done``/``failed``), the terminal line
        carrying the result — the framing PROTOCOL.CONTRACT.md documents.
        Manual chunked framing: BaseHTTPRequestHandler won't do it for us."""
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(obj: Dict[str, Any]) -> None:
            data = (json.dumps(obj, default=str) + "\n").encode()
            self.wfile.write(
                f"{len(data):x}\r\n".encode() + data + b"\r\n"
            )
            self.wfile.flush()

        deadline = time.monotonic() + timeout_sec
        snap = self.controller.infer_snapshot(req_id)
        try:
            while snap is not None:
                chunk(snap)
                if snap["state"] in ("done", "failed"):
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    chunk({"req_id": req_id, "state": snap["state"],
                           "event": "timeout"})
                    break
                nxt = self.controller.wait_infer_change(
                    req_id, snap["state"], remaining
                )
                if nxt is None or nxt["state"] == snap["state"]:
                    continue
                snap = nxt
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up mid-stream; the request completes anyway

    def _handle_infer_post(self, body: Dict[str, Any]) -> None:
        try:
            req_id = self.controller.submit_infer(
                op=str(body.get("op", "")),
                text=body.get("text"),
                params=body.get("params")
                if isinstance(body.get("params"), dict) else None,
                tenant=(
                    str(body["tenant"])
                    if body.get("tenant") is not None else None
                ),
                priority=body.get("priority"),
            )
        except AdmissionError as exc:
            self._send_admission(exc)
            return
        except (RuntimeError, KeyError, ValueError, TypeError) as exc:
            disabled = isinstance(exc, RuntimeError)
            self._send(501 if disabled else 400, {"error": str(exc)})
            return
        timeout = self._infer_wait_timeout(body)
        if body.get("stream"):
            self._stream_infer(req_id, timeout)
        elif body.get("wait", True):
            self._send(200, self.controller.wait_infer(req_id, timeout))
        else:
            self._send(200, {"req_id": req_id, "state": "queued"})

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        body = self._read_json()
        if body is None:
            self._send(400, {"error": "invalid JSON body"})
            return
        if self.path == "/v1/leases":
            try:
                # max_tasks=0 is a metrics-only poll (the drain-end flush
                # channel) — it must NOT coerce to 1 like the old `or 1` did.
                raw_max = body.get("max_tasks")
                max_tasks = 1 if raw_max is None else int(raw_max)
            except (TypeError, ValueError):
                self._send(400, {"error": "max_tasks must be an int"})
                return
            lease = self.controller.lease(
                agent=str(body.get("agent", "")),
                capabilities=body.get("capabilities"),
                max_tasks=max_tasks,
                worker_profile=body.get("worker_profile"),
                metrics=body.get("metrics"),
                labels=body.get("labels")
                if isinstance(body.get("labels"), dict)
                else None,
                # Drain handshake (ISSUE 10): a retiring agent's final
                # flush carries draining=true; /v1/status marks it.
                draining=bool(body.get("draining")),
            )
            if lease is None:
                n_out = self._send(204)
            else:
                n_out = self._send(200, lease)
            # Data-plane byte accounting (ISSUE 6): task bodies leave on
            # lease responses — real wire bytes, straight off this socket.
            self.controller.note_http_bytes("/v1/leases", "in",
                                            self._request_bytes)
            self.controller.note_http_bytes("/v1/leases", "out", n_out)
        elif self.path == "/v1/results":
            out = self.controller.report(
                lease_id=str(body.get("lease_id", "")),
                job_id=str(body.get("job_id", "")),
                job_epoch=body.get("job_epoch"),
                status=str(body.get("status", "")),
                result=body.get("result"),
                error=body.get("error"),
                # Piggybacked agent spans (ISSUE 5) — optional, absent from
                # legacy agents.
                spans=body.get("spans"),
                # Per-task result-wire attribution (ISSUE 9): the measured
                # request size, billed into the usage ledger.
                wire_bytes=self._request_bytes,
            )
            n_out = self._send(200, out)
            # Result bodies arrive on this route — the other half of the
            # wire-bytes/row arithmetic bench's binary-wire leg reports.
            self.controller.note_http_bytes("/v1/results", "in",
                                            self._request_bytes)
            self.controller.note_http_bytes("/v1/results", "out", n_out)
        elif self.path == "/v1/jobs":
            # Operator surface: submit one job or a sharded CSV job.
            try:
                # Per-job retry budget (ISSUE 3): absent → controller default.
                max_attempts = (
                    int(body["max_attempts"])
                    if body.get("max_attempts") is not None
                    else None
                )
                # Scheduling fields (ISSUE 4): absent → controller defaults
                # (SCHED_DEFAULT_PRIORITY, tenant "default", no deadline).
                priority = (
                    int(body["priority"])
                    if body.get("priority") is not None
                    else None
                )
                tenant = (
                    str(body["tenant"])
                    if body.get("tenant") is not None
                    else None
                )
                deadline_sec = (
                    float(body["deadline_sec"])
                    if body.get("deadline_sec") is not None
                    else None
                )
                if "source_uri" in body:
                    shard_ids, reduce_id = self.controller.submit_csv_job(
                        source_uri=str(body["source_uri"]),
                        total_rows=int(body["total_rows"]),
                        # Absent → None → profile-derived shard sizing.
                        shard_size=(
                            int(body["shard_size"])
                            if body.get("shard_size") is not None
                            else None
                        ),
                        map_op=str(body.get("map_op", "read_csv_shard")),
                        extra_payload=body.get("extra_payload"),
                        reduce_op=body.get("reduce_op"),
                        reduce_payload=body.get("reduce_payload"),
                        required_labels=body.get("required_labels"),
                        collect_partials=bool(body.get("collect_partials")),
                        max_attempts=max_attempts,
                        priority=priority,
                        tenant=tenant,
                        deadline_sec=deadline_sec,
                    )
                    self._send(200, {"job_ids": shard_ids, "reduce_id": reduce_id})
                else:
                    job_id = self.controller.submit(
                        op=str(body["op"]),
                        payload=body.get("payload"),
                        # Client-chosen id (ISSUE 14): a submitter that
                        # lost the response to a dead primary resubmits
                        # the SAME id to the standby — the duplicate-id
                        # 400 is its exactly-once acknowledgment.
                        job_id=(
                            str(body["job_id"])
                            if body.get("job_id") is not None else None
                        ),
                        required_labels=body.get("required_labels"),
                        max_attempts=max_attempts,
                        priority=priority,
                        tenant=tenant,
                        deadline_sec=deadline_sec,
                    )
                    self._send(200, {"job_id": job_id})
            except AdmissionError as exc:
                self._send_admission(exc)
            except (KeyError, ValueError, TypeError) as exc:
                self._send(400, {"error": str(exc)})
        elif self.path == "/v1/workflows":
            # Workflow DAG engine (ISSUE 19): a fan-out/fan-in graph
            # submitted as ONE unit; stages become ordinary dep-gated jobs.
            try:
                out = self.controller.submit_workflow(
                    workflow=body,
                    tenant=(
                        str(body["tenant"])
                        if body.get("tenant") is not None else None
                    ),
                    priority=body.get("priority"),
                    deadline_sec=(
                        float(body["deadline_sec"])
                        if body.get("deadline_sec") is not None else None
                    ),
                    workflow_id=(
                        str(body["workflow_id"])
                        if body.get("workflow_id") is not None else None
                    ),
                )
                self._send(200, out)
            except AdmissionError as exc:
                self._send_admission(exc)
            except (KeyError, ValueError, TypeError) as exc:
                self._send(400, {"error": str(exc)})
            except RuntimeError as exc:
                # FLOW_ENABLED=0: the subsystem is configured off.
                self._send(501, {"error": str(exc)})
        elif self.path == "/v1/infer":
            # Online serving front door (ISSUE 15): one classify/summarize
            # request; blocks to the result by default, ?wait:false returns
            # the req_id for GET polling, stream:true frames the lifecycle
            # as chunked NDJSON.
            self._handle_infer_post(body)
        elif self.path == "/v1/profile/capture":
            # On-demand deep capture (ISSUE 9): arm one jax.profiler trace
            # on the named agent; the request rides its next granted lease.
            try:
                out = self.controller.request_capture(
                    agent=body.get("agent"),
                    op=body.get("op"),
                    duration_ms=body.get("duration_ms"),
                )
                self._send(200, {"capture_id": out["capture_id"],
                                 "capture": out})
            except (ValueError, TypeError) as exc:
                self._send(400, {"error": str(exc)})
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        parts = urlsplit(self.path)
        path, query = parts.path, parse_qs(parts.query)
        if path == "/v1/traces":
            # Newest-first trace summaries; ?limit=N caps the listing.
            try:
                limit = int(query.get("limit", ["20"])[0])
            except ValueError:
                self._send(400, {"error": "limit must be an int"})
                return
            self._send(200, {"traces": self.controller.traces_json(limit)})
            return
        if path.startswith("/v1/trace/"):
            # Assembled span tree for one job — or one serving request
            # (ISSUE 17: a req_id resolves to its stitched tree, the batch
            # job traces it links to inlined under ``linked_traces``).
            # ?format=perfetto returns the Chrome-trace JSON Perfetto loads
            # directly; ?format=jsonl the span-per-line dump; default is
            # the assembled wire schema.
            trace_id = path[len("/v1/trace/"):]
            assembled = self.controller.trace_json(trace_id)
            if assembled is None:
                self._send(404, {"error": f"no trace {trace_id!r}"})
                return
            fmt = query.get("format", ["json"])[0]
            # Flat exports flatten the stitched view: the trace's own spans
            # plus every linked trace's, one timeline.
            flat_spans = list(assembled["spans"])
            for lt in assembled.get("linked_traces", ()):
                flat_spans.extend(lt["spans"])
            if fmt == "perfetto":
                from agent_tpu.obs.trace import to_chrome_trace

                self._send(200, to_chrome_trace(flat_spans))
            elif fmt == "jsonl":
                from agent_tpu.obs.trace import to_jsonl

                self._send_text(
                    200, to_jsonl(flat_spans),
                    "application/jsonl; charset=utf-8",
                )
            else:
                self._send(200, assembled)
            return
        if path == "/v1/debug/events":
            # Flight-recorder dump on demand — the controller half of the
            # post-hoc diagnosis story (the agent half is SIGUSR1).
            # ?job_id= filters to one job's life (ISSUE 5 satellite);
            # ?req_id= to one serving request's (ISSUE 17 satellite).
            job_id = query.get("job_id", [None])[0]
            req_id = query.get("req_id", [None])[0]
            self._send(
                200,
                {
                    "events": self.controller.recorder.events(
                        job_id=job_id, req_id=req_id
                    ),
                    "dropped": self.controller.recorder.dropped,
                    "capacity": self.controller.recorder.capacity,
                },
            )
            return
        if path == "/v1/debug/requests":
            # Wide-event request log (ISSUE 17): one tail-sampled record
            # per terminal serving request. ?tenant= / ?outcome= filter,
            # ?slow=1 restricts to the kept tail (errors + slow decile),
            # ?limit=N caps, ?format=jsonl exports record-per-line.
            try:
                limit = int(query.get("limit", ["256"])[0])
            except ValueError:
                self._send(400, {"error": "limit must be an int"})
                return
            body = self.controller.requests_json(
                tenant=query.get("tenant", [None])[0],
                outcome=query.get("outcome", [None])[0],
                slow=query.get("slow", ["0"])[0] in ("1", "true", "yes"),
                limit=limit,
            )
            if query.get("format", ["json"])[0] == "jsonl":
                self._send_text(
                    200,
                    "".join(
                        json.dumps(rec, sort_keys=True, default=str) + "\n"
                        for rec in body["requests"]
                    ),
                    "application/jsonl; charset=utf-8",
                )
            else:
                self._send(200, body)
            return
        if path == "/v1/workflows":
            # Workflow DAG summary list + result-cache stats (ISSUE 19) —
            # swarmtop's Workflows panel reads this.
            self._send(200, self.controller.workflows_json())
            return
        if path.startswith("/v1/workflows/"):
            wf_id = path[len("/v1/workflows/"):]
            out = self.controller.workflow_json(wf_id)
            if out is None:
                self._send(404, {"error": f"unknown workflow {wf_id!r}"})
            else:
                self._send(200, out)
            return
        if path == "/v1/usage":
            # Showback report (ISSUE 9): billed device/host seconds, FLOPs,
            # rows, and wire bytes per tenant/tier/op + top-K jobs + the
            # live per-tenant queue depth. ?top_k=N resizes the job list.
            try:
                top_k = (
                    int(query["top_k"][0]) if "top_k" in query else None
                )
            except ValueError:
                self._send(400, {"error": "top_k must be an int"})
                return
            self._send(200, self.controller.usage_json(top_k=top_k))
            return
        if path == "/v1/timeseries":
            # Controller trend ring (ISSUE 9): ?name=<family> (required),
            # ?rate=1 for per-second deltas, ?window_sec=N to narrow, and
            # any other query key=value pairs filter series labels
            # (?op=map_classify_tpu&tenant=a). ?since=<epoch>/?step=<sec>
            # (ISSUE 20) serve history from the durable store; values of
            # since up to 1e6 are read as "seconds ago".
            name = query.get("name", [None])[0]
            if not name:
                self._send(400, {
                    "error": "name is required",
                    "names": self.controller.timeseries_names(),
                })
                return
            try:
                window = (
                    float(query["window_sec"][0])
                    if "window_sec" in query else None
                )
                since = (
                    float(query["since"][0]) if "since" in query else None
                )
                step = (
                    float(query["step"][0]) if "step" in query else None
                )
            except ValueError:
                self._send(400, {
                    "error": "window_sec/since/step must be numbers"
                })
                return
            if since is not None and since <= 1e6:
                since = time.time() - max(0.0, since)
            rate = query.get("rate", ["0"])[0] in ("1", "true", "yes")
            label_filter = {
                k: v[0] for k, v in query.items()
                if k not in ("name", "rate", "window_sec", "since", "step")
                and v
            }
            self._send(200, self.controller.timeseries_json(
                name, label_filter or None, rate=rate, window_sec=window,
                since=since, step=step,
            ))
            return
        if path == "/v1/timeseries/export":
            # Delta-scrape surface (ISSUE 20): raw ring samples newer than
            # ?since=<epoch> — the router collector's cursor endpoint.
            try:
                since = float(query.get("since", ["0"])[0])
            except ValueError:
                self._send(400, {"error": "since must be a number"})
                return
            self._send(200, self.controller.timeseries_export_json(since))
            return
        if path == "/v1/incidents":
            self._send(200, self.controller.incidents_json())
            return
        if path.startswith("/v1/incidents/"):
            incident_id = path[len("/v1/incidents/"):]
            out = self.controller.incidents_json(incident_id)
            if out.get("enabled") and out.get("incident") is None:
                self._send(404, {"error": f"unknown incident "
                                          f"{incident_id!r}"})
            else:
                self._send(200, out)
            return
        if path == "/v1/profile/host":
            # Host sampling profiler (ISSUE 9): collapsed-stack flamegraph
            # text of the controller process (flamegraph.pl format).
            text = self.controller.host_profile_text()
            if text is None:
                self._send(404, {"error": "host profiler disabled "
                                          "(PROFILE_HOST_ENABLED=0)"})
                return
            self._send_text(200, text, "text/plain; charset=utf-8")
            return
        if path == "/v1/profile/captures":
            self._send(200, self.controller.captures_json())
            return
        if path.startswith("/v1/infer/"):
            # Serving request status/result (ISSUE 15); ?wait_ms=N long-polls
            # to a terminal state (capped by SERVE_WAIT_TIMEOUT_SEC).
            req_id = path[len("/v1/infer/"):]
            try:
                wait_ms = (
                    float(query["wait_ms"][0]) if "wait_ms" in query else 0.0
                )
            except ValueError:
                self._send(400, {"error": "wait_ms must be a number"})
                return
            if wait_ms > 0:
                try:
                    snap = self.controller.wait_infer(
                        req_id,
                        min(wait_ms / 1e3,
                            self.controller.serve_config.wait_timeout_sec),
                    )
                except RuntimeError as exc:  # serving disabled
                    self._send(501, {"error": str(exc)})
                    return
            else:
                snap = self.controller.infer_snapshot(req_id)
            if snap is None:
                self._send(404, {"error": f"unknown request {req_id!r}"})
            else:
                self._send(200, snap)
            return
        if path == "/v1/health":
            # Fleet health verdict (ISSUE 8): per-tier SLO attainment +
            # burn-rate alert states, per-agent duty cycle/MFU/liveness,
            # queue pressure, one rolled-up ok|warn|page verdict — the
            # machine-readable signal vector the autoscaler (ROADMAP item
            # 4) and scripts/swarmtop.py consume.
            self._send(200, self.controller.health_json())
            return
        if path == "/v1/depth":
            # Partitioned control plane (ISSUE 18): the steal probe. A
            # deliberately tiny payload the router polls per idle lease —
            # /v1/status computes fleet merges and is far too heavy for
            # that loop.
            self._send(
                200,
                {
                    "partition": self.controller.partition,
                    "queue_depth": self.controller.queue_depth(),
                    "leasable": self.controller.leasable_depth(),
                },
            )
            return
        if self.path == "/v1/status":
            status_body = {
                "counts": self.controller.counts(),
                "counts_by_op": self.controller.counts_by_op(),
                "queue_depth": self.controller.queue_depth(),
                "drained": self.controller.drained(),
                "stale_results": self.controller.stale_results,
                "agents": self.controller.agents_summary(),
                "summary": self.controller.status_summary(),
                # Journal durability block (ISSUE 14 satellite): replay
                # damage (torn FINAL line vs mid-file corruption) plus
                # segment count/bytes, last-snapshot age, and the last
                # replay's duration — the O(live state) claim as a
                # number operators can read off one status call.
                "journal": self.controller.journal_status(),
                # Serving front-door block (ISSUE 15): request states,
                # open buckets, in-flight batch jobs, 429 drops.
                "serving": self.controller.serve_status(),
                "last_metrics": self.controller.last_metrics,
            }
            # Partitioned mode only (ISSUE 18): the router's fan-out merge
            # keys on this. A standalone controller's status schema stays
            # byte-stable.
            if self.controller.partition:
                status_body["partition"] = self.controller.partition
            self._send(200, status_body)
        elif self.path == "/v1/metrics":
            # Prometheus text exposition: controller series + fleet-merged
            # agent series + per-agent liveness (see Controller.metrics_text).
            self._send_text(
                200,
                self.controller.metrics_text(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif self.path.startswith("/v1/jobs/"):
            job_id = self.path[len("/v1/jobs/"):]
            try:
                self._send(200, self.controller.job_snapshot(job_id))
            except KeyError:
                self._send(404, {"error": f"unknown job {job_id!r}"})
        else:
            self._send(404, {"error": f"no route {self.path}"})


class ControllerServer:
    """Owns a Controller + an HTTP server on a background thread.

    ``port=0`` binds an ephemeral port; ``url`` reports the bound address —
    tests point an agent's CONTROLLER_URL at it.
    """

    def __init__(
        self,
        controller: Optional[Controller] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self.controller = controller or Controller()
        handler = type("Handler", (_Handler,), {"controller": self.controller})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "ControllerServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="controller-http", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def __enter__(self) -> "ControllerServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def main() -> int:
    """Standalone controller: ``agent-tpu-controller`` / ``python -m
    agent_tpu.controller.server``. Env: CONTROLLER_HOST (default 0.0.0.0),
    CONTROLLER_PORT (default 8080), LEASE_TTL_SEC (default 30),
    MAX_ATTEMPTS (default retry budget, 2), REQUEUE_DELAY_SEC (retried jobs
    held back this long, default 1), WIRE_BINARY (0 disables the binary
    shard wire; default on), plus the SCHED_* scheduler knobs
    (SCHED_POLICY fifo|fair, SCHED_MAX_PENDING[_PER_TENANT],
    SCHED_TENANT_WEIGHTS, … — see config.SchedConfig)."""
    import signal

    from agent_tpu.config import (
        FlowConfig,
        JournalConfig,
        ObsConfig,
        SchedConfig,
        ServeConfig,
        SloConfig,
        env_bool,
        env_float,
        env_int,
        env_str,
    )

    host = env_str("CONTROLLER_HOST", "0.0.0.0")
    port = env_int("CONTROLLER_PORT", 8080)
    ttl = env_float("LEASE_TTL_SEC", 30.0)
    journal = env_str("CONTROLLER_JOURNAL", "") or None
    sweep = env_float("CONTROLLER_SWEEP_SEC", 5.0)
    # CONTROLLER_PARTITION (ISSUE 18): this process is one shard of a
    # partitioned control plane — ids it generates carry the name and the
    # router's fan-out merges key on it. Empty = standalone controller.
    partition = env_str("CONTROLLER_PARTITION", "") or None
    sched = SchedConfig.from_env()
    controller = Controller(
        partition=partition,
        lease_ttl_sec=ttl,
        journal_path=journal,
        sweep_interval_sec=sweep if sweep > 0 else None,
        max_attempts=max(1, env_int("MAX_ATTEMPTS", 2)),
        requeue_delay_sec=env_float("REQUEUE_DELAY_SEC", 1.0),
        sched=sched,
        # WIRE_BINARY=0 runs a JSON-only controller (binary-capable agents
        # simply never get the `wire` answer and stay on JSON).
        wire_binary=env_bool("WIRE_BINARY", True),
        # SLO_* / HEALTH_* knobs (ISSUE 8): declarative objectives, burn
        # thresholds, windows; SLO_ENABLED=0 no-ops the judgment path.
        slo=SloConfig.from_env(),
        # USAGE_* / TSDB_* / PROFILE_* knobs (ISSUE 9): showback ledger,
        # trend ring, host profiler, on-demand deep captures.
        obs=ObsConfig.from_env(),
        # JOURNAL_* / SNAPSHOT_* knobs (ISSUE 14): segment rotation,
        # compacting snapshots, optional fdatasync. Defaults reproduce the
        # historical single-file journal byte for byte.
        journal=JournalConfig.from_env(),
        # SERVE_* knobs (ISSUE 15): the POST /v1/infer front door —
        # coalescing deadline/batch caps, length buckets, admission budget.
        serve=ServeConfig.from_env(),
        # FLOW_* / CACHE_* knobs (ISSUE 19): workflow DAG limits + the
        # content-addressed result cache (capacity, model version, price).
        flow=FlowConfig.from_env(),
    )
    server = ControllerServer(controller, host=host, port=port)
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    server.start()
    print(
        f"[agent-tpu-controller] serving on {server.url} "
        f"(sched policy={sched.policy})",
        flush=True,
    )
    stop.wait()
    server.stop()
    controller.close()
    print("[agent-tpu-controller] stopped", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
