"""Controller scheduling core (protocol per SURVEY.md §2.9, inferred from the
reference client at ``app.py:162-213``).

Design decisions:

- **Pull-based**: agents long-poll; the controller never initiates contact.
  A lease hands out up to ``max_tasks`` tasks whose op is in the agent's
  advertised capabilities.
- **Lease expiry**: each lease carries a TTL; a sweeper re-queues tasks whose
  lease expired, bumping ``job_epoch`` so the original agent's late result is
  fenced off (the reference protocol's whole point, ref ``app.py:201,209``).
- **Epoch fencing**: a result is accepted only if its ``job_epoch`` matches the
  job's current epoch; stale results are counted, not applied.
- **Shard splitting**: ``submit_csv_job`` turns ``(source_uri, total_rows,
  shard_size)`` into one task per shard addressed ``(start_row, shard_size)``
  — the reference's data-distribution primitive (ref ``ops/csv_shard.py:9-26``)
  — and an optional ``reduce_op`` job gated on the shards completing.
- **Fault injection** (SURVEY.md §5.3): ``inject(...)`` arms one-shot faults —
  ``drop_lease`` (issue no tasks once), ``duplicate_task`` (hand the same task
  to two leases), ``stale_epoch`` (bump a job's epoch right after leasing so
  the result arrives stale).

Everything is in-memory and lock-guarded; the HTTP layer in ``server.py`` is a
thin adapter over this class, so tests can drive it directly in-process.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from agent_tpu.config import TRUTHY_TOKENS

PENDING = "pending"
LEASED = "leased"
SUCCEEDED = "succeeded"
FAILED = "failed"


def _truthy(value: Any) -> bool:
    """Truthiness for advertised label values, consistent with the env
    grammar (``config.env_bool``): AGENT_LABELS="tpu=false" advertises the
    *string* "false", which must not satisfy a True requirement."""
    if isinstance(value, str):
        return value.strip().lower() in TRUTHY_TOKENS
    return bool(value)


@dataclass
class Job:
    job_id: str
    op: str
    payload: Dict[str, Any]
    epoch: int = 0
    state: str = PENDING
    result: Any = None
    error: Any = None
    lease_id: Optional[str] = None
    lease_deadline: float = 0.0
    agent: Optional[str] = None
    attempts: int = 0
    # Jobs that must complete before this one becomes leasable (reduce
    # stages). ``after_order`` preserves submission order for partials
    # materialization (shard-10 must not precede shard-2); ``after`` is the
    # same ids as a set for O(1) dependency checks.
    after: Set[str] = field(default_factory=set)
    after_order: Tuple[str, ...] = ()
    # Label constraints: every key must appear in the leasing agent's labels,
    # and non-True values must match (the consumer side of the AGENT_LABELS
    # channel the protocol has always carried, reference app.py:49-63,168).
    required_labels: Dict[str, Any] = field(default_factory=dict)

    def to_task(self) -> Dict[str, Any]:
        return {
            "id": self.job_id,
            "op": self.op,
            "payload": self.payload,
            "job_epoch": self.epoch,
        }


class Controller:
    def __init__(
        self,
        lease_ttl_sec: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.lease_ttl_sec = lease_ttl_sec
        self._clock = clock
        self._lock = threading.Lock()
        self._jobs: Dict[str, Job] = {}
        self._queue: List[str] = []  # FIFO of pending job ids
        self._faults: List[str] = []  # one-shot armed faults
        self.stale_results = 0
        self.last_metrics: Dict[str, Any] = {}
        self.last_profile: Dict[str, Any] = {}

    # ---- job submission ----

    def submit(
        self,
        op: str,
        payload: Optional[Dict[str, Any]] = None,
        job_id: Optional[str] = None,
        after: Optional[Sequence[str]] = None,
        required_labels: Optional[Dict[str, Any]] = None,
    ) -> str:
        job_id = job_id or f"job-{uuid.uuid4().hex[:12]}"
        required_labels = dict(required_labels or {})
        for k, v in required_labels.items():
            # Non-scalar requirements can never match the AGENT_LABELS
            # grammar (strings or True) — rejecting here turns would-be
            # silent starvation into an immediate submit error.
            if not isinstance(k, str) or not k:
                raise ValueError(f"required_labels keys must be strings, got {k!r}")
            scalar_ok = v is True or (
                isinstance(v, (str, int, float)) and not isinstance(v, bool)
            )
            if not scalar_ok:
                raise ValueError(
                    f"required_labels[{k!r}] must be True or a scalar, got {v!r}"
                )
        if isinstance(after, (set, frozenset)):
            # collect_partials materializes dependency results in after
            # order — an unordered collection would make shard order
            # nondeterministic. Force callers to pass a sequence.
            raise ValueError("after must be an ordered sequence, not a set")
        after_order = tuple(after or ())
        job = Job(
            job_id=job_id,
            op=op,
            payload=payload or {},
            after=set(after_order),
            after_order=after_order,
            required_labels=required_labels,
        )
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"duplicate job id {job_id!r}")
            self._jobs[job_id] = job
            self._queue.append(job_id)
        return job_id

    def submit_csv_job(
        self,
        source_uri: str,
        total_rows: int,
        shard_size: int,
        map_op: str = "read_csv_shard",
        extra_payload: Optional[Dict[str, Any]] = None,
        reduce_op: Optional[str] = None,
        reduce_payload: Optional[Dict[str, Any]] = None,
        required_labels: Optional[Dict[str, Any]] = None,
        collect_partials: bool = False,
    ) -> Tuple[List[str], Optional[str]]:
        """Split a CSV dataset into shard tasks (+ optional gated reduce job).

        Shards address rows ``[start_row, start_row + shard_size)`` — idempotent
        re-execution is the resume unit (SURVEY.md §5.4).

        With ``collect_partials`` the controller materializes the shard jobs'
        results into the reduce job's ``partials`` payload when it leases —
        the "partials combined controller-side" flow the reference implied
        (SURVEY.md §5.8) made explicit, e.g. ``map_op="risk_accumulate"``
        (per-shard stats) + ``reduce_op="risk_accumulate"`` (merge).
        """
        if shard_size <= 0:
            raise ValueError("shard_size must be positive")
        if total_rows <= 0:
            # Zero shards + an immediately-leasable reduce-over-nothing is
            # never what the caller meant.
            raise ValueError("total_rows must be positive")
        shard_ids: List[str] = []
        for i, start in enumerate(range(0, total_rows, shard_size)):
            payload = dict(extra_payload or {})
            payload.update(
                source_uri=source_uri,
                start_row=start,
                shard_size=min(shard_size, total_rows - start),
            )
            shard_ids.append(
                self.submit(
                    map_op,
                    payload,
                    job_id=f"shard-{i}-{uuid.uuid4().hex[:8]}",
                    required_labels=required_labels,
                )
            )
        reduce_id = None
        if reduce_op is not None:
            payload = dict(reduce_payload or {})
            if collect_partials:
                payload["__collect_partials__"] = True
            reduce_id = self.submit(
                reduce_op,
                payload,
                after=shard_ids,  # ordered: partials materialize shard-order
                required_labels=required_labels,
            )
        return shard_ids, reduce_id

    # ---- fault injection (one-shot, SURVEY.md §5.3) ----

    def inject(self, fault: str) -> None:
        if fault not in ("drop_lease", "duplicate_task", "stale_epoch"):
            raise ValueError(f"unknown fault {fault!r}")
        with self._lock:
            self._faults.append(fault)

    def _take_fault(self, fault: str) -> bool:
        # caller holds the lock
        if fault in self._faults:
            self._faults.remove(fault)
            return True
        return False

    # ---- lease protocol ----

    def _expire_leases_locked(self) -> None:
        now = self._clock()
        for job in self._jobs.values():
            if job.state == LEASED and now >= job.lease_deadline:
                # Dead agent: re-queue with a bumped epoch so its late result
                # is discarded on arrival.
                job.epoch += 1
                job.state = PENDING
                job.lease_id = None
                self._queue.append(job.job_id)

    def _deps_done_locked(self, job: Job) -> bool:
        return all(
            self._jobs[d].state == SUCCEEDED
            for d in job.after
            if d in self._jobs
        )

    @staticmethod
    def _labels_match(job: Job, labels: Dict[str, Any]) -> bool:
        """Every required label must be present; a required value of True
        accepts any truthy advertisement (bare-token labels parse to True).

        Value comparison is string-coerced: the AGENT_LABELS env grammar only
        produces strings (or True), so a JSON-typed requirement like
        ``{"mem_gb": 16}`` must still match an agent advertising ``"16"`` —
        a strict type-sensitive compare would starve the job silently.
        Numeric requirements compare numerically first, so ``{"mem_gb": 16.0}``
        also matches ``"16"`` (str-coercing 16.0 to "16.0" would reintroduce
        exactly the silent starvation the coercion exists to prevent).
        """
        for key, want in job.required_labels.items():
            have = labels.get(key)
            if want is True:
                if not _truthy(have):  # absent, falsy, or "false"/"0"/...
                    return False
            elif have is None:
                return False
            elif isinstance(want, (int, float)) and not isinstance(want, bool):
                if isinstance(have, bool):
                    # A bare flag label (True) carries no value — it must not
                    # satisfy a numeric requirement via float(True) == 1.0.
                    return False
                try:
                    if float(have) != float(want):
                        return False
                except (TypeError, ValueError):
                    return False
            elif str(have) != str(want):
                return False
        return True

    def lease(
        self,
        agent: str,
        capabilities: Optional[Dict[str, Any]] = None,
        max_tasks: int = 1,
        worker_profile: Optional[Dict[str, Any]] = None,
        metrics: Optional[Dict[str, Any]] = None,
        labels: Optional[Dict[str, Any]] = None,
        **_ignored: Any,
    ) -> Optional[Dict[str, Any]]:
        """One lease request → ``{lease_id, tasks}`` or None (HTTP 204)."""
        ops = set((capabilities or {}).get("ops") or [])
        labels = labels or {}
        with self._lock:
            if metrics:
                self.last_metrics = metrics
            if worker_profile:
                self.last_profile = worker_profile
            self._expire_leases_locked()
            if self._take_fault("drop_lease"):
                return None
            duplicate = self._take_fault("duplicate_task")
            stale = self._take_fault("stale_epoch")

            lease_id = f"lease-{uuid.uuid4().hex[:12]}"
            deadline = self._clock() + self.lease_ttl_sec
            tasks: List[Dict[str, Any]] = []
            remaining: List[str] = []
            for job_id in self._queue:
                job = self._jobs[job_id]
                if (
                    len(tasks) < max(1, max_tasks)
                    and job.state == PENDING
                    and (not ops or job.op in ops)
                    and self._labels_match(job, labels)
                    and self._deps_done_locked(job)
                ):
                    job.state = LEASED
                    job.lease_id = lease_id
                    job.lease_deadline = deadline
                    job.agent = agent
                    job.attempts += 1
                    if job.payload.pop("__collect_partials__", None):
                        # Reduce-time materialization: dependency results
                        # become the op's partials (kept out of the payload
                        # until every shard result actually exists), in
                        # submission order — shard order, for reduce ops
                        # that are order-sensitive.
                        job.payload["partials"] = [
                            self._jobs[d].result
                            for d in job.after_order
                            if d in self._jobs
                        ]
                    tasks.append(job.to_task())
                    if duplicate:
                        # Same task handed out twice under one lease: the
                        # second completion must be idempotent/fenced.
                        tasks.append(job.to_task())
                        duplicate = False
                    if stale:
                        # Epoch bumps right after leasing → the agent's result
                        # arrives carrying the old epoch and is discarded.
                        job.epoch += 1
                        stale = False
                else:
                    remaining.append(job_id)
            self._queue = remaining
            if not tasks:
                return None
            return {"lease_id": lease_id, "tasks": tasks}

    def report(
        self,
        lease_id: str,
        job_id: str,
        job_epoch: Any,
        status: str,
        result: Any = None,
        error: Any = None,
        **_ignored: Any,
    ) -> Dict[str, Any]:
        """One result post. Stale epochs are counted and discarded."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return {"accepted": False, "reason": "unknown job"}
            if job_epoch != job.epoch:
                self.stale_results += 1
                return {"accepted": False, "reason": "stale epoch"}
            if job.state == SUCCEEDED:
                # Duplicate completion (e.g. duplicate_task fault): first wins.
                return {"accepted": False, "reason": "already complete"}
            # result/error before state: unlocked readers keying on a
            # terminal state must never see it paired with a stale result.
            job.result = result
            job.error = error
            job.state = SUCCEEDED if status == "succeeded" else FAILED
            job.lease_id = lease_id
            if job.state == FAILED:
                # Failed jobs are re-queued once more before sticking failed —
                # transient op errors (device warmup, fallback) get one retry.
                if job.attempts <= 1:
                    job.state = PENDING
                    job.epoch += 1
                    self._queue.append(job.job_id)
            return {"accepted": True}

    # ---- introspection (for tests, bench, and a future status endpoint) ----

    def job(self, job_id: str) -> Job:
        with self._lock:
            return self._jobs[job_id]

    def job_snapshot(self, job_id: str) -> Dict[str, Any]:
        """Consistent read of a job's public fields (all under one lock —
        a field-by-field read could observe state='succeeded' before the
        result assignment lands). The HTTP GET surface uses this."""
        with self._lock:
            job = self._jobs[job_id]
            return {
                "job_id": job.job_id,
                "op": job.op,
                "state": job.state,
                "job_epoch": job.epoch,
                "attempts": job.attempts,
                "agent": job.agent,
                "result": job.result,
                "error": job.error,
            }

    def counts(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for job in self._jobs.values():
                out[job.state] = out.get(job.state, 0) + 1
            return out

    def drained(self) -> bool:
        with self._lock:
            return all(
                j.state in (SUCCEEDED, FAILED) for j in self._jobs.values()
            )

    def results(self) -> Dict[str, Any]:
        with self._lock:
            return {
                j.job_id: j.result
                for j in self._jobs.values()
                if j.state == SUCCEEDED
            }
