"""Controller scheduling core (protocol per SURVEY.md §2.9, inferred from the
reference client at ``app.py:162-213``).

Design decisions:

- **Pull-based**: agents long-poll; the controller never initiates contact.
  A lease hands out up to ``max_tasks`` tasks whose op is in the agent's
  advertised capabilities.
- **Lease expiry**: each lease carries a TTL; a sweeper re-queues tasks whose
  lease expired, bumping ``job_epoch`` so the original agent's late result is
  fenced off (the reference protocol's whole point, ref ``app.py:201,209``).
- **Epoch fencing**: a result is accepted only if its ``job_epoch`` matches the
  job's current epoch; stale results are counted, not applied.
- **Shard splitting**: ``submit_csv_job`` turns ``(source_uri, total_rows,
  shard_size)`` into one task per shard addressed ``(start_row, shard_size)``
  — the reference's data-distribution primitive (ref ``ops/csv_shard.py:9-26``)
  — and an optional ``reduce_op`` job gated on the shards completing.
- **Delegated scheduling** (ISSUE 4): every lease decision goes through a
  pluggable ``sched.Scheduler``. The default ``fifo`` policy replays the
  historical inline queue scan bit-for-bit; ``SCHED_POLICY=fair`` adds
  priority tiers (0–9), weighted deficit-round-robin across tenants,
  load/capability-aware placement (TPU-tagged ops prefer TPU agents, bulk
  shards prefer idle agents, deep-queue agents get shrunken grants),
  bounded admission (HTTP 429 + ``retry_after_ms`` past the pending
  budget), and deadline handling (``deadline_sec`` expiry lands terminal
  ``dead`` with a ``DeadlineExceeded`` reason; near-deadline pending jobs
  escalate one priority tier). The controller keeps owning correctness
  (state machine, fencing, labels, dependencies, journal); the policy owns
  only order and placement.
- **Fault injection** (SURVEY.md §5.3): ``inject(...)`` arms one-shot faults —
  ``drop_lease`` (issue no tasks once), ``duplicate_task`` (hand the same task
  to two leases), ``stale_epoch`` (bump a job's epoch right after leasing so
  the result arrives stale).

State is in-memory and lock-guarded; the HTTP layer in ``server.py`` is a
thin adapter over this class, so tests can drive it directly in-process.
Two durability/liveness extras beyond the reference protocol:

- **Background sweeper** (``sweep_interval_sec``): TTL expiry runs on a timer,
  not only inside ``lease()`` — with no polling agents, expired leases still
  re-queue and ``/v1/status`` stays truthful.
- **Append-only journal** (``journal_path``): submissions, accepted results,
  and expiry requeues are journaled as JSONL; a restarted controller replays
  the file and resumes a half-drained job — completed shards stay completed,
  in-flight ones re-queue at their current epoch (journaled fences replay;
  a result an agent spooled across the restart is accepted rather than
  re-executed, and the terminal-state guard keeps application at-most-once
  even if the job was re-leased meanwhile). Result *bodies* are durable only for jobs
  some other job depends on (reduce partials); journaling every drain shard's
  output would duplicate the whole dataset, so operators should fetch map
  results as shards complete (GET ``/v1/jobs/<id>``) or add a reduce stage.
  ISSUE 14 bounds the replay cost: with the ``JOURNAL_*``/``SNAPSHOT_*``
  knobs set, the journal rotates into segments with periodic compacting
  snapshots (``controller/journal.py``) so restart is O(live state), and a
  hot standby (``controller/standby.py``) can tail it and promote with
  epoch fencing when this process dies.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from agent_tpu.config import (
    FlowConfig,
    JournalConfig,
    ObsConfig,
    TRUTHY_TOKENS,
    SchedConfig,
    ServeConfig,
    SloConfig,
)
from agent_tpu.controller.journal import SegmentedJournal
from agent_tpu.flow.dag import (
    DagError,
    PlannedJob,
    critical_path_lengths,
    expand_workflow,
    graph_doc,
    parse_workflow,
    spec_from_graph_doc,
)
from agent_tpu.flow.result_cache import ResultCache
from agent_tpu.ops import OP_TO_MODULE, is_cacheable
from agent_tpu.controller.serving import (
    DONE as SERVE_DONE,
    SERVE_OPS,
    ServeBatch,
    ServeFrontDoor,
)
from agent_tpu.data import wire
from agent_tpu.obs.anomaly import AnomalyDetector
from agent_tpu.obs.health import build_health
from agent_tpu.obs.incident import IncidentBundler
from agent_tpu.obs.profile import CaptureCoordinator, HostProfiler
from agent_tpu.obs.timeseries import TimeSeriesRing
from agent_tpu.obs.tsdb import TsdbStore, query_history
from agent_tpu.obs.usage import UsageLedger
from agent_tpu.obs.metrics import (
    MetricsRegistry,
    histogram_quantile,
    merge_snapshots,
    render_snapshots,
)
from agent_tpu.obs.recorder import FlightRecorder, default_dump_path
from agent_tpu.obs.reqlog import RequestLog, dominant_component
from agent_tpu.obs.slo import SloTracker, parse_slo_spec
from agent_tpu.obs.trace import TraceStore
from agent_tpu.obs import trace as obs_trace
from agent_tpu.sched import (
    DEFAULT_PRIORITY,
    DEFAULT_TENANT,
    PRIORITY_MAX,
    PRIORITY_MIN,
    AdmissionError,
    LeaseContext,
    make_scheduler,
)
from agent_tpu.utils.logging import log
from agent_tpu.utils.retry import PERMANENT, classify_error

PENDING = "pending"
LEASED = "leased"
SUCCEEDED = "succeeded"
FAILED = "failed"      # permanent error — retrying cannot fix it
DEAD = "dead"          # transient failures exhausted the retry budget

# States no result post can move a job out of (ISSUE 3: `dead` joins the
# terminal set; duplicate completions against any of them are counted, not
# applied).
TERMINAL_STATES = (SUCCEEDED, FAILED, DEAD)

# Reference behavior: every failed job got exactly one retry (two attempts
# total). Kept as the default budget; per-job `max_attempts` overrides.
DEFAULT_MAX_ATTEMPTS = 2

# Reference default shard size (ref ops/csv_shard.py:62) — the fallback when
# no worker profile has suggested anything better.
DEFAULT_SHARD_ROWS = 100


def _truthy(value: Any) -> bool:
    """Truthiness for advertised label values, consistent with the env
    grammar (``config.env_bool``): AGENT_LABELS="tpu=false" advertises the
    *string* "false", which must not satisfy a True requirement."""
    if isinstance(value, str):
        return value.strip().lower() in TRUTHY_TOKENS
    return bool(value)


@dataclass
class Job:
    job_id: str
    op: str
    payload: Dict[str, Any]
    epoch: int = 0
    state: str = PENDING
    result: Any = None
    error: Any = None
    lease_id: Optional[str] = None
    lease_deadline: float = 0.0
    agent: Optional[str] = None
    attempts: int = 0
    # Per-job retry budget; None falls back to the controller default.
    max_attempts: Optional[int] = None
    # Requeue delay: a retried job is not leasable before this controller-
    # clock instant, so a crashing op can't hot-loop through the queue.
    not_before: float = 0.0
    # Controller-clock submit time (queue-wait attribution: submit→lease).
    submitted_at: float = 0.0
    # Jobs that must complete before this one becomes leasable (reduce
    # stages). ``after_order`` preserves submission order for partials
    # materialization (shard-10 must not precede shard-2); ``after`` is the
    # same ids as a set for O(1) dependency checks.
    after: Set[str] = field(default_factory=set)
    after_order: Tuple[str, ...] = ()
    # Label constraints: every key must appear in the leasing agent's labels,
    # and non-True values must match (the consumer side of the AGENT_LABELS
    # channel the protocol has always carried, reference app.py:49-63,168).
    required_labels: Dict[str, Any] = field(default_factory=dict)
    # Scheduling (ISSUE 4). priority 0–9 (9 = most urgent); tenant is the
    # fair-share bucket; deadline_sec counts from submit (re-anchored to
    # replay time after a restart — the journal carries no wall clock).
    priority: int = DEFAULT_PRIORITY
    tenant: str = DEFAULT_TENANT
    deadline_sec: Optional[float] = None
    # One-shot near-deadline escalation marker (sweeper bumps one tier).
    escalated: bool = False
    # Times the fair policy skipped this job waiting for a better-placed
    # agent; capped by SCHED_PLACEMENT_PATIENCE so preference never starves.
    placement_defers: int = 0
    # Distributed tracing (ISSUE 5): the job-lifetime root span opened at
    # submit, the currently-open lease span agent-side spans parent to, and
    # the controller-clock instant the job last became queued (what the
    # sched.decide span measures its wait from).
    root_span_id: Optional[str] = None
    lease_span_id: Optional[str] = None
    enqueued_clock: float = 0.0
    # Workflow DAG membership (ISSUE 19): stage jobs carry their graph id
    # and stage name so status/placement/tracing see the whole DAG as one
    # unit. ``critical_path`` is the longest remaining stage count to a
    # sink — the scheduler's critical-path-first tiebreak (0 = plain job,
    # which keeps non-DAG drain order bit-identical).
    workflow_id: Optional[str] = None
    stage: Optional[str] = None
    critical_path: int = 0

    @property
    def trace_root(self) -> str:
        """The trace this job's spans land in: its workflow's single tree
        when it is a DAG stage, else its own job-id trace (ISSUE 19 —
        one trace tree per DAG)."""
        return self.workflow_id or self.job_id

    def to_task(self) -> Dict[str, Any]:
        task = {
            "id": self.job_id,
            "op": self.op,
            "payload": self.payload,
            "job_epoch": self.epoch,
            # Trace propagation (ISSUE 2): the agent stamps {job_id, attempt,
            # lease_id} into ctx.tags and result bodies, so one job's life
            # greps across journal, agent logs, and both flight recorders.
            "attempt": self.attempts,
        }
        if self.tenant != DEFAULT_TENANT:
            # Tenant plumb-through (ISSUE 9): agents stamp it into their
            # trace tags so per-tenant attribution greps across agent logs
            # and flight recorders too. Appended only when non-default —
            # single-tenant drains keep the exact legacy task bytes.
            task["tenant"] = self.tenant
        if self.lease_span_id is not None:
            # Causal parenting (ISSUE 5): agent-side stage/execute/post
            # spans hang off the lease span. Absent when tracing is off,
            # keeping the wire byte-identical to the pre-trace protocol.
            task["trace"] = {
                "trace_id": self.trace_root,
                "span_id": self.lease_span_id,
            }
        return task


class Controller:
    def __init__(
        self,
        lease_ttl_sec: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        journal_path: Optional[str] = None,
        sweep_interval_sec: Optional[float] = None,
        registry: Optional[MetricsRegistry] = None,
        recorder: Optional[FlightRecorder] = None,
        max_attempts: int = DEFAULT_MAX_ATTEMPTS,
        requeue_delay_sec: float = 0.0,
        sched: Optional[SchedConfig] = None,
        trace_store: Optional[TraceStore] = None,
        wire_binary: bool = True,
        slo: Optional[SloConfig] = None,
        obs: Optional[ObsConfig] = None,
        journal: Optional[JournalConfig] = None,
        serve: Optional[ServeConfig] = None,
        partition: Optional[str] = None,
        flow: Optional[FlowConfig] = None,
        tsdb_defer_open: bool = False,
    ) -> None:
        self.lease_ttl_sec = lease_ttl_sec
        # Partitioned control plane (ISSUE 18): this controller's partition
        # name, stamped into generated job/lease/request ids and the status
        # surfaces so any id or status doc names its owning partition. None
        # (the default) keeps every id byte-compatible with the
        # single-controller shape.
        self.partition = str(partition) if partition else None
        self._id_tag = f"{self.partition}-" if self.partition else ""
        # Binary shard wire (ISSUE 6): False = never negotiate (a JSON-only
        # controller for compatibility tests and WIRE_BINARY=0 operators);
        # agents that don't advertise are unaffected either way.
        self.wire_binary = bool(wire_binary)
        self.max_attempts = max(1, int(max_attempts))
        self.requeue_delay_sec = max(0.0, float(requeue_delay_sec))
        self.sched_config = sched if sched is not None else SchedConfig()
        # Workflow DAG engine + result cache (ISSUE 19). The cache is one
        # shared instance serving both planes: batch jobs (submit/lease
        # consult, report-time fill) and /v1/infer requests (front-door
        # consult before bucketing).
        self.flow_config = flow if flow is not None else FlowConfig()
        self.result_cache: Optional[ResultCache] = None
        if self.flow_config.cache_enabled \
                and self.flow_config.cache_capacity > 0:
            self.result_cache = ResultCache(
                capacity=self.flow_config.cache_capacity,
                model_version=self.flow_config.cache_model_version,
            )
        self._clock = clock
        self._lock = threading.Lock()
        self._jobs: Dict[str, Job] = {}
        self._faults: List[str] = []  # one-shot armed faults
        self._fault_plan = None      # seeded probabilistic plan (chaos.py)
        self.stale_results = 0
        self.last_metrics: Dict[str, Any] = {}
        self.last_profile: Dict[str, Any] = {}
        # Observability (ISSUE 2): an OWN registry/recorder per controller —
        # agents frequently share the process (tests, bench) and must not
        # conflate their series with the scheduler's.
        self.metrics = registry if registry is not None else MetricsRegistry()
        self.recorder = recorder if recorder is not None else FlightRecorder()
        # Distributed tracing (ISSUE 5): the assembly point for the swarm's
        # span trees — controller-side spans land here directly; agent-side
        # spans arrive piggybacked on results/leases and are ingested, deduped
        # by span_id. Bounded like the flight recorder.
        self.traces = trace_store if trace_store is not None else TraceStore()
        # Per-agent telemetry keyed by agent id (replaces the overwritten
        # last_metrics as the fleet source of truth; last_metrics is kept as
        # the legacy /v1/status field). Each entry: {last_seen_wall, metrics
        # (sans obs), obs (the agent's registry snapshot)}.
        self.agent_metrics: Dict[str, Dict[str, Any]] = {}
        self._started_wall = time.time()
        m = self.metrics
        self._m_lease = m.counter(
            "controller_lease_requests_total",
            "Lease requests by outcome", ("outcome",))
        self._m_tasks_leased = m.counter(
            "controller_tasks_leased_total", "Tasks handed out", ("op",))
        self._m_results = m.counter(
            "controller_results_total",
            "Result posts by op and outcome (succeeded/failed/released/"
            "stale_epoch/duplicate/unknown_job)", ("op", "outcome"))
        self._m_retries = m.counter(
            "controller_retries_total",
            "Transiently-failed jobs re-queued within their retry budget",
            ("op",))
        self._m_dead = m.counter(
            "controller_jobs_dead_total",
            "Jobs that exhausted their retry budget (terminal `dead`)",
            ("op",))
        self._m_faults = m.counter(
            "controller_faults_injected_total",
            "Chaos faults injected controller-side (one-shot or plan)",
            ("fault",))
        self._m_journal_skipped = m.counter(
            "controller_journal_replay_skipped_total",
            "Unparseable mid-file journal lines skipped during replay")
        self._m_expirations = m.counter(
            "controller_lease_expirations_total",
            "Leases TTL-expired and re-queued", ("op",))
        self._m_journal_writes = m.counter(
            "controller_journal_writes_total", "Journal appends", ("ev",))
        self._m_queue_wait = m.histogram(
            "controller_queue_wait_seconds",
            "submit -> first lease latency", ("op",))
        # ISSUE 4 satellite: `state` separates jobs leasable right now from
        # jobs held back by a requeue delay (`not_before`) — the old
        # unlabeled gauge counted held jobs as leasable.
        self._m_queue_depth = m.gauge(
            "controller_queue_depth",
            "Queued (pending) jobs by leasability", ("state",))
        self._m_journal_torn = m.counter(
            "controller_journal_torn_tail_total",
            "Journal replays that found a torn (unparseable) final line")
        # Journal durability surface (ISSUE 14): segmentation/compaction
        # bookkeeping + the replay-cost number the micro-bench tracks.
        self._m_snapshots = m.counter(
            "controller_journal_snapshots_total",
            "Compacting journal snapshots committed (atomic tmp+rename)")
        self._m_snapshot_invalid = m.counter(
            "controller_journal_snapshot_invalid_total",
            "Snapshots ignored as invalid/half-written at replay (replay "
            "fell back to full segments)")
        self._m_segments = m.gauge(
            "controller_journal_segments",
            "Journal segment files currently on disk")
        self._m_journal_disk_bytes = m.gauge(
            "controller_journal_bytes",
            "Journal bytes on disk (segments; snapshot excluded)")
        self._m_snapshot_age = m.gauge(
            "controller_journal_snapshot_age_seconds",
            "Age of the newest compacting snapshot")
        self._m_replay_sec = m.gauge(
            "controller_journal_replay_seconds",
            "Wall-clock duration of this incarnation's journal replay")
        self._m_promotions = m.counter(
            "controller_promotions_total",
            "Hot-standby promotions completed by this process")
        # Scheduler observability (ISSUE 4): decision counters, per-tenant
        # queue depth, and how long jobs waited before their first lease
        # (the starvation signal the fair policy exists to bound).
        self._m_sched_decisions = m.counter(
            "sched_decisions_total",
            "Scheduler decisions (leased/deferred_placement/escalated/"
            "deadline_dead/admission_rejected)", ("policy", "decision"))
        self._m_sched_depth = m.gauge(
            "sched_queue_depth", "Queued jobs per tenant", ("tenant",))
        self._m_starvation = m.histogram(
            "sched_starvation_age_seconds",
            "Job age (since submit) at first lease, per tenant", ("tenant",))
        self._m_admission = m.counter(
            "controller_admission_rejections_total",
            "Submits rejected by admission control (HTTP 429)", ("tenant",))
        self._m_deadline_dead = m.counter(
            "controller_jobs_deadline_expired_total",
            "Pending jobs that ran out of deadline_sec (terminal `dead`, "
            "reason DeadlineExceeded)", ("op",))
        # Data-plane wire accounting (ISSUE 6): envelopes encoded/decoded
        # and raw HTTP bytes per route+direction (fed by server.py from
        # Content-Length / response sizes — real wire bytes, not estimates;
        # bench derives bytes/row from the scrape delta).
        self._m_wire = m.counter(
            "controller_wire_total",
            "Binary-wire envelopes by direction (task=encoded task "
            "payloads, result=decoded results, result_error=undecodable)",
            ("direction", "format"))
        self._m_http_bytes = m.counter(
            "controller_http_bytes_total",
            "HTTP bytes on the data-plane routes", ("route", "direction"))
        # Fleet health / SLO engine (ISSUE 8): declarative objectives fed by
        # submit→apply latencies at result-apply time, judged by multi-window
        # burn rates, rolled into GET /v1/health. SLO_ENABLED=0 leaves
        # self.slo None and no-ops the whole path (observe/evaluate/alerts).
        self.slo_config = slo if slo is not None else SloConfig()
        self.slo: Optional[SloTracker] = None
        # Page-entry auto-dump bookkeeping: dump paths written this process
        # (tests and the CI smoke assert on them), one dump per objective
        # per page episode.
        self.slo_dump_paths: List[str] = []
        if self.slo_config.enabled:
            # A malformed SLO_SPEC fails controller boot — an objective
            # typo silently judging nothing is the rot this refuses.
            self.slo = SloTracker(
                parse_slo_spec(self.slo_config.spec),
                registry=self.metrics,
                clock=self._clock,
                window_short_sec=self.slo_config.window_short_sec,
                window_long_sec=self.slo_config.window_long_sec,
                burn_warn=self.slo_config.burn_warn,
                burn_page=self.slo_config.burn_page,
                burn_exit_frac=self.slo_config.burn_exit_frac,
                on_alert=self._on_slo_alert,
            )
        # Resource accounting & continuous profiling (ISSUE 9): the showback
        # ledger billed at result-apply time, the trend ring sampled from
        # sweep/lease, on-demand deep-capture bookkeeping riding the lease
        # alerts channel, and a lazily-started host sampling profiler.
        # USAGE_ENABLED=0 / TSDB_ENABLED=0 leave the members None and no-op
        # every touch point (no families registered, no journal keys).
        self.obs_config = obs if obs is not None else ObsConfig()
        self.usage: Optional[UsageLedger] = None
        if self.obs_config.usage_enabled:
            self.usage = UsageLedger(
                registry=self.metrics,
                top_k=self.obs_config.usage_top_k,
                max_jobs=self.obs_config.usage_max_jobs,
                cost_per_chip_hour=self.obs_config.usage_cost_per_chip_hour,
                cache_price_per_hit=self.flow_config.cache_price_per_hit,
            )
        self.tsdb: Optional[TimeSeriesRing] = None
        if self.obs_config.tsdb_enabled:
            self.tsdb = TimeSeriesRing(
                window_sec=self.obs_config.tsdb_window_sec,
                interval_sec=self.obs_config.tsdb_interval_sec,
                clock=self._clock,
            )
        # Durable telemetry vertical (ISSUE 20): on-disk store + anomaly
        # detector + incident bundler, all riding the ring's sample hook.
        # A hot standby defers the store open (``tsdb_defer_open``) — two
        # incarnations must never append to the same segment stream;
        # ``finalize_promotion`` opens it when the replica takes over.
        self.tsdb_store: Optional[TsdbStore] = None
        self._tsdb_defer_open = bool(tsdb_defer_open)
        self._tsdb_prev_sample: Optional[Dict[str, Any]] = None
        self.anomaly: Optional[AnomalyDetector] = None
        if self.obs_config.anomaly_enabled and self.tsdb is not None:
            self.anomaly = AnomalyDetector(
                window=self.obs_config.anomaly_window,
                warmup=self.obs_config.anomaly_warmup,
                z_thresh=self.obs_config.anomaly_z,
                confirm=self.obs_config.anomaly_confirm,
                clear=self.obs_config.anomaly_clear,
            )
        self.incidents: Optional[IncidentBundler] = None
        if self.obs_config.incident_enabled:
            self.incidents = IncidentBundler(
                directory=self.obs_config.incident_dir,
                capacity=self.obs_config.incident_capacity,
                min_interval_sec=self.obs_config.incident_min_interval_sec,
            )
        if self.tsdb is not None:
            if not self._tsdb_defer_open:
                self._open_tsdb_store()
            self.tsdb.on_sample = self._on_tsdb_sample
        # Online-serving front door (ISSUE 15): POST /v1/infer requests
        # coalesce into length-bucketed interactive-tier batch jobs.
        # SERVE_ENABLED=0 leaves the door None and 501s the route.
        self.serve_config = serve if serve is not None else ServeConfig()
        self.serve_door: Optional[ServeFrontDoor] = None
        self._m_serve_requests = m.counter(
            "serve_requests_total",
            "POST /v1/infer requests by op and outcome "
            "(accepted/completed/failed/rejected)", ("op", "outcome"))
        self._m_serve_batches = m.counter(
            "serve_batches_total",
            "Coalesced serving batches by flush reason (full = hit "
            "SERVE_MAX_BATCH, deadline = oldest waited SERVE_MAX_WAIT_MS)",
            ("op", "reason"))
        self._m_serve_ttft = m.histogram(
            "serve_ttft_seconds",
            "Serving time-to-first-token per op (arrival -> first decode "
            "token; classify: arrival -> answer)", ("op",))
        self._m_serve_latency = m.histogram(
            "serve_latency_seconds",
            "Serving request latency per op (arrival -> completion fan-out)",
            ("op",))
        self._m_serve_tokens = m.counter(
            "serve_tokens_total",
            "Tokens emitted for completed serving requests", ("op",))
        # controller_-prefixed: the agents' live `serve_batch_occupancy`
        # gauge is the canonical one (the engine lives there) and the two
        # must not collide in the merged /v1/metrics exposition.
        self._m_serve_occupancy = m.gauge(
            "controller_serve_batch_occupancy",
            "Continuous-batching running-batch occupancy (mean requests "
            "seated per decode step, as reported by the last serving batch)")
        # Prefix-cache and paged-KV telemetry (ISSUE 16): serve results
        # carry per-batch deltas; the controller accumulates them so the
        # fleet-wide hit rate is one exposition read (swarmtop's column).
        self._m_serve_prefix = m.counter(
            "serve_prefix_cache_events_total",
            "Prefix-cache events reported by serving batches "
            "(hits = prefill rows served from cache, misses = rows that "
            "ran the encoder, evictions = LRU discards)", ("event",))
        self._m_serve_kv_total = m.gauge(
            "serve_kv_blocks_total",
            "Paged KV pool size in blocks, as reported by the last "
            "serving batch's engine (0 = dense layout)")
        self._m_serve_kv_free = m.gauge(
            "serve_kv_blocks_free",
            "Free paged KV blocks after the last serving batch drained")
        # TTFT decomposition + per-token pace (ISSUE 17). One request's
        # component observations telescope: bucket_wait + queue_wait +
        # prefill + handoff + kv_wait + first_decode = its measured TTFT.
        self._m_serve_ttft_component = m.histogram(
            "serve_ttft_component_seconds",
            "Serving TTFT decomposition per component: bucket_wait "
            "(coalescing), queue_wait (job queue + lease), prefill "
            "(encoder forward), handoff (prefill->decode transport, ~0 "
            "colocated), kv_wait (engine admit -> seated), first_decode "
            "(seated -> first token)", ("component",))
        self._m_serve_tpot = m.histogram(
            "serve_tpot_seconds",
            "Serving time-per-output-token per op: per-request mean step "
            "pace after the first token (requests with >= 2 decode steps)",
            ("op",))
        # Wide-event request log (ISSUE 17): one record per terminal
        # request, tail-sampled, served at GET /v1/debug/requests.
        self.reqlog: Optional[RequestLog] = None
        if self.serve_config.enabled:
            self.reqlog = RequestLog(
                capacity=self.serve_config.reqlog_capacity,
                sample=self.serve_config.reqlog_sample,
            )
            self.serve_door = ServeFrontDoor(
                self.serve_config, clock=self._clock, traces=self.traces,
                partition=self.partition,
            )
        self.captures = CaptureCoordinator()
        # Built on first GET /v1/profile/host (a controller never asked for
        # a flamegraph never spawns the sampler thread — tests construct
        # hundreds of Controllers).
        self.host_profiler: Optional[HostProfiler] = None
        self._host_profiler_lock = threading.Lock()
        # The policy object every lease decision delegates to (ISSUE 4).
        self._sched = make_scheduler(
            self.sched_config, on_decision=self._on_sched_decision
        )
        # Queued job ids currently held back by a requeue delay — the small
        # set scanned to split the depth gauge into leasable vs held.
        self._delayed: Set[str] = set()
        # Job ids carrying a deadline (non-terminal) — the sweeper's
        # deadline/escalation scan iterates only these.
        self._deadlined: Set[str] = set()
        # Tenants that ever had a sched_queue_depth sample: drained tenants
        # report 0 instead of a stale last value.
        self._seen_tenants: Set[str] = set()
        # The most recent profile that actually carried a TPU sizing hint —
        # kept separately because in a mixed fleet every leasing agent
        # overwrites last_profile, and a CPU agent's poll must not revert
        # shard sizing to the fallback.
        self._last_tpu_profile: Dict[str, Any] = {}
        # Job ids some other job depends on (reduce stages): their result
        # bodies must survive a restart, so only these journal results.
        self._depended_on: Set[str] = set()
        # Workflow DAG state (ISSUE 19): per-graph bookkeeping for status/
        # tracing, job -> (workflow, stage) membership, and the REVERSE dep
        # edges the generalized DependencyFailed cascade walks (forward
        # edges live on Job.after; without the reverse map a failure would
        # have to scan every job to find its dependents).
        self._workflows: Dict[str, Dict[str, Any]] = {}
        self._job_workflow: Dict[str, Tuple[str, str]] = {}
        self._dependents: Dict[str, Set[str]] = {}
        self._m_workflows = m.counter(
            "flow_workflows_total",
            "Workflow DAG submissions by outcome "
            "(submitted/succeeded/dead/rejected)", ("outcome",))
        self._m_flow_stage_jobs = m.counter(
            "flow_stage_jobs_total",
            "Jobs expanded out of workflow DAG stages", ("op",))
        self._m_result_cache = m.counter(
            "result_cache_events_total",
            "Content-addressed result cache events by plane "
            "(hit_submit/hit_lease/hit_infer = result served without "
            "compute; miss = consulted, absent; put = computed result "
            "stored)", ("event",))
        # Journal replay damage, distinctly visible to operators (ISSUE 10
        # satellite): a torn FINAL line (expected crash artifact, tolerated)
        # vs unparseable MID-FILE lines (real corruption). Mirrored from the
        # replay-time counters into /v1/status so "did my journal replay
        # clean" reads off one status call, not a metrics scrape.
        self.journal_torn_tail = 0
        self.journal_replay_skipped = 0
        # Replay cost, the number compaction exists to bound (ISSUE 14):
        # wall seconds + events this incarnation replayed before serving.
        self.journal_replay_sec = 0.0
        self.journal_replayed_events = 0
        self.promotions = 0
        self.journal_config = journal if journal is not None \
            else JournalConfig()
        self._journal_impl: Optional[SegmentedJournal] = None
        if journal_path:
            impl = SegmentedJournal(
                journal_path,
                segment_max_bytes=self.journal_config.segment_max_bytes,
                segment_max_events=self.journal_config.segment_max_events,
                snapshot_every_events=(
                    self.journal_config.snapshot_every_events
                ),
                fsync=self.journal_config.fsync,
                fsync_every=self.journal_config.fsync_every,
            )
            self._replay_journal(impl)
            impl.open_for_append()
            self._journal_impl = impl
        self._sweeper: Optional[threading.Thread] = None
        self._sweep_stop = threading.Event()
        if sweep_interval_sec:
            self.start_sweeper(sweep_interval_sec)

    def _on_sched_decision(
        self, decision: str, job_id: Optional[str] = None
    ) -> None:
        """Policy decision hook: counts every decision; policy decisions
        that name a job (placement deferrals) additionally leave an instant
        ``sched.defer`` span on the job's trace, so a deferred-placement
        wait is visible in the timeline, not just the aggregate counter.
        Called under the controller lock (from inside ``lease``)."""
        self._m_sched_decisions.inc(
            policy=self.sched_config.policy, decision=decision
        )
        if job_id is None:
            return
        job = self._jobs.get(job_id)
        if job is None or job.root_span_id is None:
            return
        self.traces.add({
            "trace_id": job.trace_root,
            "span_id": obs_trace.new_span_id(),
            "parent_span_id": job.root_span_id,
            "name": "sched.defer",
            "start_wall": time.time(),
            "start_mono": self._clock(),
            "duration_ms": 0.0,
            "process": "controller",
            "attributes": {
                "decision": decision,
                "policy": self.sched_config.policy,
                "defers": job.placement_defers,
            },
        })

    # ---- fleet health / SLO engine (ISSUE 8) ----

    def _on_slo_alert(
        self, result: Dict[str, Any], old: str, new: str
    ) -> None:
        """Burn-rate alert transition hook (fires outside the controller
        lock — evaluate runs before/after lock-held sections). Entering
        ``page`` auto-dumps the controller flight-recorder ring, tagged
        with the breaching objective's ``{tier, op}`` — the post-hoc
        evidence that previously only existed for SIGUSR1/fatal paths."""
        selector = {
            k: result.get(k) for k in ("tier", "tenant", "op")
            if result.get(k) is not None
        }
        self.recorder.record(
            "slo_alert", objective=result.get("objective"),
            old_state=old, new_state=new,
            burn_short=result.get("burn_rate_short"),
            burn_long=result.get("burn_rate_long"), **selector,
        )
        log(
            "slo alert transition", objective=result.get("objective"),
            old=old, new=new, burn_short=result.get("burn_rate_short"),
            burn_long=result.get("burn_rate_long"),
        )
        if new != "page":
            return
        tag_bits = "-".join(
            f"{k}{v}" for k, v in selector.items()
        ) or "all"
        path = default_dump_path(
            f"controller-slo-{result.get('objective')}-{tag_bits}"
        )
        try:
            n = self.recorder.dump(path)
            self.slo_dump_paths.append(path)
            log("slo page — flight recorder dumped", path=path, events=n)
        except OSError:
            pass  # a failing dump must not take down the control plane
        # Incident forensics (ISSUE 20): page entry snapshots one
        # correlated bundle (the dump above folds in via slo_dumps).
        self._capture_incident(
            "slo_page",
            str(result.get("objective")),
            {
                "objective": result.get("objective"),
                "burn_short": result.get("burn_rate_short"),
                "burn_long": result.get("burn_rate_long"),
                **selector,
            },
        )

    def _slo_observe_locked(self, job: Job, now: float) -> None:
        """Feed one terminal job into the SLO tracker: submit→apply latency
        on the controller clock, success = SUCCEEDED. The tracker has its
        own lock and does a handful of integer bumps — cheap enough to run
        under the controller lock at drain scale."""
        if self.slo is None:
            return
        self.slo.observe(
            max(0.0, now - job.submitted_at),
            ok=job.state == SUCCEEDED,
            tier=job.priority,
            tenant=job.tenant,
            op=job.op,
            now=now,
        )

    def starvation_age_sec(self) -> Optional[float]:
        """Age (since submit) of the oldest currently-queued job — the live
        starvation signal /v1/health reports (the existing
        ``sched_starvation_age_seconds`` histogram only records at first
        lease, so a job that never leases is invisible to it)."""
        with self._lock:
            now = self._clock()
            ages = [
                now - self._jobs[jid].submitted_at
                for jid in self._sched.queued_ids()
                if jid in self._jobs
            ]
        return max(ages) if ages else None

    def health_json(self) -> Dict[str, Any]:
        """The ``GET /v1/health`` body: SLO attainment/burn states, queue
        pressure (per-tier depth + starvation age), per-agent duty
        cycle/MFU/liveness, and one rolled-up verdict — the signal vector
        ROADMAP item 4's autoscaler consumes."""
        slo_results = self.slo.evaluate() if self.slo is not None else []
        with self._lock:
            counts: Dict[str, int] = {}
            for job in self._jobs.values():
                counts[job.state] = counts.get(job.state, 0) + 1
            queue_depth = self._sched.total()
            by_tier = self._sched.depth_by_priority()
            now = self._clock()
            ages = [
                now - self._jobs[jid].submitted_at
                for jid in self._sched.queued_ids()
                if jid in self._jobs
            ]
            agents = {
                a: {
                    "last_seen_wall": e.get("last_seen_wall", 0.0),
                    "obs": e.get("obs"),
                    "draining": bool(e.get("draining")),
                }
                for a, e in self.agent_metrics.items()
            }
        return build_health(
            slo_enabled=self.slo is not None,
            slo_objectives=slo_results,
            counts=counts,
            queue_depth=queue_depth,
            queue_by_tier=by_tier,
            starvation_age_sec=max(ages) if ages else None,
            agents=agents,
            agent_stale_sec=self.slo_config.agent_stale_sec,
            partition=self.partition,
            anomalies=(
                self.anomaly.active() if self.anomaly is not None else ()
            ),
        )

    @property
    def _queue(self) -> List[str]:
        """Queued job ids in dispatch order (legacy introspection surface —
        the list the scheduler replaced; tests and debugging peek at it)."""
        return self._sched.queued_ids()

    def _update_queue_stats_locked(self, now: Optional[float] = None) -> None:
        """Refresh the depth gauges: controller_queue_depth{state} splits
        leasable from requeue-delay-held jobs; sched_queue_depth{tenant} is
        the per-tenant fair-share view. Only jobs that ever received a
        requeue delay are scanned (the ``_delayed`` set), so the hot submit
        path stays O(1) in queue length."""
        if now is None:
            now = self._clock()
        total = self._sched.total()
        held = 0
        for jid in list(self._delayed):
            job = self._jobs.get(jid)
            if job is None or job.state != PENDING or job.not_before <= now:
                self._delayed.discard(jid)
            else:
                held += 1
        self._m_queue_depth.set(total - held, state="leasable")
        self._m_queue_depth.set(held, state="held")
        depths = self._sched.depth_by_tenant()
        self._seen_tenants.update(depths)
        for tenant in self._seen_tenants:
            self._m_sched_depth.set(depths.get(tenant, 0), tenant=tenant)

    # ---- durability (journal) ----

    def _journal(self, event: Dict[str, Any]) -> None:
        # Caller holds the lock; writes are ordered with the state changes
        # they record. fsync is opt-in (JOURNAL_FSYNC — ISSUE 14): by
        # default the journal protects against controller restarts, not
        # kernel crashes, and a 10M-row drain posts thousands of results.
        if self._journal_impl is not None:
            self._journal_impl.append(event)
            self._m_journal_writes.inc(ev=str(event.get("ev", "?")))

    def _apply_replay_event(self, ev: Dict[str, Any]) -> None:
        """Apply ONE journal event to job state — the unit shared by
        restart replay and the hot standby's live tail (ISSUE 14). Caller
        holds the lock (or is pre-serving __init__)."""
        if ev.get("ev") == "submit":
            after_order = tuple(ev.get("after") or ())
            raw_max = ev.get("max_attempts")
            raw_deadline = ev.get("deadline_sec")
            job = Job(
                job_id=ev["job_id"],
                op=ev["op"],
                payload=ev.get("payload") or {},
                after=set(after_order),
                after_order=after_order,
                required_labels=ev.get("required_labels") or {},
                max_attempts=int(raw_max) if raw_max else None,
                # Journal schema vN+1 (ISSUE 4): scheduling fields ride
                # the submit record only when the submitter set them, so
                # old journals (and default submissions) replay — and
                # re-journal — byte-identically.
                priority=int(
                    ev.get("priority", self.sched_config.default_priority)
                ),
                tenant=str(ev.get("tenant", DEFAULT_TENANT)),
                deadline_sec=float(raw_deadline) if raw_deadline else None,
            )
            # Workflow membership (ISSUE 19) replays from the ``workflow``
            # event that preceded the stage submits in the journal — the
            # submit record itself stays byte-identical to every prior
            # schema.
            info = self._job_workflow.get(job.job_id)
            if info is not None:
                job.workflow_id, job.stage = info
                wf = self._workflows.get(job.workflow_id)
                if wf is not None:
                    job.critical_path = int(
                        wf["critical_path"].get(job.stage, 0)
                    )
            for dep in after_order:
                self._dependents.setdefault(dep, set()).add(job.job_id)
            self._jobs[job.job_id] = job
            self._depended_on.update(after_order)
        elif ev.get("ev") == "workflow":
            # Graph bookkeeping rebuilds BEFORE the stage submits replay;
            # the root span is recreated at finalize (traces are in-memory
            # and did not survive).
            self._register_workflow_locked(
                str(ev.get("workflow_id")),
                ev.get("graph") or {},
                tenant=str(ev.get("tenant", DEFAULT_TENANT)),
                priority=int(
                    ev.get("priority", self.sched_config.default_priority)
                ),
                stage_jobs={
                    str(k): list(v)
                    for k, v in (ev.get("stage_jobs") or {}).items()
                },
                root_span_id=None,
                now=self._clock(),
            )
        elif ev.get("ev") == "result":
            job = self._jobs.get(ev.get("job_id"))
            if job is None:
                return
            job.state = ev.get("state", job.state)
            job.epoch = int(ev.get("epoch", job.epoch))
            job.attempts = int(ev.get("attempts", job.attempts))
            job.result = ev.get("result")
            job.error = ev.get("error")
            if ev.get("cache_hit"):
                info = self._job_workflow.get(job.job_id)
                wf = (
                    self._workflows.get(info[0]) if info is not None else None
                )
                if wf is not None:
                    wf["cache_hits"] += 1
            if self.usage is not None and isinstance(
                ev.get("usage"), dict
            ):
                # Replay-correct showback (ISSUE 9): billed usage rides
                # the result event, so a restarted controller's
                # /v1/usage reports the same totals the dead one did.
                self.usage.bill(
                    job.job_id, tenant=job.tenant, tier=job.priority,
                    op=job.op, attempt=ev.get("attempts", 0),
                    usage=ev["usage"],
                )
        elif ev.get("ev") == "requeue":
            # Lease-expiry epoch bump: must replay, or a result the
            # previous incarnation had fenced off could be accepted
            # after restart (its epoch would collide with ours).
            job = self._jobs.get(ev.get("job_id"))
            if job is not None:
                job.epoch = int(ev.get("epoch", job.epoch))

    def _load_snapshot_state(
        self, doc: Dict[str, Any], mirror: bool = True
    ) -> None:
        """Rehydrate job state from a compacting snapshot (ISSUE 14). Job
        records are stored in insertion order, so the post-load requeue
        step reproduces exactly the scheduler order a full-history replay
        would have built. Results ride only for depended-on jobs — the
        same bound the journal's result events keep."""
        for wrec in doc.get("workflows") or []:
            # Workflow records load FIRST so job membership re-attaches
            # while the job records stream in below.
            if not isinstance(wrec, dict) or "workflow_id" not in wrec:
                continue
            self._register_workflow_locked(
                str(wrec["workflow_id"]),
                wrec.get("graph") or {},
                tenant=str(wrec.get("tenant", DEFAULT_TENANT)),
                priority=int(
                    wrec.get(
                        "priority", self.sched_config.default_priority
                    )
                ),
                stage_jobs={
                    str(k): list(v)
                    for k, v in (wrec.get("stage_jobs") or {}).items()
                },
                root_span_id=None,
                now=self._clock(),
            )
            wf = self._workflows[str(wrec["workflow_id"])]
            wf["cache_hits"] = int(wrec.get("cache_hits", 0))
        for rec in doc.get("jobs") or []:
            after_order = tuple(rec.get("after") or ())
            raw_max = rec.get("max_attempts")
            raw_deadline = rec.get("deadline_sec")
            job = Job(
                job_id=rec["job_id"],
                op=rec.get("op", "?"),
                payload=rec.get("payload") or {},
                epoch=int(rec.get("epoch", 0)),
                state=str(rec.get("state", PENDING)),
                attempts=int(rec.get("attempts", 0)),
                result=rec.get("result"),
                error=rec.get("error"),
                after=set(after_order),
                after_order=after_order,
                required_labels=rec.get("required_labels") or {},
                max_attempts=int(raw_max) if raw_max else None,
                priority=int(
                    rec.get("priority", self.sched_config.default_priority)
                ),
                tenant=str(rec.get("tenant", DEFAULT_TENANT)),
                deadline_sec=float(raw_deadline) if raw_deadline else None,
            )
            info = self._job_workflow.get(job.job_id)
            if info is not None:
                job.workflow_id, job.stage = info
                wfrec = self._workflows.get(job.workflow_id)
                if wfrec is not None:
                    job.critical_path = int(
                        wfrec["critical_path"].get(job.stage, 0)
                    )
            for dep in after_order:
                self._dependents.setdefault(dep, set()).add(job.job_id)
            self._jobs[job.job_id] = job
            self._depended_on.update(after_order)
        if self.usage is not None and isinstance(doc.get("usage"), dict):
            self.usage.import_state(doc["usage"], mirror=mirror)

    def _finalize_replay_locked(self) -> None:
        """The replay→serving transition: jobs that were pending or in
        flight when the previous controller died re-queue at their CURRENT
        epoch — deliberately NOT bumped (ISSUE 3). Every deliberate fence
        (expiry/retry requeue) was journaled and already replayed; bumping
        here as well would fence the *good* results agents spooled while
        the controller was down, re-executing finished shards on every
        restart. An agent whose lease straddled the restart redelivers at
        the same epoch and is accepted; if the job was meanwhile re-leased
        and completed by someone else, the terminal-state guard rejects
        the second application (first wins) — never applied twice either
        way. Shared by restart replay and hot-standby promotion."""
        now = self._clock()
        # Workflow progress recomputes from the replayed job states
        # (ISSUE 19): counters fold whatever mix of snapshot + events got
        # us here, and still-running graphs get a fresh root span so
        # post-restart stage spans keep assembling into ONE tree.
        for wf in self._workflows.values():
            terminal = failed = 0
            for ids in wf["stage_jobs"].values():
                for jid in ids:
                    job = self._jobs.get(jid)
                    if job is None:
                        # Retention-dropped terminal stage job: it only
                        # left the snapshot because it was terminal.
                        terminal += 1
                        continue
                    if job.state in TERMINAL_STATES:
                        terminal += 1
                        if job.state != SUCCEEDED:
                            failed += 1
            wf["terminal_jobs"] = terminal
            wf["failed_jobs"] = failed
            if terminal >= wf["total_jobs"]:
                wf["state"] = "succeeded" if failed == 0 else "dead"
            else:
                wf["state"] = "running"
                wf["root_span_id"] = self.traces.open(
                    wf["workflow_id"], "workflow", start_clock=now,
                    attributes={
                        "replayed": True, "tenant": wf["tenant"],
                        "stages": len(wf["stage_order"]),
                    },
                )
        for job in self._jobs.values():
            if job.state not in TERMINAL_STATES:
                job.state = PENDING
                job.lease_id = None
                # Deadlines re-anchor to replay time (the journal carries no
                # wall clock); queue-wait attribution restarts here too.
                job.submitted_at = now
                job.enqueued_clock = now
                # Traces are in-memory and did not survive the restart: a
                # fresh root span lets post-restart spans still assemble.
                parent_span = None
                if job.workflow_id is not None:
                    wf = self._workflows.get(job.workflow_id)
                    parent_span = (wf or {}).get("root_span_id")
                job.root_span_id = self.traces.open(
                    job.trace_root, "submit", parent_span_id=parent_span,
                    start_clock=now,
                    attributes={"op": job.op, "replayed": True},
                )
                self._sched.add(job)
                if job.deadline_sec is not None:
                    self._deadlined.add(job.job_id)
            elif (
                self.result_cache is not None
                and job.state == SUCCEEDED
                and not job.after_order
                and isinstance(job.result, dict)
                and is_cacheable(job.op)
            ):
                # Warm the result cache from replayed dep-free results: a
                # restart must not forfeit the dedupe it already earned.
                # (Dep-gated jobs are skipped — their cache key covers the
                # lease-time materialized partials, not the submit
                # payload.)
                self.result_cache.put(job.op, job.payload, job.result)
        # Replay-ordering re-arm/cascade fix (ISSUE 19 satellite): a
        # dep-gated job is requeued above in whatever state its upstreams
        # REPLAYED to, which can differ from the order things happened
        # live — an upstream that went terminal between the downstream's
        # submit record and the journal tail. Success re-arms for free
        # (dep checks read live state at lease time), but a FAILED/DEAD
        # upstream used to strand the dependent in pending forever: the
        # only cascade ran inside ``_serve_reap`` and touched serve jobs
        # alone. Walk the general cascade for every replayed failure so
        # batch/DAG dependents die (and journal) the same way live ones
        # do.
        for job in list(self._jobs.values()):
            if job.state in (FAILED, DEAD):
                self._cascade_dep_failure_locked(job, now)
        self._update_queue_stats_locked(now)

    def _replay_journal(self, impl: SegmentedJournal) -> None:
        """Rebuild job state from a previous incarnation's journal:
        snapshot (when present and valid) + uncovered segments. Runs
        before the journal opens for append, without the lock (no other
        thread can hold a reference yet)."""
        t0 = time.perf_counter()
        snap, events, stats = impl.replay()
        if snap is not None:
            self._load_snapshot_state(snap)
        for ev in events:
            self._apply_replay_event(ev)
        stats.duration_sec = time.perf_counter() - t0
        if stats.torn_tail:
            self._m_journal_torn.inc(stats.torn_tail)
            self.journal_torn_tail += stats.torn_tail
        if stats.skipped:
            # Mid-stream corruption is NOT a torn write: something else
            # damaged the journal. Skipping silently would quietly
            # resurrect or lose jobs, so count + warn (ISSUE 3 satellite).
            self.journal_replay_skipped += stats.skipped
            self._m_journal_skipped.inc(stats.skipped)
            log(
                "journal replay skipped unparseable mid-file lines",
                path=impl.path, count=stats.skipped,
                lines=stats.skipped_lines,
            )
        if stats.snapshot_invalid:
            self._m_snapshot_invalid.inc(stats.snapshot_invalid)
        self.journal_replay_sec = stats.duration_sec
        self.journal_replayed_events = stats.events
        self._m_replay_sec.set(round(stats.duration_sec, 6))
        self._finalize_replay_locked()

    # ---- snapshot / compaction (ISSUE 14) ----

    def _snapshot_state_locked(self) -> Dict[str, Any]:
        """Live state as one snapshot document: every job's replayable
        fields (in insertion order — the order replay rebuilds the
        scheduler from), result bodies only for depended-on jobs (the
        journal's own bound — a snapshot must not become a second copy of
        the drain output), and the usage ledger.

        Terminal-job retention (``SNAPSHOT_RETAIN_TERMINAL``): with a
        positive bound, only the newest N *droppable* terminal jobs ride
        the snapshot — jobs some non-terminal job still depends on are
        never dropped (a reduce must find its partials after a restart).
        Restart then forgets older completed jobs; their late duplicates
        reject as ``unknown job`` (still never re-applied), and restart
        cost becomes O(live state + N) regardless of history length."""
        retain = self.journal_config.snapshot_retain_terminal
        drop: Set[str] = set()
        if retain > 0:
            protected: Set[str] = set()
            for job in self._jobs.values():
                if job.state not in TERMINAL_STATES:
                    protected.update(job.after)
            droppable = [
                job.job_id for job in self._jobs.values()
                if job.state in TERMINAL_STATES
                and job.job_id not in protected
            ]
            if len(droppable) > retain:
                drop = set(droppable[: len(droppable) - retain])
        jobs: List[Dict[str, Any]] = []
        for job in self._jobs.values():
            if job.job_id in drop:
                continue
            rec: Dict[str, Any] = {
                "job_id": job.job_id,
                "op": job.op,
                "payload": job.payload,
                "state": job.state,
                "epoch": job.epoch,
                "attempts": job.attempts,
                "error": job.error,
                "after": list(job.after_order),
                "required_labels": job.required_labels,
                "max_attempts": job.max_attempts,
                "priority": job.priority,
                "tenant": job.tenant,
                "deadline_sec": job.deadline_sec,
            }
            if (
                job.job_id in self._depended_on
                or job.workflow_id is not None
            ):
                rec["result"] = job.result
            jobs.append(rec)
        state: Dict[str, Any] = {"jobs": jobs}
        if self._workflows:
            # Workflow graphs ride the snapshot (ISSUE 19) the same way
            # the ``workflow`` journal event rides the segments: replay
            # re-attaches stage-job membership from them. Progress
            # counters recompute from job states at finalize; only the
            # cache-hit count (not derivable from state) is carried.
            state["workflows"] = [
                {
                    "workflow_id": wf["workflow_id"],
                    "tenant": wf["tenant"],
                    "priority": wf["priority"],
                    "graph": wf["graph"],
                    "stage_jobs": wf["stage_jobs"],
                    "cache_hits": wf["cache_hits"],
                }
                for wf in self._workflows.values()
            ]
        if drop:
            state["dropped_terminal"] = len(drop)
        if self.usage is not None:
            # The ledger is aggregate-bounded on its own (USAGE_MAX_JOBS)
            # and keeps billing history for retention-dropped jobs — the
            # (job, attempt) dedupe must survive even for forgotten jobs.
            state["usage"] = self.usage.export_state()
        return state

    def maybe_snapshot(self, force: bool = False) -> Optional[str]:
        """Take a compacting snapshot when the configured cadence is due
        (``SNAPSHOT_EVERY_EVENTS`` appends since the last one). Called
        from ``sweep()`` and the post-lease backstop; ``force=True`` is
        the operator/test handle. The active segment rotates and the state
        captures under the lock; the atomic write + covered-segment GC
        run outside it. Returns the snapshot path, or None when not due
        or snapshotting is off."""
        impl = self._journal_impl
        if impl is None or not impl.segmented:
            return None
        if not force and not impl.snapshot_every_events:
            return None
        with self._lock:
            if not force and not impl.snapshot_due():
                return None
            through = impl.rotate_for_snapshot()
            state = self._snapshot_state_locked()
        path = impl.commit_snapshot(through, state)
        self._m_snapshots.inc()
        self.recorder.record(
            "journal_snapshot", through_seq=through, jobs=len(state["jobs"]),
        )
        return path

    def journal_status(self) -> Dict[str, Any]:
        """The ``/v1/status`` ``journal`` durability block (ISSUE 14
        satellite): replay damage + segment/snapshot/replay-cost numbers,
        one schema whether or not a journal is configured."""
        impl = self._journal_impl
        file_stats = impl.stats() if impl is not None else {}
        out = {
            "torn_tail": self.journal_torn_tail,
            "replay_skipped": self.journal_replay_skipped,
            "enabled": impl is not None,
            "segmented": bool(file_stats.get("segmented")),
            "segments": int(file_stats.get("segments", 0)),
            "bytes": int(file_stats.get("bytes", 0)),
            "snapshot_bytes": int(file_stats.get("snapshot_bytes", 0)),
            "snapshots_written": int(
                file_stats.get("snapshots_written", 0)
            ),
            "last_snapshot_age_sec": file_stats.get(
                "last_snapshot_age_sec"
            ),
            "last_replay_sec": round(self.journal_replay_sec, 6),
            "replayed_events": self.journal_replayed_events,
            "fsync": bool(file_stats.get("fsync")),
            "promotions": self.promotions,
        }
        if self.partition:
            out["partition"] = self.partition
        # Mirror the file-side numbers into gauges so the scrape surface
        # tracks them too (swarmtop, tsdb sparklines).
        if impl is not None:
            self._m_segments.set(out["segments"])
            self._m_journal_disk_bytes.set(out["bytes"])
            if out["last_snapshot_age_sec"] is not None:
                self._m_snapshot_age.set(out["last_snapshot_age_sec"])
        return out

    # ---- hot-standby surface (ISSUE 14; driven by controller/standby.py) --

    def apply_snapshot_doc(
        self, doc: Dict[str, Any], mirror: bool = True
    ) -> None:
        """Standby bootstrap/resync: load a snapshot into this replica
        under the lock. A RESYNC (the primary's compaction GC'd segments
        before the tail finished reading them) overwrites every job with
        the snapshot's authoritative fold — convergent, since the
        snapshot covers everything the lost segments held."""
        with self._lock:
            self._load_snapshot_state(doc, mirror=mirror)

    def apply_journal_event(self, ev: Dict[str, Any]) -> int:
        """Standby tail: apply one primary journal event to the warm
        replica. Returns 1 (applied) so callers can count lag drains."""
        with self._lock:
            self._apply_replay_event(ev)
        return 1

    def finalize_promotion(
        self,
        impl: SegmentedJournal,
        sweep_interval_sec: Optional[float] = None,
    ) -> None:
        """Promote this warm replica to the live controller: run the
        replay→serving transition (non-terminal jobs requeue at their
        current epoch — the same applied-once-or-cleanly-rejected fencing
        a restart gets), attach the journal for append (the standby opens
        it on a FRESH segment so a lingering primary file handle can never
        interleave with the new incarnation's appends), and start the
        sweeper."""
        with self._lock:
            self._finalize_replay_locked()
            self._journal_impl = impl
            self.promotions += 1
        self._m_promotions.inc()
        self.recorder.record("promotion", path=impl.path)
        log("standby promoted to primary", journal=impl.path)
        # Durable telemetry (ISSUE 20): the replica deferred the tsdb
        # store open (the dead primary owned the segment streams); the
        # promoted incarnation reopens them — open_for_append seals any
        # torn tail — so pre-kill history stays queryable after failover.
        self._open_tsdb_store()
        if sweep_interval_sec:
            self.start_sweeper(sweep_interval_sec)

    # ---- liveness (background TTL sweeper) ----

    def sweep(self) -> None:
        """Re-queue expired leases and enforce deadlines now (both also run
        inside every ``lease()``)."""
        with self._lock:
            self._expire_leases_locked()
            self._expire_deadlines_locked()
            # Held → leasable is a time-passive transition (not_before
            # elapsing): the sweep is what keeps the split gauge truthful
            # with no lease traffic.
            self._update_queue_stats_locked()
        if self.slo is not None:
            # Burn states must decay without traffic too (recovery after the
            # last slow request is itself a window rollover) — the sweeper
            # is the no-traffic evaluation cadence. Outside the lock: the
            # alert hook does file I/O on page entry.
            self.slo.evaluate()
        # Serving flush/reap (ISSUE 15): the sweeper keeps the front door
        # moving with no lease traffic — deadline flushes and completion
        # fan-outs don't wait for an agent poll.
        if self.serve_door is not None:
            self._serve_pump()
        # Trend ring (ISSUE 9): the sweeper is the steady sampling cadence;
        # the lease path backstops it under sweeper-less tests/drains.
        self._tsdb_sample()
        # Compaction cadence (ISSUE 14): snapshot when enough has been
        # journaled since the last one. Outside the state lock except for
        # the rotation + state capture inside maybe_snapshot itself. A
        # failing snapshot write must not kill the sweeper — segments
        # still replay, and the next cadence retries.
        try:
            self.maybe_snapshot()
        except OSError as exc:
            log("snapshot failed (segments still replay)",
                error=str(exc)[:200])

    def _tsdb_sample(self) -> None:
        """Rate-limited time-series sample (controller registry + fleet
        merge). Runs OUTSIDE the controller lock — fleet_snapshot takes it —
        and costs one clock read when no sample is due. The ring's
        ``on_sample`` hook fans each recorded sample out to the durable
        store and the anomaly detector (ISSUE 20)."""
        if self.tsdb is not None:
            self.tsdb.maybe_sample(
                lambda: (self.metrics.snapshot(), self.fleet_snapshot())
            )

    # ---- durable telemetry / anomaly / incidents (ISSUE 20) ----

    def _open_tsdb_store(self) -> None:
        """Open (or reopen after promotion) the on-disk store. Idempotent;
        a failed open degrades to ring-only telemetry, never a crash."""
        if (
            self.tsdb is None
            or not self.obs_config.tsdb_dir
            or self.tsdb_store is not None
        ):
            return
        try:
            self.tsdb_store = TsdbStore(
                self.obs_config.tsdb_dir,
                segment_max_bytes=self.obs_config.tsdb_segment_bytes,
                retention_raw_sec=self.obs_config.tsdb_retention_raw_sec,
                retention_1m_sec=self.obs_config.tsdb_retention_1m_sec,
                retention_10m_sec=self.obs_config.tsdb_retention_10m_sec,
                max_bytes=self.obs_config.tsdb_max_bytes,
            )
        except OSError as exc:
            log("tsdb store open failed (ring-only telemetry)",
                dir=self.obs_config.tsdb_dir, error=str(exc)[:200])

    def _on_tsdb_sample(
        self, wall: float, mono: float, data: Dict[str, Dict[str, float]]
    ) -> None:
        """Ring sample hook: persist to disk, score for anomalies, and
        bundle an incident when one confirms. Runs on the sampling thread
        (sweeper or lease path), outside the controller lock."""
        if self.tsdb_store is not None:
            self.tsdb_store.append_sample(wall, data)
        if self.anomaly is None:
            self._tsdb_prev_sample = {"wall": wall, "data": data}
            return
        sample = {"wall": wall, "data": data}
        events = self.anomaly.observe(self._tsdb_prev_sample, sample)
        self._tsdb_prev_sample = sample
        for ev in events:
            self.recorder.record("anomaly", **ev)
            log("anomaly confirmed", watch=ev.get("watch"),
                value=ev.get("value"), z=ev.get("z"))
            self._capture_incident(
                "anomaly", str(ev.get("watch")), dict(ev)
            )

    def _capture_incident(
        self, kind: str, key: str, reason: Dict[str, Any]
    ) -> None:
        """Snapshot one correlated forensics bundle: the telemetry window
        around the event, flight-recorder tail + today's SLO dumps, the
        reqlog slow tail, traces of the K worst requests, status and
        health. Bounded and content-addressed by the bundler; dedup and
        rate-limiting happen there too."""
        if self.incidents is None:
            return
        sections: Dict[str, Any] = {}
        if self.tsdb is not None:
            watched = [
                "controller_queue_depth", "serve_ttft_seconds_sum",
                "serve_ttft_seconds_count", "serve_kv_blocks_free",
                "device_duty_cycle", "result_post_failures_total",
                "controller_results_total",
            ]
            window: Dict[str, Any] = {}
            for name in watched:
                series = self.tsdb.series(name, window_sec=600.0)
                if series:
                    window[name] = series
            sections["timeseries"] = window
        sections["flight_recorder"] = self.recorder.events()[-200:]
        if self.slo_dump_paths:
            sections["slo_dumps"] = list(self.slo_dump_paths)[-8:]
        worst: List[Dict[str, Any]] = []
        if self.reqlog is not None:
            slow = self.reqlog.snapshot(slow=True, limit=64)
            sections["reqlog_slow"] = slow[:32]
            worst = sorted(
                (r for r in slow if isinstance(
                    r.get("ttft_ms"), (int, float))),
                key=lambda r: float(r["ttft_ms"]), reverse=True,
            )[: self.obs_config.incident_worst_k]
        if worst:
            traces = []
            for rec in worst:
                req_id = rec.get("req_id")
                if not req_id:
                    continue
                doc = self.traces.assemble(str(req_id))
                if doc is not None:
                    traces.append(doc)
            if traces:
                sections["worst_request_traces"] = traces
        sections["status"] = {
            "counts": self.counts(),
            "queue_depth": self.queue_depth(),
            "journal": self.journal_status(),
            "promotions": self.promotions,
            "partition": self.partition,
        }
        try:
            sections["health"] = self.health_json()
        except Exception:  # noqa: BLE001 — forensics best-effort
            pass
        bundle = self.incidents.capture(kind, key, reason, sections)
        if bundle is not None:
            self.recorder.record(
                "incident", id=bundle["id"], trigger=kind, key=key,
            )
            log("incident bundle captured", id=bundle["id"], kind=kind,
                key=key)

    def incidents_json(self, incident_id: Optional[str] = None) -> \
            Dict[str, Any]:
        """The ``GET /v1/incidents{,/id}`` body."""
        if self.incidents is None:
            if incident_id is not None:
                return {"enabled": False, "incident": None}
            return {"enabled": False, "incidents": [], "stats": {}}
        if incident_id is not None:
            return {
                "enabled": True,
                "incident": self.incidents.get(incident_id),
            }
        return {
            "enabled": True,
            "incidents": self.incidents.list(),
            "stats": self.incidents.stats(),
        }

    def timeseries_export_json(
        self, since: float, limit: int = 2000
    ) -> Dict[str, Any]:
        """The ``GET /v1/timeseries/export`` body — raw ring samples
        newer than ``since`` (the router collector's delta-scrape
        cursor)."""
        if self.tsdb is None:
            return {"enabled": False, "samples": [], "now": time.time()}
        return {
            "enabled": True,
            "samples": self.tsdb.samples_since(float(since), limit=limit),
            "interval_sec": self.tsdb.interval_sec,
            "partition": self.partition,
            "now": time.time(),
        }

    def start_sweeper(self, interval_sec: float = 5.0) -> None:
        """TTL enforcement without traffic: a daemon thread sweeping every
        ``interval_sec`` so dead agents' tasks re-queue even when no other
        agent is polling."""
        if self._sweeper is not None:
            return
        self._sweep_stop.clear()

        def loop() -> None:
            while not self._sweep_stop.wait(interval_sec):
                self.sweep()

        self._sweeper = threading.Thread(
            target=loop, name="lease-sweeper", daemon=True
        )
        self._sweeper.start()

    def close(self) -> None:
        """Stop the sweeper and close the journal (idempotent)."""
        if self.host_profiler is not None:
            self.host_profiler.stop()
        self._sweep_stop.set()
        if self._sweeper is not None:
            self._sweeper.join(timeout=5)
            self._sweeper = None
        if self.tsdb_store is not None:
            self.tsdb_store.close()
        with self._lock:
            if self._journal_impl is not None:
                self._journal_impl.close()
                self._journal_impl = None

    # ---- job submission ----

    def _admit_locked(self, tenant: str, n: int = 1) -> None:
        """Admission control (ISSUE 4): raise ``AdmissionError`` (wire: 429
        + retry_after_ms) when accepting ``n`` more jobs would breach the
        global or per-tenant pending budget. Budgets of 0 = unbounded, so
        the default configuration admits everything (fifo bit-compat)."""
        cfg = self.sched_config
        if cfg.max_pending and self._sched.total() + n > cfg.max_pending:
            self._m_admission.inc(tenant=tenant)
            self.recorder.record(
                "admission_rejected", tenant=tenant, scope="global",
                pending=self._sched.total(), budget=cfg.max_pending,
            )
            self._m_sched_decisions.inc(
                policy=cfg.policy, decision="admission_rejected")
            raise AdmissionError(
                f"pending budget exhausted ({self._sched.total()} queued, "
                f"global budget {cfg.max_pending})",
                retry_after_ms=cfg.retry_after_ms, tenant=tenant,
                scope="global",
            )
        if cfg.max_pending_per_tenant and (
            self._sched.depth_for(tenant) + n > cfg.max_pending_per_tenant
        ):
            self._m_admission.inc(tenant=tenant)
            self.recorder.record(
                "admission_rejected", tenant=tenant, scope="tenant",
                pending=self._sched.depth_for(tenant),
                budget=cfg.max_pending_per_tenant,
            )
            self._m_sched_decisions.inc(
                policy=cfg.policy, decision="admission_rejected")
            raise AdmissionError(
                f"tenant {tenant!r} pending budget exhausted "
                f"({self._sched.depth_for(tenant)} queued, budget "
                f"{cfg.max_pending_per_tenant})",
                retry_after_ms=cfg.retry_after_ms, tenant=tenant,
                scope="tenant",
            )

    def submit(
        self,
        op: str,
        payload: Optional[Dict[str, Any]] = None,
        job_id: Optional[str] = None,
        after: Optional[Sequence[str]] = None,
        required_labels: Optional[Dict[str, Any]] = None,
        max_attempts: Optional[int] = None,
        priority: Optional[int] = None,
        tenant: Optional[str] = None,
        deadline_sec: Optional[float] = None,
        workflow_id: Optional[str] = None,
        stage: Optional[str] = None,
        critical_path: int = 0,
    ) -> str:
        """Submit one job. The trailing workflow kwargs are internal —
        ``submit_workflow`` stamps DAG membership (graph id, stage name,
        remaining-critical-path length) onto the stage jobs it expands;
        they are deliberately NOT journaled on the submit record (the
        ``workflow`` journal event carries the graph once, and replay
        re-attaches membership from it), keeping plain submit bytes
        identical to every prior journal schema."""
        job_id = job_id or f"job-{self._id_tag}{uuid.uuid4().hex[:12]}"
        if priority is not None:
            if (
                isinstance(priority, bool)
                or not isinstance(priority, int)
                or not PRIORITY_MIN <= priority <= PRIORITY_MAX
            ):
                raise ValueError(
                    f"priority must be an int in "
                    f"[{PRIORITY_MIN}, {PRIORITY_MAX}], got {priority!r}"
                )
        if tenant is not None and (
            not isinstance(tenant, str) or not tenant
        ):
            raise ValueError(f"tenant must be a non-empty string, got {tenant!r}")
        if deadline_sec is not None:
            if (
                isinstance(deadline_sec, bool)
                or not isinstance(deadline_sec, (int, float))
                or deadline_sec <= 0
            ):
                raise ValueError(
                    f"deadline_sec must be a positive number, got "
                    f"{deadline_sec!r}"
                )
        if max_attempts is not None:
            if (
                isinstance(max_attempts, bool)
                or not isinstance(max_attempts, int)
                or max_attempts < 1
            ):
                raise ValueError(
                    f"max_attempts must be a positive int, got {max_attempts!r}"
                )
        required_labels = dict(required_labels or {})
        for k, v in required_labels.items():
            # Non-scalar requirements can never match the AGENT_LABELS
            # grammar (strings or True) — rejecting here turns would-be
            # silent starvation into an immediate submit error.
            if not isinstance(k, str) or not k:
                raise ValueError(f"required_labels keys must be strings, got {k!r}")
            scalar_ok = v is True or (
                isinstance(v, (str, int, float)) and not isinstance(v, bool)
            )
            if not scalar_ok:
                raise ValueError(
                    f"required_labels[{k!r}] must be True or a scalar, got {v!r}"
                )
        if isinstance(after, (set, frozenset)):
            # collect_partials materializes dependency results in after
            # order — an unordered collection would make shard order
            # nondeterministic. Force callers to pass a sequence.
            raise ValueError("after must be an ordered sequence, not a set")
        if isinstance(after, str):
            # tuple("job-1") would split into characters and the dependency
            # would silently vanish (unknown ids are skipped in dep checks).
            raise ValueError("after must be a sequence of job ids, not a str")
        after_order = tuple(after or ())
        job = Job(
            job_id=job_id,
            op=op,
            payload=payload or {},
            after=set(after_order),
            after_order=after_order,
            required_labels=required_labels,
            max_attempts=max_attempts,
            priority=(
                priority if priority is not None
                else self.sched_config.default_priority
            ),
            tenant=tenant if tenant is not None else DEFAULT_TENANT,
            deadline_sec=(
                float(deadline_sec) if deadline_sec is not None else None
            ),
            workflow_id=workflow_id,
            stage=stage,
            critical_path=max(0, int(critical_path)),
        )
        # Submit-time result-cache consult (ISSUE 19): a dep-free cacheable
        # WORKFLOW STAGE whose content key already has a stored result
        # never enters the queue — it lands terminal SUCCEEDED with the
        # cached bytes. Dep-gated stages consult at lease time instead
        # (their real input includes the partials that don't exist yet).
        # Plain ``POST /v1/jobs`` submits never consult: every non-DAG
        # submit executes, the contract the pre-DAG controller pinned
        # (test_sched's FIFO model, fault injection, standby promotion all
        # count on submitted == executed). The lookup runs outside the
        # controller lock (the cache has its own).
        cached_result: Optional[Dict[str, Any]] = None
        if (
            self.result_cache is not None
            and not after_order
            and workflow_id is not None
            and is_cacheable(op)
        ):
            cached_result = self.result_cache.get(op, job.payload)
            if cached_result is None:
                self._m_result_cache.inc(event="miss")
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"duplicate job id {job_id!r}")
            if cached_result is None:
                # A cache hit consumes no queue slot — admission control
                # guards the pending budget, and a hit never goes pending.
                self._admit_locked(job.tenant)
            now = self._clock()
            job.submitted_at = now
            job.enqueued_clock = now
            # Root of the job's span tree (ISSUE 5): open at submit, closed
            # when the job reaches a terminal state. trace_id = job_id —
            # except workflow stage jobs (ISSUE 19), whose spans parent to
            # the workflow's root so the whole DAG is ONE trace tree.
            span_attrs: Dict[str, Any] = {
                "op": op, "tenant": job.tenant, "priority": job.priority,
            }
            parent_span = None
            if workflow_id is not None:
                wf = self._workflows.get(workflow_id)
                parent_span = (wf or {}).get("root_span_id")
                span_attrs["stage"] = stage
            job.root_span_id = self.traces.open(
                job.trace_root, "submit", parent_span_id=parent_span,
                start_clock=now, attributes=span_attrs,
            )
            self._jobs[job_id] = job
            for dep in after_order:
                # Reverse dependency edges: what the generalized
                # DependencyFailed cascade walks (ISSUE 19).
                self._dependents.setdefault(dep, set()).add(job_id)
            if cached_result is None:
                self._sched.add(job)
                if job.deadline_sec is not None:
                    self._deadlined.add(job_id)
            self._update_queue_stats_locked(now)
            self.recorder.record("submit", job_id=job_id, op=op)
            self._depended_on.update(after_order)
            # Journal schema vN+1: the scheduling fields are appended only
            # when the caller set them, so default submissions keep writing
            # the exact bytes the pre-scheduler controller wrote (the fifo
            # byte-compat guarantee) and old journals replay unchanged.
            record = {
                "ev": "submit",
                "job_id": job_id,
                "op": op,
                "payload": job.payload,
                "after": list(after_order),
                "required_labels": required_labels,
                "max_attempts": max_attempts,
            }
            if priority is not None:
                record["priority"] = job.priority
            if tenant is not None:
                record["tenant"] = job.tenant
            if deadline_sec is not None:
                record["deadline_sec"] = job.deadline_sec
            self._journal(record)
            if cached_result is not None:
                # Terminal immediately: the submit record above plus the
                # cache-hit result record replay back to the same state.
                self._finalize_cache_hit_locked(
                    job, cached_result, now, plane="submit"
                )
        return job_id

    def suggested_shard_size(self) -> Optional[int]:
        """The ``tpu.suggested_shard_rows`` hint from the most recent lease
        that carried one (``sizing/profile.py`` derives it from chip count ×
        HBM), or None when no TPU agent has leased yet. CPU agents polling in
        a mixed fleet do not revert the hint."""
        with self._lock:
            profile = self._last_tpu_profile
        tpu = (profile or {}).get("tpu") or {}
        rows = tpu.get("suggested_shard_rows")
        if isinstance(rows, (int, float)) and not isinstance(rows, bool) \
                and rows > 0:
            return int(rows)
        return None

    def submit_csv_job(
        self,
        source_uri: str,
        total_rows: int,
        shard_size: Optional[int] = None,
        map_op: str = "read_csv_shard",
        extra_payload: Optional[Dict[str, Any]] = None,
        reduce_op: Optional[str] = None,
        reduce_payload: Optional[Dict[str, Any]] = None,
        required_labels: Optional[Dict[str, Any]] = None,
        collect_partials: bool = False,
        max_attempts: Optional[int] = None,
        priority: Optional[int] = None,
        tenant: Optional[str] = None,
        deadline_sec: Optional[float] = None,
    ) -> Tuple[List[str], Optional[str]]:
        """Split a CSV dataset into shard tasks (+ optional gated reduce job).

        Shards address rows ``[start_row, start_row + shard_size)`` — idempotent
        re-execution is the resume unit (SURVEY.md §5.4).

        ``shard_size=None`` closes the sizing→controller loop (SURVEY.md §2.5):
        the split uses the submitting cluster's last-seen worker profile
        (``tpu.suggested_shard_rows``, derived from topology + HBM), falling
        back to the reference's 100-row default when no TPU agent has leased
        yet. Pass an explicit size to override.

        With ``collect_partials`` the controller materializes the shard jobs'
        results into the reduce job's ``partials`` payload when it leases —
        the "partials combined controller-side" flow the reference implied
        (SURVEY.md §5.8) made explicit, e.g. ``map_op="risk_accumulate"``
        (per-shard stats) + ``reduce_op="risk_accumulate"`` (merge).
        """
        if shard_size is None:
            shard_size = self.suggested_shard_size() or DEFAULT_SHARD_ROWS
        if shard_size <= 0:
            raise ValueError("shard_size must be positive")
        if total_rows <= 0:
            # Zero shards + an immediately-leasable reduce-over-nothing is
            # never what the caller meant.
            raise ValueError("total_rows must be positive")
        # Whole-batch admission pre-check: reject before the first shard
        # submits rather than 429-ing mid-split and leaving a half-submitted
        # job behind. (Advisory — each submit re-checks under the lock.)
        n_jobs = -(-total_rows // shard_size) + (1 if reduce_op else 0)
        with self._lock:
            self._admit_locked(tenant if tenant is not None else DEFAULT_TENANT,
                               n_jobs)
        shard_ids: List[str] = []
        for i, start in enumerate(range(0, total_rows, shard_size)):
            payload = dict(extra_payload or {})
            payload.update(
                source_uri=source_uri,
                start_row=start,
                shard_size=min(shard_size, total_rows - start),
            )
            shard_ids.append(
                self.submit(
                    map_op,
                    payload,
                    job_id=f"shard-{i}-{self._id_tag}{uuid.uuid4().hex[:8]}",
                    required_labels=required_labels,
                    max_attempts=max_attempts,
                    priority=priority,
                    tenant=tenant,
                    deadline_sec=deadline_sec,
                )
            )
        reduce_id = None
        if reduce_op is not None:
            payload = dict(reduce_payload or {})
            if collect_partials:
                payload["__collect_partials__"] = True
            reduce_id = self.submit(
                reduce_op,
                payload,
                after=shard_ids,  # ordered: partials materialize shard-order
                required_labels=required_labels,
                max_attempts=max_attempts,
                priority=priority,
                tenant=tenant,
                deadline_sec=deadline_sec,
            )
        return shard_ids, reduce_id

    # ---- workflow DAG engine + result cache (ISSUE 19) ----

    def submit_workflow(
        self,
        workflow: Dict[str, Any],
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
        deadline_sec: Optional[float] = None,
        workflow_id: Optional[str] = None,
    ) -> Dict[str, Any]:
        """``POST /v1/workflows``: accept a fan-out/fan-in graph as ONE
        unit, validate it (acyclic, known ops, bounded width — ``DagError``
        maps to HTTP 400), expand stages into ordinary jobs with
        generalized dep edges, and journal the graph FIRST so replay and
        standby promotion rebuild membership before any stage submit
        replays. Returns ``{workflow_id, job_ids, stages}``."""
        if not self.flow_config.enabled:
            raise RuntimeError("workflows are disabled (FLOW_ENABLED=0)")
        spec = parse_workflow(
            workflow,
            known_ops=list(OP_TO_MODULE),
            max_stages=self.flow_config.max_stages,
            max_width=self.flow_config.max_width,
        )
        if priority is not None and (
            isinstance(priority, bool) or not isinstance(priority, int)
            or not PRIORITY_MIN <= priority <= PRIORITY_MAX
        ):
            raise ValueError(
                f"priority must be an int in [{PRIORITY_MIN}, "
                f"{PRIORITY_MAX}], got {priority!r}"
            )
        if tenant is not None and (
            not isinstance(tenant, str) or not tenant
        ):
            raise ValueError(
                f"tenant must be a non-empty string, got {tenant!r}"
            )
        if workflow_id is not None and (
            not isinstance(workflow_id, str) or not workflow_id
        ):
            raise ValueError("workflow_id must be a non-empty string")
        workflow_id = (
            workflow_id or f"wf-{self._id_tag}{uuid.uuid4().hex[:12]}"
        )
        default_priority = (
            priority if priority is not None
            else self.sched_config.default_priority
        )
        tenant_val = tenant if tenant is not None else DEFAULT_TENANT
        planned = expand_workflow(
            spec, workflow_id, default_priority=default_priority
        )
        graph = graph_doc(spec)
        stage_jobs: Dict[str, List[str]] = {}
        for pj in planned:
            stage_jobs.setdefault(pj.stage, []).append(pj.job_id)
        with self._lock:
            if workflow_id in self._workflows:
                raise ValueError(f"duplicate workflow id {workflow_id!r}")
            if any(pj.job_id in self._jobs for pj in planned):
                raise ValueError(
                    f"workflow {workflow_id!r} stage job ids collide with "
                    "existing jobs"
                )
            # Whole-graph admission pre-check (the CSV rule): reject before
            # the first stage submits rather than 429 mid-expansion.
            self._admit_locked(tenant_val, len(planned))
            now = self._clock()
            root_span = self.traces.open(
                workflow_id, "workflow", start_clock=now,
                attributes={
                    "stages": len(spec.stages), "jobs": len(planned),
                    "tenant": tenant_val, "priority": default_priority,
                },
            )
            self._register_workflow_locked(
                workflow_id, graph, tenant_val, default_priority,
                stage_jobs, root_span_id=root_span, now=now,
            )
            self._m_workflows.inc(outcome="submitted")
            self.recorder.record(
                "workflow_submit", workflow_id=workflow_id,
                stages=len(spec.stages), jobs=len(planned),
            )
            self._journal({
                "ev": "workflow",
                "workflow_id": workflow_id,
                "tenant": tenant_val,
                "priority": default_priority,
                "graph": graph,
                "stage_jobs": stage_jobs,
            })
        job_ids: List[str] = []
        for pj in planned:
            job_ids.append(self.submit(
                pj.op,
                pj.payload,
                job_id=pj.job_id,
                after=list(pj.after),
                required_labels=pj.required_labels,
                max_attempts=pj.max_attempts,
                priority=pj.priority,
                tenant=tenant,
                deadline_sec=deadline_sec,
                workflow_id=workflow_id,
                stage=pj.stage,
                critical_path=pj.critical_path,
            ))
            self._m_flow_stage_jobs.inc(op=pj.op)
        return {
            "workflow_id": workflow_id,
            "job_ids": job_ids,
            "stages": [s.name for s in spec.stages],
        }

    def _register_workflow_locked(
        self,
        workflow_id: str,
        graph: Dict[str, Any],
        tenant: str,
        priority: int,
        stage_jobs: Dict[str, List[str]],
        root_span_id: Optional[str],
        now: float,
    ) -> None:
        """Install the per-graph bookkeeping record + job membership map.
        Shared by live submit and journal replay (the ``workflow`` event)."""
        spec = spec_from_graph_doc(graph)
        cp = critical_path_lengths(spec)
        total = sum(len(ids) for ids in stage_jobs.values())
        self._workflows[workflow_id] = {
            "workflow_id": workflow_id,
            "tenant": tenant,
            "priority": priority,
            "graph": graph,
            "stage_jobs": {k: list(v) for k, v in stage_jobs.items()},
            "stage_order": [s.name for s in spec.stages],
            "critical_path": cp,
            "total_jobs": total,
            "terminal_jobs": 0,
            "failed_jobs": 0,
            "cache_hits": 0,
            "state": "running",
            "root_span_id": root_span_id,
            "submitted_clock": now,
            "submitted_wall": time.time(),
        }
        for stage, ids in stage_jobs.items():
            for jid in ids:
                self._job_workflow[jid] = (workflow_id, stage)

    def _workflow_note_terminal_locked(self, job: Job, now: float) -> None:
        """Progress accounting on any stage job reaching a terminal state.
        When the last stage job lands, the workflow itself goes terminal:
        root span finished (closing the single DAG trace tree), outcome
        counted, recorder event."""
        info = self._job_workflow.get(job.job_id)
        if info is None:
            return
        wf = self._workflows.get(info[0])
        if wf is None or wf["state"] != "running":
            return
        wf["terminal_jobs"] += 1
        if job.state != SUCCEEDED:
            wf["failed_jobs"] += 1
        if wf["terminal_jobs"] < wf["total_jobs"]:
            return
        wf["state"] = "succeeded" if wf["failed_jobs"] == 0 else "dead"
        wf["finished_clock"] = now
        self.traces.finish(
            wf["workflow_id"], wf.get("root_span_id"), now,
            attributes={
                "outcome": wf["state"], "failed_jobs": wf["failed_jobs"],
                "cache_hits": wf["cache_hits"],
            },
        )
        self._m_workflows.inc(outcome=wf["state"])
        self.recorder.record(
            "workflow_done", workflow_id=wf["workflow_id"],
            outcome=wf["state"], failed_jobs=wf["failed_jobs"],
            cache_hits=wf["cache_hits"],
        )

    def _cascade_dep_failure_locked(self, failed: Job, now: float) -> None:
        """Generalized DependencyFailed cascade (ISSUE 19): walk the
        REVERSE dep edges from a terminally-failed job and kill every
        still-pending dependent, transitively — a workflow's downstream
        stages must not sit queued forever behind a dead upstream. This
        supersedes the serve-only scan ``_serve_reap`` used to carry (that
        path now rides the same edges). Each death journals as a result
        record so replay keeps it dead.

        Scope: workflow members and serve-door jobs (the two populations
        with a waiter who must see the failure). Plain dep-gated jobs keep
        the legacy contract — a dead upstream leaves them pending, the
        behavior the pre-DAG controller pinned (test_sched's FIFO model
        replays interleavings against it byte-for-byte)."""
        if failed.state not in (FAILED, DEAD):
            return
        serve_ids = (
            set(self.serve_door.job_ids())
            if self.serve_door is not None else set()
        )
        stack = [failed.job_id]
        while stack:
            dead_id = stack.pop()
            for dep_id in sorted(self._dependents.get(dead_id, ())):
                job = self._jobs.get(dep_id)
                if job is None or job.state != PENDING:
                    continue
                if job.workflow_id is None and dep_id not in serve_ids:
                    continue
                self._sched.discard(dep_id)
                self._delayed.discard(dep_id)
                self._deadlined.discard(dep_id)
                job.error = {
                    "type": "DependencyFailed",
                    "message": f"dependency {dead_id} failed",
                    "trace": "",
                }
                job.state = DEAD
                self.traces.finish(
                    job.trace_root, job.root_span_id, now,
                    attributes={
                        "outcome": DEAD, "reason": "DependencyFailed",
                    },
                )
                self._slo_observe_locked(job, now)
                self._m_dead.inc(op=job.op)
                self.recorder.record(
                    "dead", job_id=dep_id, op=job.op,
                    reason="dependency", attempts=job.attempts,
                )
                self._journal({
                    "ev": "result",
                    "job_id": dep_id,
                    "state": DEAD,
                    "epoch": job.epoch,
                    "attempts": job.attempts,
                    "result": None,
                    "error": job.error,
                })
                self._workflow_note_terminal_locked(job, now)
                stack.append(dep_id)
        self._update_queue_stats_locked(now)

    def _finalize_cache_hit_locked(
        self, job: Job, result: Dict[str, Any], now: float, plane: str
    ) -> None:
        """Land a result-cache hit as a terminal SUCCEEDED application:
        journaled as a cache-hit result event (with the result BODY —
        downstream stages and replay must see the exact cached bytes),
        billed at cache price in the usage ledger, root span closed with
        the hit attribute, workflow progress noted. Caller holds the lock
        and has kept the job out of (or removed it from) the queue."""
        job.result = result
        job.error = None
        job.state = SUCCEEDED
        self._delayed.discard(job.job_id)
        self._deadlined.discard(job.job_id)
        self._m_result_cache.inc(event=f"hit_{plane}")
        self.recorder.record(
            "cache_hit", job_id=job.job_id, op=job.op, plane=plane,
        )
        if job.lease_span_id is not None:
            self.traces.finish(
                job.trace_root, job.lease_span_id, now,
                attributes={"outcome": SUCCEEDED, "cache_hit": True},
            )
            job.lease_span_id = None
        self.traces.finish(
            job.trace_root, job.root_span_id, now,
            attributes={"outcome": SUCCEEDED, "cache_hit": True},
        )
        self._slo_observe_locked(job, now)
        billed = None
        if self.usage is not None:
            billed = self.usage.bill(
                job.job_id, tenant=job.tenant, tier=job.priority,
                op=job.op, attempt=job.attempts,
                usage={"result_cache_hits": 1},
            )
        record: Dict[str, Any] = {
            "ev": "result",
            "job_id": job.job_id,
            "state": SUCCEEDED,
            "epoch": job.epoch,
            "attempts": job.attempts,
            "result": job.result,
            "error": None,
            "cache_hit": True,
        }
        if billed is not None:
            record["usage"] = billed
        self._journal(record)
        info = self._job_workflow.get(job.job_id)
        if info is not None:
            wf = self._workflows.get(info[0])
            if wf is not None:
                wf["cache_hits"] += 1
        self._workflow_note_terminal_locked(job, now)

    def workflow_json(self, workflow_id: str) -> Optional[Dict[str, Any]]:
        """``GET /v1/workflows/{id}``: graph + per-stage progress + the
        critical-path stage (deepest remaining work — what the scheduler
        is preferring right now) + terminal results of the sink stages."""
        with self._lock:
            wf = self._workflows.get(workflow_id)
            if wf is None:
                return None
            stages = []
            critical_stage = None
            critical_depth = -1
            for stage in wf["stage_order"]:
                ids = wf["stage_jobs"].get(stage, [])
                counts: Dict[str, int] = {}
                for jid in ids:
                    job = self._jobs.get(jid)
                    state = job.state if job is not None else "forgotten"
                    counts[state] = counts.get(state, 0) + 1
                remaining = sum(
                    n for s, n in counts.items()
                    if s not in TERMINAL_STATES
                )
                depth = int(wf["critical_path"].get(stage, 0))
                if remaining and depth > critical_depth:
                    critical_depth = depth
                    critical_stage = stage
                stages.append({
                    "name": stage,
                    "jobs": len(ids),
                    "counts": counts,
                    "critical_path": depth,
                })
            # Sink results: stages nothing depends on (fan-in outputs).
            downstream: Set[str] = set()
            for raw in wf["graph"].get("stages", []):
                downstream.update(raw.get("after") or ())
            results: Dict[str, Any] = {}
            for stage in wf["stage_order"]:
                if stage in downstream:
                    continue
                for jid in wf["stage_jobs"].get(stage, []):
                    job = self._jobs.get(jid)
                    if job is not None and job.state == SUCCEEDED:
                        results[jid] = job.result
            out = {
                "workflow_id": workflow_id,
                "tenant": wf["tenant"],
                "priority": wf["priority"],
                "state": wf["state"],
                "stages": stages,
                "total_jobs": wf["total_jobs"],
                "terminal_jobs": wf["terminal_jobs"],
                "failed_jobs": wf["failed_jobs"],
                "cache_hits": wf["cache_hits"],
                "critical_stage": critical_stage,
                "submitted_wall": round(wf["submitted_wall"], 3),
                "results": results,
            }
            if self.partition:
                out["partition"] = self.partition
            return out

    def workflows_json(self) -> Dict[str, Any]:
        """Summary list for swarmtop's Workflows panel + ``--json``."""
        with self._lock:
            items = []
            for wf in self._workflows.values():
                done = wf["terminal_jobs"]
                critical_stage = None
                critical_depth = -1
                for stage in wf["stage_order"]:
                    remaining = sum(
                        1 for jid in wf["stage_jobs"].get(stage, [])
                        if (j := self._jobs.get(jid)) is not None
                        and j.state not in TERMINAL_STATES
                    )
                    depth = int(wf["critical_path"].get(stage, 0))
                    if remaining and depth > critical_depth:
                        critical_depth = depth
                        critical_stage = stage
                items.append({
                    "workflow_id": wf["workflow_id"],
                    "tenant": wf["tenant"],
                    "state": wf["state"],
                    "stages": len(wf["stage_order"]),
                    "total_jobs": wf["total_jobs"],
                    "terminal_jobs": done,
                    "failed_jobs": wf["failed_jobs"],
                    "cache_hits": wf["cache_hits"],
                    "critical_stage": critical_stage,
                })
            cache = (
                self.result_cache.stats()
                if self.result_cache is not None else None
            )
        return {"workflows": items, "result_cache": cache}

    # ---- fault injection (SURVEY.md §5.3, extended by ISSUE 3) ----

    def inject(self, fault: Optional[str] = None, plan: Any = None) -> None:
        """Arm a one-shot fault by name, or install a seeded probabilistic
        ``chaos.FaultPlan`` (``inject(plan=...)``) consulted on every lease —
        sustained, reproducible failure instead of a single shot. Passing
        ``plan=None`` with no fault name clears an installed plan."""
        if fault is not None:
            if fault not in ("drop_lease", "duplicate_task", "stale_epoch"):
                raise ValueError(f"unknown fault {fault!r}")
            with self._lock:
                self._faults.append(fault)
            return
        with self._lock:
            self._fault_plan = plan

    def _take_fault(self, fault: str) -> bool:
        # caller holds the lock
        if fault in self._faults:
            self._faults.remove(fault)
            return True
        return False

    # ---- lease protocol ----

    def _expire_leases_locked(self) -> None:
        now = self._clock()
        for job in self._jobs.values():
            if job.state == LEASED and now >= job.lease_deadline:
                # Dead agent: re-queue with a bumped epoch so its late result
                # is discarded on arrival.
                job.epoch += 1
                job.state = PENDING
                job.lease_id = None
                self.traces.finish(
                    job.trace_root, job.lease_span_id, now,
                    attributes={"outcome": "expired"},
                )
                job.lease_span_id = None
                job.enqueued_clock = now
                self._sched.add(job)
                self._m_expirations.inc(op=job.op)
                self._update_queue_stats_locked(now)
                self.recorder.record(
                    "lease_expired", job_id=job.job_id, op=job.op,
                    epoch=job.epoch, agent=job.agent,
                )
                self._journal(
                    {"ev": "requeue", "job_id": job.job_id, "epoch": job.epoch}
                )

    def _expire_deadlines_locked(self) -> None:
        """Deadline/TTL enforcement (ISSUE 4): a PENDING job whose
        ``deadline_sec`` elapsed lands the existing terminal ``dead`` state
        with a distinct ``DeadlineExceeded`` reason; a still-pending job
        past ``SCHED_ESCALATE_FRAC`` of its deadline escalates one priority
        tier (once). Leased jobs are left alone — an in-flight attempt may
        still beat the deadline, and its result is accepted if it does."""
        if not self._deadlined:
            return
        now = self._clock()
        frac = self.sched_config.escalate_frac
        for jid in list(self._deadlined):
            job = self._jobs.get(jid)
            if job is None or job.state in TERMINAL_STATES \
                    or job.deadline_sec is None:
                self._deadlined.discard(jid)
                continue
            age = now - job.submitted_at
            if job.state != PENDING:
                continue  # leased: give the in-flight attempt its chance
            if age >= job.deadline_sec:
                self._sched.discard(jid)
                self._delayed.discard(jid)
                self._deadlined.discard(jid)
                job.error = {
                    "type": "DeadlineExceeded",
                    "message": (
                        f"deadline_sec {job.deadline_sec} elapsed after "
                        f"{job.attempts} attempt(s)"
                    ),
                    "trace": "",
                }
                job.state = DEAD
                self.traces.finish(
                    job.trace_root, job.root_span_id, now,
                    attributes={"outcome": DEAD, "reason": "DeadlineExceeded"},
                )
                # A deadline death is an availability breach the SLO engine
                # must see — it never passes through report().
                self._slo_observe_locked(job, now)
                self._m_dead.inc(op=job.op)
                self._m_deadline_dead.inc(op=job.op)
                self._m_sched_decisions.inc(
                    policy=self.sched_config.policy, decision="deadline_dead")
                self.recorder.record(
                    "dead", job_id=jid, op=job.op, reason="deadline",
                    deadline_sec=job.deadline_sec, attempts=job.attempts,
                )
                self._update_queue_stats_locked(now)
                # Journaled as a result record so replay keeps it dead.
                self._journal(
                    {
                        "ev": "result",
                        "job_id": jid,
                        "state": DEAD,
                        "epoch": job.epoch,
                        "attempts": job.attempts,
                        "result": None,
                        "error": job.error,
                    }
                )
                # A deadline death inside a DAG fails every downstream stage
                # (ISSUE 19) — after journaling the death itself, so replay
                # sees cause before effect.
                self._workflow_note_terminal_locked(job, now)
                self._cascade_dep_failure_locked(job, now)
            elif not job.escalated and age >= job.deadline_sec * frac:
                job.escalated = True
                if job.priority < PRIORITY_MAX:
                    job.priority += 1
                    self._sched.reprioritize(job)
                self._m_sched_decisions.inc(
                    policy=self.sched_config.policy, decision="escalated")
                self.recorder.record(
                    "deadline_escalated", job_id=jid, op=job.op,
                    priority=job.priority,
                )

    def _deps_done_locked(self, job: Job) -> bool:
        return all(
            self._jobs[d].state == SUCCEEDED
            for d in job.after
            if d in self._jobs
        )

    @staticmethod
    def _labels_match(job: Job, labels: Dict[str, Any]) -> bool:
        """Every required label must be present; a required value of True
        accepts any truthy advertisement (bare-token labels parse to True).

        Value comparison is string-coerced: the AGENT_LABELS env grammar only
        produces strings (or True), so a JSON-typed requirement like
        ``{"mem_gb": 16}`` must still match an agent advertising ``"16"`` —
        a strict type-sensitive compare would starve the job silently.
        Numeric requirements compare numerically first, so ``{"mem_gb": 16.0}``
        also matches ``"16"`` (str-coercing 16.0 to "16.0" would reintroduce
        exactly the silent starvation the coercion exists to prevent).
        """
        for key, want in job.required_labels.items():
            have = labels.get(key)
            if want is True:
                if not _truthy(have):  # absent, falsy, or "false"/"0"/...
                    return False
            elif have is None:
                return False
            elif isinstance(want, (int, float)) and not isinstance(want, bool):
                if isinstance(have, bool):
                    # A bare flag label (True) carries no value — it must not
                    # satisfy a numeric requirement via float(True) == 1.0.
                    return False
                try:
                    if float(have) != float(want):
                        return False
                except (TypeError, ValueError):
                    return False
            elif str(have) != str(want):
                return False
        return True

    def lease(
        self,
        agent: str,
        capabilities: Optional[Dict[str, Any]] = None,
        max_tasks: int = 1,
        worker_profile: Optional[Dict[str, Any]] = None,
        metrics: Optional[Dict[str, Any]] = None,
        labels: Optional[Dict[str, Any]] = None,
        draining: bool = False,
        **_ignored: Any,
    ) -> Optional[Dict[str, Any]]:
        # Serving flush (ISSUE 15): deadline-expired infer buckets become
        # leasable jobs BEFORE this poll's take, so the asking agent can
        # carry them now instead of a poll cycle later. Outside the state
        # lock by construction (the front door has its own).
        if self.serve_door is not None:
            self._serve_pump()
        try:
            return self._lease_impl(
                agent, capabilities=capabilities, max_tasks=max_tasks,
                worker_profile=worker_profile, metrics=metrics,
                labels=labels, draining=draining, **_ignored,
            )
        finally:
            # Trend-ring backstop (ISSUE 9): AFTER the lease, so the sample
            # sees the telemetry this very poll ingested (the metrics-only
            # drain-end flush is what carries the final counters). Rate-
            # limited to TSDB_INTERVAL — one clock read per lease between
            # samples — and outside the controller lock by construction.
            self._tsdb_sample()
            # Compaction backstop for sweeper-less drains (ISSUE 14): a
            # cheap counter check unless a snapshot is actually due. A
            # failing write must not fail the lease that triggered it.
            try:
                self.maybe_snapshot()
            except OSError as exc:
                log("snapshot failed (segments still replay)",
                    error=str(exc)[:200])

    def _lease_impl(
        self,
        agent: str,
        capabilities: Optional[Dict[str, Any]] = None,
        max_tasks: int = 1,
        worker_profile: Optional[Dict[str, Any]] = None,
        metrics: Optional[Dict[str, Any]] = None,
        labels: Optional[Dict[str, Any]] = None,
        draining: bool = False,
        **_ignored: Any,
    ) -> Optional[Dict[str, Any]]:
        """One lease request → ``{lease_id, tasks}`` or None (HTTP 204).

        ``draining=True`` (ISSUE 10) marks the agent as retiring in the
        per-agent view — ``/v1/status`` and ``/v1/health`` surface it, and
        the autoscaler stops counting the member as live capacity. The mark
        clears when the same agent name polls again without the flag (a
        fresh incarnation after a reclaim). Placement needs no change: a
        draining agent never asks for work, and the pull protocol is the
        fence.

        ``max_tasks < 1`` is a **metrics-only poll**: the agent's telemetry
        is recorded (per-agent snapshot, profile) but nothing leases — the
        flush channel drain loops use to push their final counters after the
        last task posts (old agents always send ≥ 1, so the wire contract
        is unchanged for them).

        Which jobs go out — and how many — is the scheduler's call
        (ISSUE 4): this method owns eligibility (state, not_before,
        capability ops, labels, dependencies) and the lease bookkeeping;
        ``self._sched.take`` owns order and placement, fed the enriched
        capability fields (``device_kind``, ``mesh_devices``,
        ``queue_depth``) agents now advertise.
        """
        caps = capabilities or {}
        ops = set(caps.get("ops") or [])
        labels = labels or {}
        # SLO alert piggyback (ISSUE 8 satellite): keep the judgment fresh
        # (rate-limited to ~1/s inside the tracker) and collect any paging
        # objectives BEFORE taking the controller lock — the page-entry hook
        # dumps the flight recorder (file I/O). Granted leases carry the
        # active page alerts so agents can auto-dump their own rings.
        page_alerts: List[Dict[str, Any]] = []
        if self.slo is not None:
            self.slo.maybe_evaluate()
            page_alerts = self.slo.active_alerts("page")
        # Binary-wire negotiation (ISSUE 6): both sides must opt in — the
        # agent by advertising, this controller by configuration. Old
        # agents never advertise, so they keep byte-identical JSON.
        adv = caps.get("wire_formats")
        wire_fmt = (
            wire.FORMAT
            if self.wire_binary and isinstance(adv, (list, tuple))
            and wire.FORMAT in adv
            else None
        )
        with self._lock:
            now_wall = time.time()
            if metrics:
                # Piggybacked agent spans (ISSUE 5): the lease `metrics`
                # channel doubles as the span ship — including the
                # metrics-only flush at drain end — keyed by agent id like
                # the obs snapshot, deduped by span_id at the store.
                piggyback = metrics.pop("spans", None) \
                    if isinstance(metrics, dict) else None
                if piggyback:
                    self.traces.ingest(piggyback)
                # Deep-capture completions ride the same channel (ISSUE 9):
                # popped so the stored per-agent snapshot stays clean.
                done_captures = metrics.pop("profile_captures", None) \
                    if isinstance(metrics, dict) else None
                for payload in done_captures or []:
                    self.captures.complete(payload)
                self.last_metrics = metrics
                if agent:
                    self.agent_metrics[agent] = {
                        "last_seen_wall": now_wall,
                        "metrics": {
                            k: v for k, v in metrics.items() if k != "obs"
                        },
                        "obs": metrics.get("obs"),
                    }
            elif agent and agent in self.agent_metrics:
                self.agent_metrics[agent]["last_seen_wall"] = now_wall
            if agent:
                # Drain handshake: sticky until a NON-draining poll from the
                # same name (a restarted incarnation) clears it.
                entry = self.agent_metrics.get(agent)
                if entry is not None:
                    entry["draining"] = bool(draining)
                elif draining:
                    self.agent_metrics[agent] = {
                        "last_seen_wall": now_wall,
                        "metrics": {},
                        "obs": None,
                        "draining": True,
                    }
                if draining:
                    self.recorder.record("agent_draining", agent=agent)
            if worker_profile:
                self.last_profile = worker_profile
                tpu = worker_profile.get("tpu") or {}
                if isinstance(tpu, dict) and tpu.get("suggested_shard_rows"):
                    self._last_tpu_profile = worker_profile
            self._expire_leases_locked()
            self._expire_deadlines_locked()
            if max_tasks < 1:
                self._m_lease.inc(outcome="metrics_only")
                return None
            plan = self._fault_plan
            if self._take_fault("drop_lease") or (
                plan is not None and plan.decide("drop_lease")
            ):
                self._m_lease.inc(outcome="fault_drop")
                self._m_faults.inc(fault="drop_lease")
                self.recorder.record("fault", fault="drop_lease", agent=agent)
                return None
            duplicate = self._take_fault("duplicate_task") or (
                plan is not None and plan.decide("duplicate_task")
            )
            stale = self._take_fault("stale_epoch") or (
                plan is not None and plan.decide("stale_epoch")
            )

            lease_id = f"lease-{self._id_tag}{uuid.uuid4().hex[:12]}"
            now = self._clock()
            deadline = now + self.lease_ttl_sec
            tasks: List[Dict[str, Any]] = []
            # Grant accounting: the historical loop bounded len(tasks) —
            # which included the duplicate_task copy — so an armed duplicate
            # consumes one distinct-job slot (unless only one slot exists).
            n = max(1, max_tasks)
            limit = max(1, n - 1) if duplicate else n

            def eligible(job: Job) -> bool:
                return (
                    job.state == PENDING
                    and job.not_before <= now
                    and (not ops or job.op in ops)
                    and self._labels_match(job, labels)
                    and self._deps_done_locked(job)
                )

            ctx = LeaseContext(
                agent=agent,
                now=now,
                limit=limit,
                requested=n,
                ops=frozenset(ops),
                labels=labels,
                device_kind=caps.get("device_kind"),
                mesh_devices=caps.get("mesh_devices"),
                queue_depth=caps.get("queue_depth"),
            )
            while True:
                cache_hits_round = 0
                for job in self._sched.take(ctx, eligible):
                    if (
                        self.result_cache is not None
                        and job.workflow_id is not None
                        and is_cacheable(job.op)
                    ):
                        # Lease-time result-cache consult (ISSUE 19): the
                        # first moment a dep-gated stage's REAL input exists
                        # — materialize its partials, then key on the full
                        # payload. A hit lands terminal here without ever
                        # reaching an agent (the job already left the queue
                        # via take()). Dep-free cacheable stages re-consult
                        # too: an identical job may have computed while this
                        # one sat queued. Workflow stages only — plain jobs
                        # keep the submitted == executed contract.
                        if job.payload.pop("__collect_partials__", None):
                            job.payload["partials"] = [
                                self._jobs[d].result
                                for d in job.after_order
                                if d in self._jobs
                            ]
                        cached = self.result_cache.get(job.op, job.payload)
                        if cached is not None:
                            self._finalize_cache_hit_locked(
                                job, cached, now, plane="lease"
                            )
                            cache_hits_round += 1
                            continue
                        self._m_result_cache.inc(event="miss")
                    job.state = LEASED
                    job.lease_id = lease_id
                    job.lease_deadline = deadline
                    job.agent = agent
                    job.attempts += 1
                    self._m_tasks_leased.inc(op=job.op)
                    self._m_sched_decisions.inc(
                        policy=self.sched_config.policy, decision="leased")
                    if job.attempts == 1:
                        # Queue-wait attribution: submit → FIRST lease only
                        # (a retry's wait measures failure handling, not
                        # scheduling pressure).
                        self._m_queue_wait.observe(
                            max(0.0, now - job.submitted_at),
                            exemplar={"trace_id": job.trace_root},
                            op=job.op,
                        )
                        self._m_starvation.observe(
                            max(0.0, now - job.submitted_at), tenant=job.tenant
                        )
                    if job.root_span_id is not None:
                        # The scheduling wait as a span: last-enqueued → this
                        # grant, annotated with the policy's deferral/held
                        # history so "why did this job sit" reads off the trace.
                        wait = max(0.0, now - job.enqueued_clock)
                        self.traces.add({
                            "trace_id": job.trace_root,
                            "span_id": obs_trace.new_span_id(),
                            "parent_span_id": job.root_span_id,
                            "name": "sched.decide",
                            "start_wall": time.time() - wait,
                            "start_mono": job.enqueued_clock,
                            "duration_ms": round(wait * 1e3, 3),
                            "process": "controller",
                            "attributes": {
                                "decision": "leased",
                                "policy": self.sched_config.policy,
                                "attempt": job.attempts,
                                "placement_defers": job.placement_defers,
                                "held": job.not_before > job.enqueued_clock,
                                "agent": agent,
                            },
                        })
                        # The lease window stays open until the result applies
                        # or the TTL expires; agent-side spans parent to it.
                        job.lease_span_id = self.traces.open(
                            job.trace_root, "lease",
                            parent_span_id=job.root_span_id, start_clock=now,
                            attributes={
                                "lease_id": lease_id, "agent": agent,
                                "epoch": job.epoch, "attempt": job.attempts,
                            },
                        )
                    self.recorder.record(
                        "lease", job_id=job.job_id, op=job.op,
                        lease_id=lease_id, agent=agent, epoch=job.epoch,
                        attempt=job.attempts,
                    )
                    if job.payload.pop("__collect_partials__", None):
                        # Reduce-time materialization: dependency results
                        # become the op's partials (kept out of the payload
                        # until every shard result actually exists), in
                        # submission order — shard order, for reduce ops
                        # that are order-sensitive.
                        job.payload["partials"] = [
                            self._jobs[d].result
                            for d in job.after_order
                            if d in self._jobs
                        ]
                    def out_task(j: Job = job) -> Dict[str, Any]:
                        task = j.to_task()
                        if wire_fmt and wire.encodable_task(j.op, j.payload):
                            # Bulk ``texts`` columns ship binary to a
                            # negotiated agent; the job's own payload (journal,
                            # replay, /v1/jobs) stays plain JSON.
                            task["payload"] = wire.encode_task_payload(j.payload)
                            self._m_wire.inc(direction="task", format=wire_fmt)
                        return task

                    tasks.append(out_task())
                    if duplicate:
                        # Same task handed out twice under one lease: the
                        # second completion must be idempotent/fenced.
                        tasks.append(out_task())
                        duplicate = False
                        self._m_faults.inc(fault="duplicate_task")
                        self.recorder.record(
                            "fault", fault="duplicate_task", job_id=job.job_id
                        )
                    if stale:
                        # Epoch bumps right after leasing → the agent's result
                        # arrives carrying the old epoch and is discarded.
                        job.epoch += 1
                        stale = False
                        self._m_faults.inc(fault="stale_epoch")
                        self.recorder.record(
                            "fault", fault="stale_epoch", job_id=job.job_id
                        )
                if tasks or not cache_hits_round:
                    break
                # Every job this scan took landed straight from the
                # result cache; their dependents may have just become
                # serviceable. Rescan instead of granting an idle
                # lease — bounded: each rescan only repeats if it
                # finalized at least one more job.
            self._update_queue_stats_locked(now)
            if not tasks:
                self._m_lease.inc(outcome="idle")
                return None
            self._m_lease.inc(outcome="granted")
            out = {"lease_id": lease_id, "tasks": tasks}
            # Pending deep-capture requests for THIS agent ride granted
            # leases only (ISSUE 9) — a capture wraps an op execution, so
            # delivering alongside tasks is the natural (and only) slot.
            alerts = page_alerts + self.captures.pending_for(agent)
            if alerts:
                # Only when something is paging or a capture is pending:
                # the wire stays byte-identical to the pre-health protocol
                # otherwise, and old agents ignore the extra key either way.
                out["alerts"] = alerts
            if wire_fmt:
                # The negotiation answer: the agent may now binary-encode
                # its result columns. Stamped on every negotiated grant so
                # agents self-correct against a reconfigured controller.
                out["wire"] = wire_fmt
            return out

    def report(
        self,
        lease_id: str,
        job_id: str,
        job_epoch: Any,
        status: str,
        result: Any = None,
        error: Any = None,
        spans: Any = None,
        wire_bytes: int = 0,
        **_ignored: Any,
    ) -> Dict[str, Any]:
        out = self._report_impl(
            lease_id, job_id, job_epoch, status, result=result, error=error,
            spans=spans, wire_bytes=wire_bytes, **_ignored,
        )
        # Serving fan-out (ISSUE 15): an accepted application may have
        # landed a serve batch job terminal — complete its riders now, not
        # on the next sweep. Outside the state lock (the reap re-takes it
        # briefly per job; lock order controller → front door, never both).
        if (
            self.serve_door is not None
            and out.get("accepted") and not out.get("released")
        ):
            self._serve_reap()
        return out

    def _report_impl(
        self,
        lease_id: str,
        job_id: str,
        job_epoch: Any,
        status: str,
        result: Any = None,
        error: Any = None,
        spans: Any = None,
        wire_bytes: int = 0,
        **_ignored: Any,
    ) -> Dict[str, Any]:
        """One result post. Stale epochs are counted and discarded.

        ``spans`` is the agent's piggybacked span batch (ISSUE 5) — ingested
        regardless of whether the result is accepted (a fenced result's
        execution still happened and belongs on the timeline).

        ``wire_bytes`` is the HTTP layer's measured request size (ISSUE 9):
        the exact per-task result-wire attribution the usage ledger bills —
        0 for in-process sessions, which simply have no wire."""
        if spans:
            self.traces.ingest(spans)
        if wire.is_binary_result(result):
            # Binary shard wire (ISSUE 6): decode OUTSIDE the lock (zlib +
            # numpy work) so the hot path holds it no longer than a JSON
            # result would. The stored result is exactly what a JSON-wire
            # agent would have posted — downstream consumers (journal
            # partials, /v1/jobs, reduce stages) never see the envelope.
            try:
                result = wire.decode_result(result)
                self._m_wire.inc(direction="result", format=wire.FORMAT)
            except ValueError as exc:
                # Undecodable envelope: keep the raw body (debuggable, not
                # silently dropped) and make the corruption visible.
                self._m_wire.inc(
                    direction="result_error", format=wire.FORMAT
                )
                log("binary result envelope undecodable", job_id=job_id,
                    error=str(exc)[:200])
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                self._m_results.inc(op="?", outcome="unknown_job")
                self.recorder.record(
                    "result_rejected", job_id=job_id, reason="unknown job",
                    lease_id=lease_id,
                )
                return {"accepted": False, "reason": "unknown job"}
            if job_epoch != job.epoch:
                # The epoch fence doing its job — a real counter now
                # (``controller_results_total{outcome="stale_epoch"}``), not
                # just the legacy attribute.
                self.stale_results += 1
                self._m_results.inc(op=job.op, outcome="stale_epoch")
                self.recorder.record(
                    "epoch_fence", job_id=job_id, op=job.op,
                    posted_epoch=job_epoch, current_epoch=job.epoch,
                    lease_id=lease_id, attempt=job.attempts,
                )
                return {"accepted": False, "reason": "stale epoch"}
            if job.state in TERMINAL_STATES:
                # Duplicate completion (duplicate_task fault, a result
                # redelivered after its response was lost): first wins —
                # terminal states never move, and nothing re-applies.
                self._m_results.inc(op=job.op, outcome="duplicate")
                self.recorder.record(
                    "result_rejected", job_id=job_id, op=job.op,
                    reason="already complete", lease_id=lease_id,
                )
                return {"accepted": False, "reason": "already complete"}
            if status == "released":
                # Drain handback (ISSUE 10): a retiring agent returns an
                # unstarted leased task. Requeue NOW (no TTL wait), bump the
                # epoch (any late duplicate of this lease is fenced), and
                # give the attempt back — a release is not a failure and
                # must not eat the retry budget. The epoch check above
                # already proved this lease still owns the job.
                if job.state != LEASED:
                    self._m_results.inc(op=job.op, outcome="duplicate")
                    self.recorder.record(
                        "result_rejected", job_id=job_id, op=job.op,
                        reason="release of unleased job", lease_id=lease_id,
                    )
                    return {"accepted": False, "reason": "not leased"}
                now = self._clock()
                job.epoch += 1
                job.state = PENDING
                job.lease_id = None
                job.attempts = max(0, job.attempts - 1)
                job.not_before = now
                job.enqueued_clock = now
                self.traces.finish(
                    job.trace_root, job.lease_span_id, now,
                    attributes={"outcome": "released"},
                )
                job.lease_span_id = None
                self._sched.add(job)
                self._m_results.inc(op=job.op, outcome="released")
                self.recorder.record(
                    "released", job_id=job_id, op=job.op, epoch=job.epoch,
                    lease_id=lease_id, agent=job.agent,
                )
                self._update_queue_stats_locked(now)
                # Journaled like an expiry requeue: replay must keep the
                # fence or a post-restart duplicate could apply.
                self._journal(
                    {"ev": "requeue", "job_id": job_id, "epoch": job.epoch}
                )
                return {"accepted": True, "released": True}
            # result/error before state: unlocked readers keying on a
            # terminal state must never see it paired with a stale result.
            t_apply = self._clock()
            job.result = result
            job.error = error
            job.state = SUCCEEDED if status == "succeeded" else FAILED
            job.lease_id = lease_id
            self._m_results.inc(
                op=job.op,
                outcome="succeeded" if job.state == SUCCEEDED else "failed",
            )
            self.recorder.record(
                "result", job_id=job_id, op=job.op, state=job.state,
                epoch=job.epoch, attempt=job.attempts, lease_id=lease_id,
                error_type=(error or {}).get("type")
                if isinstance(error, dict) else None,
            )
            if job.state == FAILED:
                # Classified retry policy (ISSUE 3): a permanent error
                # (UnknownOp, malformed payload — re-running cannot fix it)
                # sticks `failed` immediately without burning retries; a
                # transient one re-queues until the attempt budget is spent,
                # then the job lands terminal `dead`. Retried jobs carry a
                # requeue delay so a crashing op can't hot-loop the queue.
                budget = job.max_attempts or self.max_attempts
                if classify_error(error) == PERMANENT:
                    self.recorder.record(
                        "permanent_error", job_id=job_id, op=job.op,
                        error_type=(error or {}).get("type")
                        if isinstance(error, dict) else None,
                    )
                elif job.attempts < budget:
                    job.state = PENDING
                    job.epoch += 1
                    job.not_before = self._clock() + self.requeue_delay_sec
                    self._sched.add(job)
                    if self.requeue_delay_sec > 0:
                        # Feeds the held/leasable split of the depth gauge.
                        self._delayed.add(job.job_id)
                    self._m_retries.inc(op=job.op)
                    self._update_queue_stats_locked()
                    self.recorder.record(
                        "retry", job_id=job_id, op=job.op, epoch=job.epoch,
                        attempt=job.attempts, budget=budget,
                    )
                else:
                    job.state = DEAD
                    self._m_dead.inc(op=job.op)
                    self.recorder.record(
                        "dead", job_id=job_id, op=job.op,
                        attempts=job.attempts, budget=budget,
                    )
            now = self._clock()
            self.traces.finish(
                job.trace_root, job.lease_span_id, now,
                attributes={"outcome": job.state},
            )
            job.lease_span_id = None
            if job.root_span_id is not None:
                # The controller-side application itself (state transition +
                # retry classification + journal ordering), closing the
                # submit→…→apply chain.
                self.traces.add({
                    "trace_id": job.trace_root,
                    "span_id": obs_trace.new_span_id(),
                    "parent_span_id": job.root_span_id,
                    "name": "apply",
                    "start_wall": time.time() - max(0.0, now - t_apply),
                    "start_mono": t_apply,
                    "duration_ms": round(max(0.0, now - t_apply) * 1e3, 3),
                    "process": "controller",
                    "attributes": {
                        "outcome": job.state, "attempt": job.attempts,
                    },
                })
            if job.state in TERMINAL_STATES:
                self.traces.finish(
                    job.trace_root, job.root_span_id, now,
                    attributes={"outcome": job.state},
                )
                # SLO feed (ISSUE 8): one observation per job, at terminal
                # apply — the submit→apply span, the latency a submitter
                # actually experienced (retries included).
                self._slo_observe_locked(job, now)
            else:
                # Transient-failure requeue: the next sched.decide span
                # measures its wait from here.
                job.enqueued_clock = now
            # Showback billing (ISSUE 9): every ACCEPTED application bills
            # once — fenced/duplicate posts already returned above, and the
            # ledger's (job, attempt) dedupe makes double-billing
            # structurally impossible even across replay + live overlap.
            billed_usage = None
            if self.usage is not None:
                billed_usage = self.usage.bill(
                    job.job_id, tenant=job.tenant, tier=job.priority,
                    op=job.op, attempt=job.attempts,
                    usage=result.get("usage")
                    if isinstance(result, dict) else None,
                    wire_bytes=wire_bytes,
                )
            # Journal the post-decision state (not the raw report): replay
            # applies it verbatim, so a failed-then-requeued job replays as
            # pending at the bumped epoch and a completed shard stays done.
            # Result bodies are journaled only for depended-on jobs (a reduce
            # will need them as partials after a restart) — journaling every
            # drain shard's output would make the journal an unbounded second
            # copy of the dataset. Workflow members (ISSUE 19) keep theirs
            # too: a DAG's sink result is the workflow's deliverable and must
            # replay bit-identically; stage width is bounded by
            # FLOW_MAX_WIDTH, so the journal stays bounded.
            record = {
                "ev": "result",
                "job_id": job.job_id,
                "state": job.state,
                "epoch": job.epoch,
                "attempts": job.attempts,
                "result": (
                    job.result
                    if (
                        job.job_id in self._depended_on
                        or job.workflow_id is not None
                    )
                    else None
                ),
                "error": job.error,
            }
            if billed_usage is not None:
                # Appended only when billed (journal schema vN+1 rule):
                # usage-less drains keep writing the exact legacy bytes.
                record["usage"] = billed_usage
            self._journal(record)
            if (
                job.state == SUCCEEDED
                and self.result_cache is not None
                and is_cacheable(job.op)
                and isinstance(job.result, dict)
            ):
                # Content-addressed memoization (ISSUE 19): the key covers
                # the payload AS EXECUTED — for a reduce that includes the
                # materialized partials, so an identical fan-in replays from
                # cache only when every upstream byte matched too.
                self.result_cache.put(job.op, job.payload, job.result)
                self._m_result_cache.inc(event="put")
            if job.state in TERMINAL_STATES:
                # Workflow bookkeeping + downstream cascade AFTER this job's
                # own journal record: replay must see the upstream terminal
                # before any DependencyFailed deaths it caused.
                self._workflow_note_terminal_locked(job, now)
                if job.state in (FAILED, DEAD):
                    self._cascade_dep_failure_locked(job, now)
            return {"accepted": True}

    # ---- online serving front door (ISSUE 15) ----

    def _require_serve(self) -> ServeFrontDoor:
        if self.serve_door is None:
            raise RuntimeError("serving is disabled (SERVE_ENABLED=0)")
        return self.serve_door

    def submit_infer(
        self,
        op: str,
        text: Any,
        params: Optional[Dict[str, Any]] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> str:
        """One ``POST /v1/infer`` request → req_id. The request joins a
        length-bucketed coalescing bucket; a bucket that fills flushes to
        the job queue immediately, a partial one flushes on the lease/sweep
        cadence once its oldest rider waited ``SERVE_MAX_WAIT_MS``. Raises
        ``ValueError`` (HTTP 400) / ``AdmissionError`` (HTTP 429)."""
        door = self._require_serve()
        if self.result_cache is not None and isinstance(text, str) and text:
            # Front-door memoization (ISSUE 19): consulted BEFORE bucketing
            # (and before admission — a hit costs no pending-budget slot).
            # Keys cover op+text+params, not tenant: dedupe is global, the
            # usage ledger attributes the hit to the asking tenant.
            cached = self.result_cache.get(
                f"infer:{op}", {"text": text, "params": dict(params or {})}
            )
            if cached is not None:
                req = door.complete_cached(
                    op, text, cached, params=params, tenant=tenant,
                    priority=priority,
                )
                self._m_result_cache.inc(event="hit_infer")
                self._m_serve_requests.inc(op=req.op, outcome="accepted")
                self.recorder.record(
                    "serve_request", req_id=req.req_id, op=req.op,
                    tenant=req.tenant, cache_hit=True,
                )
                self.recorder.record(
                    "cache_hit", req_id=req.req_id, op=req.op, plane="infer",
                )
                if self.usage is not None:
                    self.usage.bill(
                        req.req_id, tenant=req.tenant, tier=req.priority,
                        op=SERVE_OPS[req.op], attempt=1,
                        usage={"result_cache_hits": 1},
                    )
                self._note_serve_completions([req])
                return req.req_id
            self._m_result_cache.inc(event="miss")
        try:
            req, full = door.submit(
                op, text, params=params, tenant=tenant, priority=priority,
            )
        except AdmissionError:
            self._m_serve_requests.inc(op=str(op), outcome="rejected")
            self.recorder.record(
                "serve_rejected", op=str(op), tenant=tenant,
            )
            raise
        self._m_serve_requests.inc(op=req.op, outcome="accepted")
        self.recorder.record(
            "serve_request", req_id=req.req_id, op=req.op, tenant=req.tenant,
        )
        self._submit_serve_batches(full)
        return req.req_id

    def _submit_serve_batches(self, batches: List[ServeBatch]) -> None:
        """Flushed buckets → ordinary jobs on the existing queue (priority
        tier, tenant, journal, fencing all inherited). A job-queue admission
        refusal fails the riders visibly rather than re-queueing them —
        backpressure at the front door is the 429 the submitter already got;
        a full JOB queue behind it means the system is saturated."""
        door = self.serve_door
        if door is None:
            return
        for batch in batches:
            op = SERVE_OPS[batch.key.op]
            # Disaggregated pools (ISSUE 16): the decode path splits into a
            # serve_prefill job and a dep-gated serve_decode job, so the two
            # phases can land on SEPARATE fleets (capability routing + the
            # fair scheduler's steer). The prefill result's encoded rows
            # ride the ordinary results wire into the decode job's
            # ``partials`` — the controller's dep-gating queue IS the
            # KV-handoff transport, no new endpoints.
            disagg = (
                self.serve_config.disaggregated and op == "serve_summarize"
            )
            job_id = f"serve-{uuid.uuid4().hex[:12]}"
            pf_id: Optional[str] = None
            try:
                if disagg:
                    pf_id = f"serve-pf-{uuid.uuid4().hex[:12]}"
                    self.submit(
                        "serve_prefill",
                        batch.job_payload(),
                        job_id=pf_id,
                        priority=batch.key.priority,
                        tenant=batch.key.tenant,
                    )
                    # If THIS submit 429s the prefill job above is already
                    # queued and runs as an orphan — its result simply never
                    # fans out. Acceptable: admission refusal here means the
                    # system is saturated and the riders fail visibly below.
                    payload = batch.job_payload()
                    payload["__collect_partials__"] = True
                    self.submit(
                        "serve_decode",
                        payload,
                        job_id=job_id,
                        after=[pf_id],
                        priority=batch.key.priority,
                        tenant=batch.key.tenant,
                    )
                else:
                    self.submit(
                        op,
                        batch.job_payload(),
                        job_id=job_id,
                        priority=batch.key.priority,
                        tenant=batch.key.tenant,
                    )
            except AdmissionError as exc:
                completed = door.fail_batch(batch, {
                    "type": "AdmissionError",
                    "message": str(exc),
                })
                self._note_serve_completions(completed)
            else:
                door.mark_batched(batch, job_id, prefill_job_id=pf_id)
                self._m_serve_batches.inc(
                    op=batch.key.op, reason=batch.reason
                )
                self.recorder.record(
                    "serve_batch", job_id=job_id, op=batch.key.op,
                    n_requests=len(batch.requests), reason=batch.reason,
                )
                self._link_serve_batch(batch, job_id, pf_id)

    def _link_serve_batch(
        self,
        batch: ServeBatch,
        job_id: str,
        prefill_job_id: Optional[str],
    ) -> None:
        """Cross-trace stitching for one flushed batch (ISSUE 17): the
        batch job's root span gains one link per rider request, each rider's
        request trace links back to the job(s) it rides — so GET
        /v1/trace/{req_id} can inline the shared batch timeline and a job
        trace names every request it carried."""
        with self._lock:
            job_ids = [j for j in (job_id, prefill_job_id) if j]
            roots = {
                j: self._jobs[j].root_span_id
                for j in job_ids if j in self._jobs
            }
        for jid in job_ids:
            self.traces.add_links(jid, roots.get(jid), [
                obs_trace.span_link(
                    r.req_id, r.root_span_id, kind="serve_request"
                )
                for r in batch.requests
            ])
        kinds = {job_id: "serve_batch_job", prefill_job_id: "serve_prefill_job"}
        for r in batch.requests:
            self.traces.add_links(r.req_id, r.root_span_id, [
                obs_trace.span_link(jid, roots.get(jid), kind=kinds[jid])
                for jid in job_ids
            ])

    def _serve_pump(self) -> None:
        """Deadline-flush due buckets and reap terminal serve jobs — driven
        by the lease path, the sweeper, and the HTTP wait loops, so the
        front door makes progress under any one of them."""
        door = self.serve_door
        if door is None:
            return
        self._submit_serve_batches(door.pop_due())
        self._serve_reap()

    def _serve_reap(self) -> None:
        """Fan terminal serve jobs' results out to their riding requests.
        The catch-all completion path: covers result application, retry
        exhaustion, deadline death, and replayed jobs alike."""
        door = self.serve_door
        if door is None:
            return
        for job_id in door.job_ids():
            with self._lock:
                job = self._jobs.get(job_id)
                if job is None:
                    state: Optional[str] = DEAD
                    ok, result, error = False, None, {
                        "type": "UnknownJob",
                        "message": "serve batch job vanished",
                    }
                elif job.state not in TERMINAL_STATES:
                    # Disaggregated-chain cascade (ISSUE 16): dep gating
                    # only ever RELEASES on success, so a serve_decode job
                    # whose prefill dependency died would sit queued
                    # forever with its riders' HTTP waits open. Fail it
                    # now, the deadline-death way.
                    dead_dep = next(
                        (
                            d for d in job.after
                            if d in self._jobs
                            and self._jobs[d].state in (FAILED, DEAD)
                        ),
                        None,
                    ) if job.state == PENDING and job.after else None
                    if dead_dep is None:
                        continue
                    # Catch-all: the generalized cascade (ISSUE 19) fires at
                    # the upstream's terminal apply, but a reap can still race
                    # ahead of it (replayed journals from before the cascade
                    # existed, or a dep that died under a code path without
                    # the hook). Drive the same cascade from the dead
                    # upstream so the kill is identical either way.
                    now = self._clock()
                    self._cascade_dep_failure_locked(
                        self._jobs[dead_dep], now
                    )
                    if job.state not in TERMINAL_STATES:
                        continue
                    ok = job.state == SUCCEEDED
                    result, error = job.result, job.error
                else:
                    ok = job.state == SUCCEEDED
                    result, error = job.result, job.error
            completed = door.complete_job(
                job_id, ok, result=result, error=error
            )
            if completed:
                if ok and isinstance(result, dict):
                    occ = result.get("occupancy")
                    if isinstance(occ, (int, float)):
                        self._m_serve_occupancy.set(float(occ))
                    # Prefix-cache / paged-KV telemetry (ISSUE 16): the
                    # result carries per-batch deltas (disagg decode jobs
                    # forward the prefill agent's counters).
                    pc = result.get("prefix_cache")
                    if isinstance(pc, dict):
                        for event in ("hits", "misses", "evictions"):
                            n = pc.get(event)
                            if isinstance(n, (int, float)) and n > 0:
                                self._m_serve_prefix.inc(int(n), event=event)
                    kv_total = result.get("kv_blocks_total")
                    if isinstance(kv_total, (int, float)) and kv_total > 0:
                        self._m_serve_kv_total.set(float(kv_total))
                        kv_free = result.get("kv_blocks_free")
                        if isinstance(kv_free, (int, float)):
                            self._m_serve_kv_free.set(float(kv_free))
                self._note_serve_completions(completed)

    # Wall-clock checkpoint chain of one request's road to its first token.
    # Consecutive checkpoints bound one component, so the components
    # TELESCOPE: their sum is first_token − arrival = the measured TTFT
    # (modulo per-component clamping of cross-host clock skew to >= 0).
    _TTFT_CHAIN = (
        ("bucket_wait", "arrived_wall", "batched_wall"),
        ("queue_wait", "batched_wall", "prefill_t0_wall"),
        ("prefill", "prefill_t0_wall", "prefill_t1_wall"),
        ("handoff", "prefill_t1_wall", "admitted_wall"),
        ("kv_wait", "admitted_wall", "joined_wall"),
        ("first_decode", "joined_wall", "first_token_wall"),
    )

    def _ttft_components(self, req: Any) -> Dict[str, float]:
        """Per-request TTFT decomposition in ms, from the lifecycle walls
        the engine/op stamped (``req.telemetry``) plus the front door's own
        arrival/flush walls. Components with a missing endpoint (failed
        before reaching it) are simply absent."""
        walls: Dict[str, Any] = dict(req.telemetry or {})
        walls["arrived_wall"] = req.arrived_wall
        walls["batched_wall"] = req.batched_wall
        out: Dict[str, float] = {}
        for name, k0, k1 in self._TTFT_CHAIN:
            w0, w1 = walls.get(k0), walls.get(k1)
            if isinstance(w0, (int, float)) and isinstance(w1, (int, float)):
                out[name] = round(max(0.0, (w1 - w0)) * 1e3, 3)
        return out

    def _synthesize_request_spans(
        self,
        req: Any,
        outcome: str,
        components: Dict[str, float],
        tel: Dict[str, Any],
    ) -> None:
        """Close out the request trace (ISSUE 17): one child span per TTFT
        component plus a ``decode`` span for the post-first-token stream,
        then finish the ``infer`` root — so GET /v1/trace/{req_id} assembles
        a complete, gap-free tree on its own (links stitch in the batch
        job's timeline separately)."""
        if req.root_span_id is None:
            return
        walls: Dict[str, Any] = dict(tel)
        walls["arrived_wall"] = req.arrived_wall
        walls["batched_wall"] = req.batched_wall
        for name, k0, _k1 in self._TTFT_CHAIN:
            ms = components.get(name)
            w0 = walls.get(k0)
            if ms is None or not isinstance(w0, (int, float)):
                continue
            attrs: Dict[str, Any] = {"component": name}
            if name == "kv_wait":
                # The seat delta; the pure KV-block stall inside it is the
                # engine's own measurement.
                attrs["kv_blocked_ms"] = tel.get("kv_wait_ms")
                attrs["occupancy_at_join"] = tel.get("occupancy_at_join")
            if name == "bucket_wait":
                attrs["flush_reason"] = req.flush_reason
                attrs["bucket"] = req.bucket
            self.traces.add({
                "trace_id": req.req_id,
                "span_id": obs_trace.new_span_id(),
                "parent_span_id": req.root_span_id,
                "name": f"ttft.{name}",
                "start_wall": float(w0),
                "start_mono": float(w0),
                "duration_ms": ms,
                "process": "controller",
                "attributes": attrs,
            })
        first = tel.get("first_token_wall")
        done = tel.get("done_wall")
        if isinstance(first, (int, float)) and isinstance(done, (int, float)):
            self.traces.add({
                "trace_id": req.req_id,
                "span_id": obs_trace.new_span_id(),
                "parent_span_id": req.root_span_id,
                "name": "decode",
                "start_wall": float(first),
                "start_mono": float(first),
                "duration_ms": round(max(0.0, done - first) * 1e3, 3),
                "process": "controller",
                "attributes": {
                    "steps": tel.get("steps"), "tokens": req.tokens,
                },
            })
        self.traces.finish(
            req.req_id, req.root_span_id, self._clock(),
            attributes={
                "outcome": outcome,
                "job_id": req.job_id,
                "prefill_job_id": req.prefill_job_id,
                "path": tel.get("path"),
            },
        )

    def _note_serve_completions(self, completed: List[Any]) -> None:
        """Terminal-request bookkeeping: metrics + SLO feed (latency into
        the default objectives, TTFT into the ``metric: "ttft"`` ones), the
        TTFT component decomposition (histograms + synthesized request-trace
        spans), and the wide-event request log (ISSUE 17)."""
        now = self._clock()
        for req in completed:
            ok = req.state == SERVE_DONE
            outcome = "completed" if ok else "failed"
            if not ok and isinstance(req.error, dict) \
                    and req.error.get("type") == "DependencyFailed":
                # The disagg cascade: decode riders killed by a dead
                # prefill dependency are their own failure class.
                outcome = "dep_failed"
            self._m_serve_requests.inc(
                op=req.op, outcome="completed" if ok else "failed"
            )
            if req.latency_ms is not None:
                self._m_serve_latency.observe(
                    req.latency_ms / 1e3, op=req.op
                )
            if req.ttft_ms is not None:
                self._m_serve_ttft.observe(req.ttft_ms / 1e3, op=req.op)
            if req.tokens:
                self._m_serve_tokens.inc(req.tokens, op=req.op)
            if ok and req.job_id is not None \
                    and self.result_cache is not None \
                    and isinstance(req.result, dict):
                # Populate the front-door cache from computed riders only
                # (job_id None = this completion WAS a cache hit). The key
                # re-includes max_length: it shaped the answer.
                req_params = dict(req.params)
                if req.max_length is not None:
                    req_params["max_length"] = req.max_length
                self.result_cache.put(
                    f"infer:{req.op}",
                    {"text": req.text, "params": req_params},
                    req.result,
                )
                self._m_result_cache.inc(event="put")
            tel: Dict[str, Any] = (
                req.telemetry if isinstance(req.telemetry, dict) else {}
            )
            components = self._ttft_components(req)
            for name, ms in components.items():
                self._m_serve_ttft_component.observe(
                    ms / 1e3, component=name
                )
            tpot_ms: Optional[float] = None
            steps = tel.get("steps")
            first = tel.get("first_token_wall")
            done = tel.get("done_wall")
            if isinstance(steps, int) and steps >= 2 \
                    and isinstance(first, (int, float)) \
                    and isinstance(done, (int, float)):
                tpot_ms = round(
                    max(0.0, done - first) * 1e3 / (steps - 1), 3
                )
                self._m_serve_tpot.observe(tpot_ms / 1e3, op=req.op)
            self._synthesize_request_spans(req, outcome, components, tel)
            if self.reqlog is not None:
                self.reqlog.add({
                    "req_id": req.req_id,
                    "tenant": req.tenant,
                    "op": req.op,
                    "bucket": req.bucket,
                    "priority": req.priority,
                    "outcome": outcome,
                    "path": tel.get("path") or (
                        "disagg" if req.prefill_job_id else "colocated"
                    ),
                    "ttft_ms": req.ttft_ms,
                    "tpot_ms": tpot_ms,
                    "latency_ms": req.latency_ms,
                    "tokens": req.tokens,
                    "steps": steps,
                    "prefix_hit": bool(tel.get("cache_hit")),
                    "kv_wait_ms": components.get("kv_wait"),
                    "kv_blocked_ms": tel.get("kv_wait_ms"),
                    "occupancy": tel.get("occupancy_at_join"),
                    "flush_reason": req.flush_reason,
                    "components": components,
                    "dominant_component": dominant_component(components),
                    "trace_id": req.req_id,
                    "job_id": req.job_id,
                    "prefill_job_id": req.prefill_job_id,
                    "error": (
                        req.error.get("type")
                        if isinstance(req.error, dict) else None
                    ),
                })
            self.recorder.record(
                "serve_done", req_id=req.req_id, op=req.op,
                outcome=outcome,
                ttft_ms=req.ttft_ms, latency_ms=req.latency_ms,
            )
            if self.slo is not None and req.latency_ms is not None:
                self.slo.observe(
                    req.latency_ms / 1e3, ok=ok, tier=req.priority,
                    tenant=req.tenant, op=f"infer_{req.op}", now=now,
                )
                if req.ttft_ms is not None:
                    self.slo.observe(
                        req.ttft_ms / 1e3, ok=ok, tier=req.priority,
                        tenant=req.tenant, op=f"infer_{req.op}", now=now,
                        metric="ttft",
                    )

    def infer_snapshot(self, req_id: str) -> Optional[Dict[str, Any]]:
        door = self.serve_door
        return door.snapshot(req_id) if door is not None else None

    def wait_infer(
        self, req_id: str, timeout_sec: float
    ) -> Optional[Dict[str, Any]]:
        """Long-poll one request to a terminal state (or timeout). The wait
        loop itself pumps the front door, so pure-HTTP traffic (no sweeper,
        no polling agent yet) still deadline-flushes its buckets."""
        door = self._require_serve()
        slice_sec = max(0.005, self.serve_config.max_wait_ms / 2e3)
        deadline = time.monotonic() + max(0.0, timeout_sec)
        while True:
            self._serve_pump()
            remaining = deadline - time.monotonic()
            snap = door.wait(req_id, min(max(remaining, 0.0), slice_sec))
            if snap is None or snap["state"] in ("done", "failed") \
                    or remaining <= 0:
                return snap

    def wait_infer_change(
        self, req_id: str, last_state: str, timeout_sec: float
    ) -> Optional[Dict[str, Any]]:
        """Block until the request's state moves past ``last_state`` (or
        timeout) — the chunked-streaming event source. Pumps like
        :meth:`wait_infer`."""
        door = self._require_serve()
        slice_sec = max(0.005, self.serve_config.max_wait_ms / 2e3)
        deadline = time.monotonic() + max(0.0, timeout_sec)
        while True:
            self._serve_pump()
            remaining = deadline - time.monotonic()
            snap = door.wait_change(
                req_id, last_state, min(max(remaining, 0.0), slice_sec)
            )
            if snap is None or snap["state"] != last_state or remaining <= 0:
                return snap

    def serve_status(self) -> Dict[str, Any]:
        """The ``/v1/status`` serving block (one schema enabled or not)."""
        out: Dict[str, Any] = {"enabled": self.serve_door is not None}
        if self.serve_door is not None:
            out.update(self.serve_door.stats())
        if self.reqlog is not None:
            out["request_log"] = self.reqlog.stats()
        return out

    def requests_json(
        self,
        tenant: Optional[str] = None,
        outcome: Optional[str] = None,
        slow: bool = False,
        limit: int = 256,
    ) -> Dict[str, Any]:
        """The ``GET /v1/debug/requests`` body: newest-first wide-event
        request records (tail-sampled) plus the log's keep/drop counters."""
        if self.reqlog is None:
            return {"enabled": False, "requests": []}
        return {
            "enabled": True,
            "requests": self.reqlog.snapshot(
                tenant=tenant, outcome=outcome, slow=slow, limit=limit
            ),
            "stats": self.reqlog.stats(),
        }

    def note_http_bytes(self, route: str, direction: str, n: int) -> None:
        """Raw data-plane byte accounting, fed by the HTTP layer (request
        Content-Length in, response body bytes out) — what bench's
        ``drain_binary_wire`` leg derives wire bytes/row from."""
        if n > 0:
            self._m_http_bytes.inc(int(n), route=route, direction=direction)

    # ---- introspection (for tests, bench, and a future status endpoint) ----

    def job(self, job_id: str) -> Job:
        with self._lock:
            return self._jobs[job_id]

    def job_snapshot(self, job_id: str) -> Dict[str, Any]:
        """Consistent read of a job's public fields (all under one lock —
        a field-by-field read could observe state='succeeded' before the
        result assignment lands). The HTTP GET surface uses this."""
        with self._lock:
            job = self._jobs[job_id]
            return {
                "job_id": job.job_id,
                "op": job.op,
                "state": job.state,
                "job_epoch": job.epoch,
                "attempts": job.attempts,
                "agent": job.agent,
                "result": job.result,
                "error": job.error,
                "priority": job.priority,
                "tenant": job.tenant,
                "deadline_sec": job.deadline_sec,
            }

    def counts(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for job in self._jobs.values():
                out[job.state] = out.get(job.state, 0) + 1
            return out

    def leased_to(self, agent: str) -> List[str]:
        """Job ids currently leased to ``agent`` — the scale-down
        stranded-lease probe (ISSUE 10): the moment a graceful retirement
        completes this must be empty, because the drain finished the
        in-flight task and released the rest instead of abandoning them to
        the TTL."""
        with self._lock:
            return [
                j.job_id for j in self._jobs.values()
                if j.state == LEASED and j.agent == agent
            ]

    def drained(self) -> bool:
        with self._lock:
            return all(
                j.state in TERMINAL_STATES for j in self._jobs.values()
            )

    def results(self) -> Dict[str, Any]:
        with self._lock:
            return {
                j.job_id: j.result
                for j in self._jobs.values()
                if j.state == SUCCEEDED
            }

    # ---- observability surface (GET /v1/metrics, /v1/status) ----

    def counts_by_op(self) -> Dict[str, Dict[str, int]]:
        """``{op: {state: n}}`` — the per-op breakdown /v1/status exposes."""
        with self._lock:
            out: Dict[str, Dict[str, int]] = {}
            for job in self._jobs.values():
                per = out.setdefault(job.op, {})
                per[job.state] = per.get(job.state, 0) + 1
            return out

    def queue_depth(self) -> int:
        with self._lock:
            return self._sched.total()

    def leasable_depth(self) -> int:
        """Pending jobs an agent could lease RIGHT NOW — the number the
        cross-partition steal probe reads off ``GET /v1/depth``
        (ISSUE 18). Computed from job state, NOT the scheduler heap: the
        heap deletes lazily, and a stale entry (a job completed via a
        redelivered result while also requeued) would advertise phantom
        depth — a steal victim with nothing to grant that can shadow a
        partition with REAL work behind the min-advantage filter,
        starving that job for as long as the phantom persists. O(jobs),
        which the router's depth cache amortizes."""
        with self._lock:
            now = self._clock()
            n = 0
            for job in self._jobs.values():
                if job.state != PENDING:
                    continue
                if job.not_before > now:
                    continue
                if job.after and not self._deps_done_locked(job):
                    continue
                n += 1
            return n

    def agents_summary(self) -> Dict[str, Any]:
        """Per-agent liveness: seconds since the last lease poll plus the
        light host/device telemetry it pushed (sans the obs snapshot — that
        feeds /v1/metrics, not status JSON)."""
        now = time.time()
        with self._lock:
            entries = {
                a: (
                    e.get("last_seen_wall", 0.0),
                    bool(e.get("draining")),
                    e.get("metrics") or {},
                )
                for a, e in self.agent_metrics.items()
            }
        return {
            a: {
                "last_seen_sec_ago": round(max(0.0, now - seen), 3),
                "draining": drain,
                "metrics": m,
            }
            for a, (seen, drain, m) in entries.items()
        }

    def fleet_snapshot(self) -> Dict[str, Any]:
        """Per-agent obs snapshots summed into fleet totals."""
        with self._lock:
            snaps = [
                e.get("obs") for e in self.agent_metrics.values()
                if isinstance(e.get("obs"), dict)
            ]
        return merge_snapshots(snaps)

    # Counter families that join the gauges in the per-agent view: load/
    # utilization series whose per-agent split is the whole point of a
    # fleet drain's attribution (ISSUE 7 satellite).
    _PER_AGENT_COUNTERS = (
        "device_busy_seconds_total",
        "device_idle_seconds_total",
    )

    def _per_agent_view(self, snap: Dict[str, Any]) -> Dict[str, Any]:
        """The families of one agent's snapshot that also render PER AGENT
        (stamped with an ``agent`` label): every gauge — summing two agents'
        ``queue_depth`` into one fleet series collapses exactly the signal a
        fleet operator needs — plus the device busy/idle counters."""
        return {
            name: fam for name, fam in snap.items()
            if isinstance(fam, dict) and (
                fam.get("type") == "gauge"
                or name in self._PER_AGENT_COUNTERS
            )
        }

    def metrics_text(self) -> str:
        """The full Prometheus exposition: controller series, fleet-merged
        agent series, and a synthetic per-agent liveness gauge. Agent metric
        names never collide with the ``controller_``-prefixed families, so
        one flat exposition stays valid.

        Fleet hygiene (ISSUE 7 satellite): when ≥ 2 agents have pushed
        snapshots, gauge families and the device busy/idle counters
        ADDITIONALLY render once per agent with an ``agent`` label next to
        the unlabeled fleet merge — without it the merged view collapses
        per-agent load into one number and a starving fleet member is
        invisible. Single-agent expositions keep the legacy (unlabeled)
        shape byte-for-byte; scrape consumers that sum fleet series must
        skip ``agent``-labeled samples (``obs.scrape.op_phase_seconds``
        already does)."""
        liveness = {
            "agent_last_seen_seconds": {
                "type": "gauge",
                "help": "Seconds since each agent's last lease poll",
                "labels": ["agent"],
                "series": [
                    {"labels": {"agent": a}, "value": s["last_seen_sec_ago"]}
                    for a, s in self.agents_summary().items()
                ],
            }
        }
        with self._lock:
            agent_snaps = [
                (a, e.get("obs")) for a, e in self.agent_metrics.items()
                if isinstance(e.get("obs"), dict)
            ]
        parts = [
            (self.metrics.snapshot(), {}),
            (merge_snapshots([s for _, s in agent_snaps]), {}),
        ]
        if len(agent_snaps) >= 2:
            for a, snap in agent_snaps:
                parts.append((self._per_agent_view(snap), {"agent": a}))
        parts.append((liveness, {}))
        return render_snapshots(parts)

    # Linked traces inlined per GET /v1/trace/{id} — enough for a serving
    # batch's full rider list (SERVE_MAX_BATCH is 16 by default).
    MAX_LINKED_TRACES = 32

    def trace_json(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Assembled span tree for one trace (``GET /v1/trace/{id}`` —
        ``trace_id`` is a job id or, since ISSUE 17, a serving ``req_id``):
        spans sorted by wall start, orphans flagged, completeness = one root
        + no orphans + every span closed. Traces whose spans carry cross-
        trace ``links`` (a request ↔ its coalesced batch job) get the linked
        traces assembled inline under ``linked_traces`` — the stitched view
        spanning the disagg prefill → decode handoff. None for unknown
        traces."""
        assembled = self.traces.assemble(trace_id)
        if assembled is None:
            return None
        linked: Dict[str, Dict[str, Any]] = {}
        for span in assembled["spans"]:
            for link in span.get("links") or ():
                tid = link.get("trace_id")
                if (
                    isinstance(tid, str) and tid and tid != trace_id
                    and tid not in linked
                    and len(linked) < self.MAX_LINKED_TRACES
                ):
                    sub = self.traces.assemble(tid)
                    if sub is not None:
                        linked[tid] = sub
        if linked:
            assembled["linked_traces"] = list(linked.values())
        return assembled

    def traces_json(self, limit: int = 20) -> List[Dict[str, Any]]:
        """Newest-first trace summaries (``GET /v1/traces?limit=N``)."""
        return self.traces.summaries(limit)

    # ---- resource accounting & profiling surface (ISSUE 9) ----

    def usage_json(self, top_k: Optional[int] = None) -> Dict[str, Any]:
        """The ``GET /v1/usage`` body: billed totals per tenant/tier/op,
        top-K jobs by device seconds, and the LIVE per-tenant queue depth so
        consumed and still-pending demand read off one report."""
        if self.usage is None:
            return {"enabled": False}
        with self._lock:
            pending = self._sched.depth_by_tenant()
        return self.usage.report(top_k=top_k, pending_by_tenant=pending)

    def timeseries_json(
        self,
        name: str,
        label_filter: Optional[Dict[str, str]] = None,
        rate: bool = False,
        window_sec: Optional[float] = None,
        since: Optional[float] = None,
        step: Optional[float] = None,
    ) -> Dict[str, Any]:
        """The ``GET /v1/timeseries`` body. Unknown names and an empty ring
        return an empty ``series`` list, never an error. ``since``/``step``
        (ISSUE 20) switch to the historical view: the durable store when
        one is open (it holds every ring sample and survives restarts),
        the ring's bounded window otherwise — seamless either way."""
        if self.tsdb is None:
            return {"enabled": False, "name": name, "series": []}
        if since is not None or step is not None:
            out = query_history(
                name, label_filter=label_filter, rate=rate,
                since=since, step=step,
                ring=self.tsdb, store=self.tsdb_store,
            )
        else:
            out = self.tsdb.query(
                name, label_filter, rate=rate, window_sec=window_sec
            )
        out["enabled"] = True
        return out

    def timeseries_names(self) -> List[str]:
        return self.tsdb.names() if self.tsdb is not None else []

    def request_capture(
        self,
        agent: str,
        op: Optional[str] = None,
        duration_ms: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Arm one on-demand ``jax.profiler`` deep capture (``POST
        /v1/profile/capture``); the request rides the target agent's next
        granted lease via the ``alerts`` channel."""
        return self.captures.request(agent, op=op, duration_ms=duration_ms)

    def captures_json(self) -> Dict[str, Any]:
        return {"captures": self.captures.snapshot()}

    def host_profile_text(self) -> Optional[str]:
        """Collapsed-stack flamegraph text of THIS process (``GET
        /v1/profile/host``), or None when disabled. The sampler thread
        starts lazily on the first request; the first response still
        carries ≥1 real sample (one synchronous walk if the thread hasn't
        beaten yet)."""
        if not self.obs_config.profile_host_enabled:
            return None
        with self._host_profiler_lock:
            if self.host_profiler is None:
                self.host_profiler = HostProfiler(
                    hz=self.obs_config.profile_host_hz
                ).start()
            prof = self.host_profiler
        if prof.n_samples == 0:
            prof.sample_once()
        return prof.collapsed()

    def host_profile_stats(self) -> Optional[Dict[str, Any]]:
        if self.host_profiler is None:
            return None
        return self.host_profiler.stats()

    def status_summary(self) -> Dict[str, Any]:
        """Structured rollup for /v1/status: per-op task counts + throughput
        since controller start, and p50/p95/p99 per task phase estimated
        from the fleet-merged ``task_phase_seconds`` histogram buckets."""
        uptime = max(1e-9, time.time() - self._started_wall)
        snap = self.metrics.snapshot()
        per_op: Dict[str, Dict[str, Any]] = {}
        for s in snap.get("controller_results_total", {}).get("series", []):
            labels = s.get("labels", {})
            op, outcome = labels.get("op"), labels.get("outcome")
            if op is None or outcome not in ("succeeded", "failed"):
                continue
            entry = per_op.setdefault(op, {"succeeded": 0, "failed": 0})
            entry[outcome] = int(s.get("value", 0))
        for op, entry in per_op.items():
            entry["tasks_per_sec"] = round(entry["succeeded"] / uptime, 3)
        phases: Dict[str, Dict[str, Any]] = {}
        fleet = self.fleet_snapshot().get("task_phase_seconds")
        if fleet:
            buckets = fleet.get("buckets", [])
            for s in fleet.get("series", []):
                labels = s.get("labels", {})
                op, phase = labels.get("op"), labels.get("phase")
                if op is None or phase is None or not s.get("count"):
                    continue
                qs = {
                    f"p{int(q * 100)}": histogram_quantile(
                        buckets, s.get("counts", []), q
                    )
                    for q in (0.5, 0.95, 0.99)
                }
                phases.setdefault(op, {})[phase] = {
                    "count": s["count"],
                    "sum_seconds": round(float(s.get("sum", 0.0)), 6),
                    **{
                        k: (round(v, 6) if v is not None else None)
                        for k, v in qs.items()
                    },
                }
        return {
            "uptime_sec": round(uptime, 3),
            "ops": per_op,
            "task_phase_seconds": phases,
        }
