"""Segmented journal with snapshot/compaction — controller crash survival
at O(live state) replay cost (ISSUE 14 tentpole a).

The append-only JSONL journal made controller death survivable (ISSUE 3),
but replay cost grew without bound: a month-old controller replays every
submit/result/requeue it ever journaled before serving its first lease.
This module bounds that:

- **Segments** — the journal rotates into bounded files
  ``<path>.seg-<NNNNNNNN>`` once ``segment_max_bytes`` (or
  ``segment_max_events``) is exceeded. The active segment is always the
  highest sequence number; a hot standby tails segments in order by
  ``(seq, byte offset)``.
- **Snapshots** — ``<path>.snapshot`` is a one-JSON-document image of live
  controller state (jobs, epochs, attempts, depended-on result bodies,
  usage ledger) taken at a segment boundary: the journal rotates first, the
  state is captured under the controller lock, and the snapshot covers
  every segment up to and including the just-closed one
  (``through_seq``). Replay = snapshot + segments with ``seq >
  through_seq`` — O(live state + tail), not O(history).
- **Atomicity** — snapshots write ``<path>.snapshot.tmp``, fsync, then
  ``os.replace`` (atomic on POSIX): at every instant ``<path>.snapshot``
  is either absent or a complete previous/new image. A snapshot that fails
  validation anyway (externally truncated, version skew) is *ignored* in
  favor of full-segment replay and counted (``snapshot_invalid``).
- **Garbage collection** — segments covered by the current snapshot are
  deleted after the rename lands; the disk footprint is bounded by one
  snapshot + the uncovered tail.
- **Durability knob** (ISSUE 14 satellite) — ``JOURNAL_FSYNC=1`` fdatasyncs
  appends; ``JOURNAL_FSYNC_EVERY=N`` batches the sync to every N appends
  (group commit) plus rotation/close boundaries. Default off: the journal
  protects against process death (flushed OS buffers survive SIGKILL),
  not kernel crashes, and a 10M-row drain posts thousands of results.

**Legacy mode**: with every segmentation/snapshot knob at 0 (the default),
the journal is the exact historical single file at ``<path>`` —
byte-identical appends, identical replay semantics — so existing journals,
tests, and operators see no change until they opt in. A legacy file that
predates a switch to segmented mode is replayed first (before segment 1)
until a snapshot covers it.

Torn-line semantics across the segment chain (matching the single-file
contract): an unparseable FINAL line of the FINAL segment is the expected
crash artifact — tolerated, counted ``torn_tail``. An unparseable line
anywhere else in the logical stream (mid-segment, or the last line of a
non-final segment) is real corruption — skipped, counted ``skipped``.
Promotion (``controller/standby.py``) *seals* a dead primary's torn tail
by truncating the active segment to the last complete line before the new
incarnation appends, so the healed journal replays clean.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from agent_tpu.utils.logging import log

SNAPSHOT_VERSION = 1
SEGMENT_PREFIX = ".seg-"
SNAPSHOT_SUFFIX = ".snapshot"


def segment_path(base: str, seq: int) -> str:
    return f"{base}{SEGMENT_PREFIX}{seq:08d}"


def parse_segment_seq(base: str, path: str) -> Optional[int]:
    name = os.path.basename(path)
    prefix = os.path.basename(base) + SEGMENT_PREFIX
    if not name.startswith(prefix):
        return None
    try:
        return int(name[len(prefix):])
    except ValueError:
        return None


def list_segments(base: str) -> List[Tuple[int, str]]:
    """``[(seq, path)]`` sorted ascending — the replay/tail order."""
    parent = os.path.dirname(base) or "."
    if not os.path.isdir(parent):
        return []
    out: List[Tuple[int, str]] = []
    for name in os.listdir(parent):
        path = os.path.join(parent, name)
        seq = parse_segment_seq(base, path)
        if seq is not None and os.path.isfile(path):
            out.append((seq, path))
    return sorted(out)


def load_snapshot(base: str) -> Optional[Dict[str, Any]]:
    """The current snapshot document, or None when absent or invalid (a
    half-written/corrupt snapshot must never win over replayable
    segments). Validation: parses as JSON, carries the version and a
    ``through_seq``/``jobs`` payload."""
    path = base + SNAPSHOT_SUFFIX
    if not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    if doc.get("version") != SNAPSHOT_VERSION:
        return None
    if not isinstance(doc.get("through_seq"), int):
        return None
    if not isinstance(doc.get("jobs"), list):
        return None
    return doc


class ReplayStats:
    """What one replay pass saw — the counters the controller mirrors."""

    def __init__(self) -> None:
        self.events = 0
        self.torn_tail = 0
        self.skipped = 0
        self.skipped_lines: List[str] = []   # "<file>:<lineno>" samples
        self.snapshot_used = False
        self.snapshot_invalid = 0
        self.segments_read = 0
        self.duration_sec = 0.0


def _iter_file_events(
    path: str, stats: ReplayStats, final_file: bool
) -> Iterator[Dict[str, Any]]:
    """Parse one journal file's lines. The torn-FINAL-line tolerance only
    applies when this file is the last of the logical stream."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return
    for i, raw in enumerate(lines):
        line = raw.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            if final_file and i == len(lines) - 1:
                stats.torn_tail += 1
                log(
                    "journal replay tolerated a torn final line",
                    path=path, line=i + 1,
                )
            else:
                stats.skipped += 1
                if len(stats.skipped_lines) < 20:
                    stats.skipped_lines.append(f"{path}:{i + 1}")
            continue
        if isinstance(ev, dict):
            stats.events += 1
            yield ev


class SegmentedJournal:
    """Owns the journal files for one controller incarnation.

    Appends are serialized by the caller (the controller journals under
    its state lock, ordered with the mutations the events record);
    ``commit_snapshot`` runs outside that lock and is internally
    serialized. Thread-safe members only where the snapshot path needs
    them.
    """

    def __init__(
        self,
        path: str,
        segment_max_bytes: int = 0,
        segment_max_events: int = 0,
        snapshot_every_events: int = 0,
        fsync: bool = False,
        fsync_every: int = 1,
    ) -> None:
        self.path = path
        self.segment_max_bytes = max(0, int(segment_max_bytes))
        self.segment_max_events = max(0, int(segment_max_events))
        self.snapshot_every_events = max(0, int(snapshot_every_events))
        self.fsync = bool(fsync)
        self.fsync_every = max(1, int(fsync_every))
        # Segmented the moment any bound is set; a snapshot cadence alone
        # forces segmentation too (compaction GC works on whole segments).
        self.segmented = bool(
            self.segment_max_bytes
            or self.segment_max_events
            or self.snapshot_every_events
        )
        if self.segmented and not (
            self.segment_max_bytes or self.segment_max_events
        ):
            self.segment_max_bytes = 4 * 1024 * 1024
        self._file = None
        self._active_seq = 0
        self._active_bytes = 0
        self._active_events = 0
        self._events_since_snapshot = 0
        self._unsynced = 0
        self._snapshot_lock = threading.Lock()
        self.appended_events = 0
        self.fsyncs = 0
        self.snapshots_written = 0
        self.last_snapshot_wall: Optional[float] = None
        self.last_replay: Optional[ReplayStats] = None

    # ---- replay (before open_for_append) ----

    def replay(self) -> Tuple[Optional[Dict[str, Any]], Iterator[Dict[str, Any]], ReplayStats]:
        """``(snapshot_doc, event_iterator, stats)``. The iterator yields
        the logical event stream NOT covered by the snapshot, torn/skip
        rules applied; ``stats`` is also kept as ``last_replay`` (fields
        keep filling while the iterator is consumed)."""
        stats = ReplayStats()
        self.last_replay = stats
        snap = load_snapshot(self.path)
        if snap is None and os.path.exists(self.path + SNAPSHOT_SUFFIX):
            # Present but unreadable/invalid: fall back to full-segment
            # replay, loudly — a half image must never beat whole segments.
            stats.snapshot_invalid += 1
            log(
                "snapshot invalid — ignored in favor of full segment replay",
                path=self.path + SNAPSHOT_SUFFIX,
            )
        stats.snapshot_used = snap is not None
        through = snap["through_seq"] if snap else -1

        def events() -> Iterator[Dict[str, Any]]:
            files: List[str] = []
            # The legacy single file predates every segment; a snapshot
            # (always taken at seq >= 1) covers it.
            if through < 0 and os.path.exists(self.path) \
                    and os.path.getsize(self.path) > 0:
                files.append(self.path)
            for seq, seg in list_segments(self.path):
                if seq > through:
                    files.append(seg)
            stats.segments_read = len(files)
            for i, fp in enumerate(files):
                yield from _iter_file_events(
                    fp, stats, final_file=(i == len(files) - 1)
                )

        return snap, events(), stats

    # ---- append ----

    @staticmethod
    def _seal_torn_tail_at_open(path: str) -> int:
        """Truncate a half-written final line before the first append.

        Replay *tolerates* a dead incarnation's torn death write, but
        appending after it would glue the next event onto the fragment —
        turning a benign torn tail into mid-stream corruption (and losing
        that next event) on every later replay. Promotion already seals via
        ``StandbyTailer.seal()``; a plain restart over the same journal
        (the partition-kill recovery path) must seal too. Returns the
        bytes cut (0 = file was clean)."""
        try:
            size = os.path.getsize(path)
        except OSError:
            return 0
        if size == 0:
            return 0
        with open(path, "rb+") as f:
            f.seek(-1, os.SEEK_END)
            if f.read(1) == b"\n":
                return 0
            f.seek(0)
            data = f.read()
            keep = data.rfind(b"\n") + 1
            f.truncate(keep)
            f.flush()
            os.fsync(f.fileno())
        cut = size - keep
        log("sealed torn journal tail at restart", path=path, bytes=cut)
        return cut

    def open_for_append(self) -> None:
        if self._file is not None:
            return
        if not self.segmented:
            self._seal_torn_tail_at_open(self.path)
            self._file = open(self.path, "a", encoding="utf-8")
            return
        segments = list_segments(self.path)
        self._active_seq = segments[-1][0] if segments else 1
        active = segment_path(self.path, self._active_seq)
        self._seal_torn_tail_at_open(active)
        self._file = open(active, "a", encoding="utf-8")
        self._active_bytes = self._file.tell()
        self._active_events = 0  # event budget counts THIS incarnation's

    def append(self, event: Dict[str, Any]) -> None:
        """One journal event. Caller holds the controller lock — appends
        are ordered with the state changes they record."""
        if self._file is None:
            return
        data = json.dumps(event) + "\n"
        self._file.write(data)
        self._file.flush()
        self.appended_events += 1
        self._events_since_snapshot += 1
        if self.fsync:
            self._unsynced += 1
            if self._unsynced >= self.fsync_every:
                self._fdatasync()
        if self.segmented:
            self._active_bytes += len(data.encode("utf-8"))
            self._active_events += 1
            if self._over_budget():
                self._rotate_locked()

    def _over_budget(self) -> bool:
        return (
            (self.segment_max_bytes
             and self._active_bytes >= self.segment_max_bytes)
            or (self.segment_max_events
                and self._active_events >= self.segment_max_events)
        )

    def _fdatasync(self) -> None:
        try:
            fd = self._file.fileno()
            if hasattr(os, "fdatasync"):
                os.fdatasync(fd)
            else:  # pragma: no cover — platforms without fdatasync
                os.fsync(fd)
            self.fsyncs += 1
        except (OSError, ValueError):
            pass  # durability is best-effort; the drain must not die on it
        self._unsynced = 0

    def _rotate_locked(self) -> int:
        """Close the active segment, open the next. Returns the seq of the
        segment just closed. Caller holds the controller lock (append
        ordering)."""
        closed = self._active_seq
        if self.fsync and self._unsynced:
            self._fdatasync()
        self._file.close()
        self._active_seq += 1
        self._file = open(
            segment_path(self.path, self._active_seq), "a", encoding="utf-8"
        )
        self._active_bytes = 0
        self._active_events = 0
        return closed

    # ---- snapshot / compaction ----

    def snapshot_due(self) -> bool:
        return bool(
            self.snapshot_every_events
            and self._events_since_snapshot >= self.snapshot_every_events
        )

    def rotate_for_snapshot(self) -> int:
        """Seal the active segment so the snapshot about to be captured
        covers whole segments only. Caller holds the controller lock; the
        state captured right after this call is exactly the state at the
        returned segment boundary (events appended later land in the new
        segment, which replay applies on top of the snapshot)."""
        if not self.segmented or self._file is None:
            raise RuntimeError("snapshotting requires a segmented journal")
        through = self._rotate_locked()
        self._events_since_snapshot = 0
        return through

    def commit_snapshot(
        self, through_seq: int, state: Dict[str, Any]
    ) -> str:
        """Write the snapshot atomically (tmp, fsync, rename) and GC the
        segments it covers. Runs OUTSIDE the controller lock — pure file
        I/O over an already-captured state dict."""
        with self._snapshot_lock:
            doc = {
                "version": SNAPSHOT_VERSION,
                "through_seq": int(through_seq),
                "taken_wall": time.time(),
                **state,
            }
            path = self.path + SNAPSHOT_SUFFIX
            tmp = f"{path}.tmp.{os.getpid()}"
            data = json.dumps(doc)
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except OSError:
                # A failed snapshot must not take down the control plane:
                # the previous snapshot (or full segments) still replay.
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            self.snapshots_written += 1
            self.last_snapshot_wall = doc["taken_wall"]
            self._gc_covered(through_seq)
            # The pre-segmentation legacy file is folded into the snapshot
            # too — compact it away like any covered segment.
            if os.path.exists(self.path):
                try:
                    os.unlink(self.path)
                except OSError:
                    pass
            return path

    def _gc_covered(self, through_seq: int) -> None:
        for seq, seg in list_segments(self.path):
            if seq <= through_seq:
                try:
                    os.unlink(seg)
                except OSError:
                    pass

    # ---- introspection ----

    def stats(self) -> Dict[str, Any]:
        """The ``/v1/status`` ``journal`` durability block's file-side
        half: segment count, total bytes, snapshot age."""
        segments = list_segments(self.path) if self.segmented else []
        total = sum(
            os.path.getsize(p) for _, p in segments if os.path.exists(p)
        )
        if not self.segmented and os.path.exists(self.path):
            total = os.path.getsize(self.path)
        snap_path = self.path + SNAPSHOT_SUFFIX
        snapshot_age: Optional[float] = None
        if self.last_snapshot_wall is not None:
            snapshot_age = max(0.0, time.time() - self.last_snapshot_wall)
        elif os.path.exists(snap_path):
            try:
                snapshot_age = max(
                    0.0, time.time() - os.path.getmtime(snap_path)
                )
            except OSError:
                pass
        return {
            "segmented": self.segmented,
            "segments": len(segments) if self.segmented else 1,
            "bytes": int(total),
            "snapshot_bytes": (
                os.path.getsize(snap_path)
                if os.path.exists(snap_path) else 0
            ),
            "snapshots_written": self.snapshots_written,
            "last_snapshot_age_sec": (
                round(snapshot_age, 3) if snapshot_age is not None else None
            ),
            "fsync": self.fsync,
            "appended_events": self.appended_events,
        }

    def close(self) -> None:
        if self._file is not None:
            if self.fsync and self._unsynced:
                self._fdatasync()
            self._file.close()
            self._file = None


class JournalTailer:
    """Read-only incremental cursor over another incarnation's segments —
    the hot standby's feed (file-tail first; an HTTP tail endpoint can
    ride the same cursor later).

    Yields complete newline-terminated events only; a partial final line
    (the primary mid-append, or its torn death write) is left for the next
    poll — or for ``seal()``, which truncates it away at promotion time.
    Legacy single-file journals tail too (segment seq 0).
    """

    def __init__(self, path: str) -> None:
        self.path = path
        self._seq: Optional[int] = None     # None = not positioned yet
        self._offset = 0
        self._buf = b""
        self.events_read = 0
        self.torn_sealed = 0
        # Set when the segment under the cursor was garbage-collected (a
        # snapshot covered it before we finished reading): the consumer
        # must resync from the snapshot — silently jumping ahead would
        # drop the unread events from its replica.
        self.need_resync = False

    def _current_files(self) -> List[Tuple[int, str]]:
        files = list_segments(self.path)
        if os.path.exists(self.path) and os.path.getsize(self.path) > 0:
            files.insert(0, (0, self.path))
        return files

    def _file_for_seq(self, seq: int) -> Optional[str]:
        if seq == 0:
            return self.path if os.path.exists(self.path) else None
        p = segment_path(self.path, seq)
        return p if os.path.exists(p) else None

    def poll(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """New complete events since the last poll, oldest first. Skips
        unparseable complete lines (counted by the consumer via its own
        apply path if it cares); advances across segment boundaries when
        the current segment is exhausted and a higher one exists."""
        out: List[Dict[str, Any]] = []
        while limit is None or len(out) < limit:
            files = self._current_files()
            if not files:
                break
            if self._seq is None:
                self._seq, _ = files[0]
                self._offset = 0
                self._buf = b""
            path = self._file_for_seq(self._seq)
            if path is None:
                # Our segment was GC'd under us (a compacting snapshot
                # landed and collected it, possibly before we finished
                # reading). STOP and flag: the consumer reloads the
                # snapshot (which folds in everything we may have missed)
                # and repositions us via resync_to().
                self.need_resync = True
                break
            chunk = self._read_chunk(path)
            if chunk is None:
                # The file vanished between the existence check and the
                # read (GC racing us): resync, don't skip.
                self.need_resync = True
                break
            if chunk:
                self._buf += chunk
                *complete, rest = self._buf.split(b"\n")
                self._buf = rest
                for raw in complete:
                    line = raw.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(ev, dict):
                        out.append(ev)
                        self.events_read += 1
                        if limit is not None and len(out) >= limit:
                            return out
                continue
            # Current file exhausted: move to the next segment only when
            # one exists (the primary rotated past us) AND no partial line
            # is pending (a rotation never splits a line).
            newer = [s for s, _ in files if s > self._seq]
            if newer and not self._buf:
                self._seq = newer[0]
                self._offset = 0
                continue
            break
        return out

    def resync_to(self, through_seq: int) -> None:
        """Reposition past everything a just-loaded snapshot covers: the
        next poll resumes at the oldest surviving segment newer than
        ``through_seq`` (or re-reads ``through_seq`` itself if GC left it
        behind — re-application on top of the snapshot fold is
        convergent)."""
        self._seq = max(0, int(through_seq))
        self._offset = 0
        self._buf = b""
        self.need_resync = False
        if self._file_for_seq(self._seq) is None:
            newer = [s for s, _ in self._current_files()
                     if s > self._seq]
            if newer:
                self._seq = newer[0]

    def _read_chunk(
        self, path: str, size: int = 1 << 20
    ) -> Optional[bytes]:
        """Next chunk from ``path`` at the cursor. ``b""`` = genuine EOF;
        ``None`` = the file is gone/unreadable (GC won a race — the
        caller must resync rather than treat it as exhausted)."""
        try:
            with open(path, "rb") as f:
                f.seek(self._offset)
                chunk = f.read(size)
        except OSError:
            return None
        self._offset += len(chunk)
        return chunk

    def lag_bytes(self) -> int:
        """Bytes appended beyond this cursor — the standby staleness
        signal."""
        files = self._current_files()
        if not files:
            return 0
        if self._seq is None:
            return sum(os.path.getsize(p) for _, p in files)
        lag = 0
        for seq, p in files:
            try:
                size = os.path.getsize(p)
            except OSError:
                continue
            if seq == self._seq:
                lag += max(0, size - self._offset)
            elif seq > self._seq:
                lag += size
        return lag + len(self._buf)

    def seal(self) -> Tuple[List[Dict[str, Any]], int]:
        """Promotion-time repair: truncate the current segment at the last
        complete line, discarding a dead primary's torn final write (it
        never acked that event to anyone — the poster redelivers).

        Returns ``(late_events, bytes_cut)``: any COMPLETE events that
        landed after the caller's last ``poll`` are returned for
        application, only the genuinely newline-less tail is cut. Only
        call once the primary is known dead; a live writer's buffered
        append would fight the truncation."""
        if self._seq is None:
            return [], 0
        path = self._file_for_seq(self._seq)
        if path is None:
            return [], 0
        # Pull in anything written since the last poll so complete lines
        # in it are applied, not truncated.
        chunk = self._read_chunk(path)
        if chunk:
            self._buf += chunk
        elif chunk is None:
            return [], 0
        late: List[Dict[str, Any]] = []
        if b"\n" in self._buf:
            complete, _, rest = self._buf.rpartition(b"\n")
            self._buf = rest
            for raw in complete.split(b"\n"):
                line = raw.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if isinstance(ev, dict):
                    late.append(ev)
                    self.events_read += 1
        cut = len(self._buf)
        if cut <= 0:
            return late, 0
        keep = max(0, self._offset - cut)
        try:
            with open(path, "rb+") as f:
                f.truncate(keep)
        except OSError:
            return late, 0
        self._buf = b""
        self._offset = keep
        self.torn_sealed += 1
        log("sealed torn journal tail at promotion", path=path, bytes=cut)
        return late, cut
