"""The controller — the other half of the lease protocol.

The reference ships only the *client* side; the server at CONTROLLER_URL is
external (SURVEY.md §2.9 infers its contract from reference ``app.py:162-213``).
A self-contained framework needs both, so this package implements it:

- :class:`~agent_tpu.controller.core.Controller` — pure in-memory scheduler:
  job queue, capability matching, lease issuance + expiry, ``job_epoch``
  fencing, result collection, CSV shard splitting, and fault-injection hooks
  (drop a lease, duplicate a task, re-queue with a bumped epoch) for the
  failure tests SURVEY.md §5.3 calls for.
- :class:`~agent_tpu.controller.server.ControllerServer` — a stdlib
  ``ThreadingHTTPServer`` speaking ``POST /v1/leases`` / ``POST /v1/results``
  with 204-on-idle, matching the wire contract byte for byte. Doubles as the
  integration-test fake (SURVEY.md §4.2) and as a real single-process
  controller for small swarms.
"""

from agent_tpu.controller.core import Controller, Job
from agent_tpu.controller.server import ControllerServer

__all__ = ["Controller", "ControllerServer", "Job"]
