"""Hot-standby controller failover (ISSUE 14 tentpole b).

A second ``Controller`` incarnation that tails the primary's journal
segments (file-tail — the two incarnations share the journal volume; an
HTTP tail endpoint can ride the same cursor later) and maintains a WARM
in-memory replica of job state: every submit/result/requeue the primary
journals is applied to the replica within one poll interval, so promotion
pays only the uncovered tail, not a cold replay.

Promotion sequence (``promote()``):

1. stop the tail thread;
2. final catch-up poll — every complete event the dead primary managed to
   flush is applied;
3. **seal** the torn tail: the primary's mid-append death leaves a
   newline-less final line; it is truncated away (counted). That event
   was never acked to anyone — the submitter/agent that posted it saw a
   transport error and will redeliver — so sealing loses nothing and the
   healed journal replays clean forever after;
4. finalize: non-terminal jobs requeue at their CURRENT epoch (the same
   epoch-fencing contract a plain restart has — results agents spooled
   against the old incarnation are applied once; anything the old
   incarnation already fenced or completed is cleanly rejected by the
   journaled fences / terminal-state guard);
5. the journal reopens for append on a FRESH segment, so a zombie
   primary's still-open file handle can never interleave writes with the
   new incarnation's (its stray appends would land in an orphaned,
   already-sealed position).

Agents reach the promoted incarnation via ``CONTROLLER_URLS`` — the
agent-side failover list: a transport error rotates the active URL, and
the existing spool/retry classifier redelivers completed results to the
standby instead of dropping them.

``python -m agent_tpu.controller.standby`` runs a standalone standby:
it tails ``CONTROLLER_JOURNAL``, optionally watches the primary's
``/v1/status`` (``PRIMARY_URL``), and promotes — then serves HTTP — when
the primary misses ``PRIMARY_DOWN_AFTER`` consecutive health polls or on
SIGUSR1 (operator-forced failover).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from agent_tpu.config import JournalConfig
from agent_tpu.controller.core import Controller
from agent_tpu.controller.journal import (
    JournalTailer,
    SegmentedJournal,
    load_snapshot,
)
from agent_tpu.utils.logging import log


class HotStandby:
    """Warm replica of a primary controller, fed by journal tailing.

    ``controller_kwargs`` are forwarded to the replica ``Controller``
    (journal_path/sweep_interval excluded — the replica neither appends
    nor sweeps until promoted). The replica object IS the controller that
    serves after ``promote()``; point a ``ControllerServer`` at
    ``standby.controller`` once promotion returns.
    """

    def __init__(
        self,
        journal_path: str,
        journal: Optional[JournalConfig] = None,
        poll_interval_sec: float = 0.05,
        sweep_interval_sec: Optional[float] = None,
        **controller_kwargs: Any,
    ) -> None:
        self.journal_path = journal_path
        self.journal_config = journal if journal is not None \
            else JournalConfig()
        self.poll_interval_sec = max(0.005, float(poll_interval_sec))
        self.sweep_interval_sec = sweep_interval_sec
        # The replica must never append to the primary's tsdb segment
        # streams while the primary lives — the store opens at promotion
        # (finalize_promotion), sealing whatever torn tail the dead
        # primary left (ISSUE 20).
        controller_kwargs.setdefault("tsdb_defer_open", True)
        self.controller = Controller(
            journal_path=None, journal=self.journal_config,
            **controller_kwargs,
        )
        self._tailer = JournalTailer(journal_path)
        self._bootstrapped = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.promoted = False
        self.events_applied = 0
        self.torn_sealed_bytes = 0
        # Snapshot resyncs: how often compaction outran the tail and the
        # replica reloaded from the snapshot instead (lossless either way).
        self.resyncs = 0

    # ---- replica feed ----

    def _bootstrap(self) -> None:
        """Initial catch-up: snapshot (if one exists) + everything the
        tailer can read right now. Runs once, before the tail loop."""
        snap = load_snapshot(self.journal_path)
        if snap is not None:
            self.controller.apply_snapshot_doc(snap)
            # Position the cursor past the covered segments: the tailer
            # skips files the snapshot already folded in.
            through = snap.get("through_seq", -1)
            self._tailer._seq = max(0, int(through))  # noqa: SLF001
            self._tailer._offset = 0                  # noqa: SLF001
            # through_seq itself was GC'd (or is about to be); poll() jumps
            # to the oldest surviving newer segment on its own.
        self._bootstrapped = True

    def catch_up(self, limit: Optional[int] = None) -> int:
        """Apply newly-journaled events to the replica. Returns how many
        were applied. Safe to call concurrently with the tail thread.

        When the primary's compaction GC'd a segment before this tail
        finished reading it, the tailer flags a RESYNC: the replica
        reloads the (newer) snapshot — which folds in everything the
        collected segments held — and resumes past it. Bounded retries:
        snapshots advance monotonically, so a second GC mid-resync can
        only move the cursor forward."""
        with self._lock:
            return self._catch_up_locked(limit)

    def _catch_up_locked(self, limit: Optional[int] = None) -> int:
        if not self._bootstrapped:
            self._bootstrap()
        n = 0
        for _ in range(8):
            for ev in self._tailer.poll(limit=limit):
                n += self.controller.apply_journal_event(ev)
            if not self._tailer.need_resync:
                break
            snap = load_snapshot(self.journal_path)
            if snap is not None:
                # mirror=False: this replica's usage mirrors already
                # counted the events it applied live.
                self.controller.apply_snapshot_doc(snap, mirror=False)
                self._tailer.resync_to(snap.get("through_seq", 0))
            else:
                # GC without a snapshot cannot happen on a healthy
                # volume; resume at the oldest surviving segment.
                self._tailer.resync_to(0)
            self.resyncs += 1
        self.events_applied += n
        return n

    def _tail_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_sec):
            try:
                self.catch_up()
            except Exception as exc:  # noqa: BLE001 — a tail hiccup must
                # not kill the standby; the next poll retries from the
                # same cursor.
                log(
                    "standby tail error (will retry)",
                    error=f"{type(exc).__name__}: {exc}"[:200],
                )

    def start(self) -> "HotStandby":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._tail_loop, name="standby-tail", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # ---- introspection ----

    def lag_bytes(self) -> int:
        with self._lock:
            return self._tailer.lag_bytes()

    def replica_counts(self) -> Dict[str, int]:
        return self.controller.counts()

    # ---- promotion ----

    def promote(self) -> Controller:
        """Take over as the live controller. The primary MUST be dead (or
        fenced off the journal volume) before this is called — see module
        docstring for the sequence and the zero-loss argument."""
        self.stop()
        with self._lock:
            if self.promoted:
                return self.controller
            # Final catch-up (resync-aware), then seal the torn tail.
            # seal() returns any complete events that landed between the
            # last poll and now.
            self._catch_up_locked()
            late, cut = self._tailer.seal()
            for ev in late:
                self.events_applied += (
                    self.controller.apply_journal_event(ev)
                )
            self.torn_sealed_bytes = cut
            if cut:
                # Operator-visible like any replay-time torn tail.
                self.controller.journal_torn_tail += 1
                self.controller._m_journal_torn.inc()  # noqa: SLF001
            impl = SegmentedJournal(
                self.journal_path,
                segment_max_bytes=self.journal_config.segment_max_bytes,
                segment_max_events=self.journal_config.segment_max_events,
                snapshot_every_events=(
                    self.journal_config.snapshot_every_events
                ),
                fsync=self.journal_config.fsync,
                fsync_every=self.journal_config.fsync_every,
            )
            impl.open_for_append()
            if impl.segmented:
                # Fresh-segment fencing: never append to a file the dead
                # primary may still hold open.
                impl._rotate_locked()  # noqa: SLF001
            self.controller.finalize_promotion(
                impl, sweep_interval_sec=self.sweep_interval_sec
            )
            self.promoted = True
        return self.controller


def main() -> int:
    """Standalone hot standby. Env: CONTROLLER_JOURNAL (required — the
    primary's journal path on a shared volume), CONTROLLER_HOST/PORT (where
    to serve AFTER promotion), PRIMARY_URL (optional — poll its /v1/status;
    PRIMARY_DOWN_AFTER consecutive failures trigger promotion),
    STANDBY_POLL_SEC (tail cadence), plus the same SCHED_*/SLO_*/JOURNAL_*
    knobs the primary runs with (the replica must judge state the same
    way). SIGUSR1 forces promotion."""
    import signal
    import urllib.request

    from agent_tpu.config import (
        ObsConfig,
        SchedConfig,
        SloConfig,
        env_float,
        env_int,
        env_str,
    )
    from agent_tpu.controller.server import ControllerServer

    journal = env_str("CONTROLLER_JOURNAL", "")
    if not journal:
        print("[agent-tpu-standby] CONTROLLER_JOURNAL is required", flush=True)
        return 2
    primary_url = env_str("PRIMARY_URL", "").rstrip("/")
    down_after = max(1, env_int("PRIMARY_DOWN_AFTER", 3))
    poll = env_float("STANDBY_POLL_SEC", 0.25)
    standby = HotStandby(
        journal,
        journal=JournalConfig.from_env(),
        poll_interval_sec=poll,
        sweep_interval_sec=env_float("CONTROLLER_SWEEP_SEC", 5.0) or None,
        lease_ttl_sec=env_float("LEASE_TTL_SEC", 30.0),
        max_attempts=max(1, env_int("MAX_ATTEMPTS", 2)),
        requeue_delay_sec=env_float("REQUEUE_DELAY_SEC", 1.0),
        sched=SchedConfig.from_env(),
        slo=SloConfig.from_env(),
        obs=ObsConfig.from_env(),
    ).start()

    promote_now = threading.Event()
    signal.signal(signal.SIGUSR1, lambda *_: promote_now.set())
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    print(
        f"[agent-tpu-standby] tailing {journal}"
        + (f", watching {primary_url}" if primary_url else "")
        + " (SIGUSR1 promotes)",
        flush=True,
    )
    misses = 0
    while not stop.is_set() and not promote_now.is_set():
        if primary_url:
            try:
                with urllib.request.urlopen(
                    primary_url + "/v1/status", timeout=2
                ) as resp:
                    resp.read()
                misses = 0
            except Exception:  # noqa: BLE001 — any failure counts a miss
                misses += 1
                if misses >= down_after:
                    print(
                        f"[agent-tpu-standby] primary missed {misses} "
                        "health polls — promoting",
                        flush=True,
                    )
                    promote_now.set()
        stop.wait(1.0)
    if stop.is_set():
        standby.stop()
        standby.controller.close()
        print("[agent-tpu-standby] stopped (never promoted)", flush=True)
        return 0
    controller = standby.promote()
    server = ControllerServer(
        controller,
        host=env_str("CONTROLLER_HOST", "0.0.0.0"),
        port=env_int("CONTROLLER_PORT", 8080),
    )
    server.start()
    print(f"[agent-tpu-standby] promoted — serving on {server.url}", flush=True)
    stop.wait()
    server.stop()
    controller.close()
    print("[agent-tpu-standby] stopped", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
